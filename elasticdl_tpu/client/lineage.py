"""`elasticdl lineage`: event log -> per-window freshness waterfalls.

The train-path twin of `elasticdl trace`'s request summary: it joins the
`window_span` stamps in an event log (common/lineage.py does the same
join the live master does) and renders where each stream window's
ingest-to-first-serve time went — the decomposition an operator reads
BEFORE opening the Chrome trace:

  * a phase table (p50/p99/total per lineage phase, share of all
    traced window time);
  * the slowest-K windows with their dominant phase named;
  * an ASCII waterfall per slowest window (and `--window` for any
    specific one), one bar per phase, dropped/replayed flags inline.

Open (incomplete) windows are charged up to the newest stamp in the
log, attributed to the phase they are blocked in — a mid-incident log
still names the guilty phase.  stdlib-only, like `elasticdl top`.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from elasticdl_tpu.common import events
from elasticdl_tpu.common import lineage as lineage_lib

_BAR_WIDTH = 32


def _quantile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def _flags(decomp: dict) -> str:
    flags = [
        f for f in ("dropped", "replayed", "rearmed") if decomp[f]
    ]
    return f" [{'+'.join(flags)}]" if flags else ""


def _dominant(decomp: dict) -> Optional[str]:
    phases = decomp.get("phases") or {}
    if not phases:
        return None
    return max(phases, key=phases.get)


def _decompositions(evts: List[dict]) -> List[dict]:
    """Every window's decomposition, window-id order.  Open windows are
    charged against the newest lineage stamp in the log."""
    states = lineage_lib.from_events(evts)
    stamps = [
        float(e["at_unix_s"]) for e in evts
        if e.get("event") == events.WINDOW_SPAN
        and e.get("at_unix_s") is not None
    ]
    now = max(stamps) if stamps else None
    return [
        lineage_lib.decompose(states[wid], now=now)
        for wid in sorted(states)
    ]


def waterfall(decomp: dict) -> List[str]:
    """One window's phases as proportional ASCII bars."""
    phases = [
        (p, decomp["phases"][p])
        for p in lineage_lib.PHASE_ORDER if p in decomp["phases"]
    ]
    total = sum(seconds for _, seconds in phases)
    header = (
        f"window {decomp['window_id']}{_flags(decomp)}: "
        f"{decomp['e2e_s']:.3f}s"
        + ("" if decomp["complete"] else
           f" (open, blocked in {decomp['blocked_phase'] or '?'})")
    )
    lines = [header]
    for phase, seconds in phases:
        share = seconds / total if total > 0 else 0.0
        bar = "#" * max(1 if seconds > 0 else 0,
                        int(round(share * _BAR_WIDTH)))
        lines.append(
            f"  {phase:<12}{seconds:9.3f}s {share * 100:5.1f}%  {bar}"
        )
    return lines


def render(evts: List[dict], slowest_k: int = 3,
           window_id: Optional[int] = None) -> str:
    """The full `elasticdl lineage` report text."""
    decomps = _decompositions(evts)
    if not decomps:
        return "no window_span events found"
    if window_id is not None:
        match = [d for d in decomps if d["window_id"] == int(window_id)]
        if not match:
            return f"window {window_id} has no lineage stamps"
        return "\n".join(waterfall(match[0]))

    complete = [d for d in decomps if d["complete"]]
    open_ = [d for d in decomps if not d["complete"]]
    dropped = [d for d in decomps if d["dropped"]]
    replayed = [d for d in decomps if d["replayed"]]
    lines = [
        f"windows traced: {len(decomps)} ({len(complete)} complete, "
        f"{len(open_)} open, {len(dropped)} dropped, "
        f"{len(replayed)} replayed)"
    ]
    e2e = sorted(d["e2e_s"] for d in complete)
    if e2e:
        lines.append(
            f"ingest->first-serve: p50={_quantile(e2e, 0.5):.3f}s "
            f"p99={_quantile(e2e, 0.99):.3f}s"
        )
    dominant = lineage_lib.dominant_phase(decomps)
    if dominant:
        lines.append(f"dominant phase: {dominant}")

    by_phase: Dict[str, List[float]] = {}
    for d in decomps:
        for phase, seconds in d["phases"].items():
            by_phase.setdefault(phase, []).append(float(seconds))
    grand_total = sum(sum(v) for v in by_phase.values()) or 1.0
    lines.append("")
    lines.append(
        "phase".ljust(12) + "n".rjust(6) + "p50_s".rjust(10)
        + "p99_s".rjust(10) + "total_s".rjust(10) + "share".rjust(8)
    )
    for phase in lineage_lib.PHASE_ORDER:
        if phase not in by_phase:
            continue
        vals = sorted(by_phase[phase])
        total = sum(vals)
        lines.append(
            phase.ljust(12)
            + str(len(vals)).rjust(6)
            + f"{_quantile(vals, 0.5):.3f}".rjust(10)
            + f"{_quantile(vals, 0.99):.3f}".rjust(10)
            + f"{total:.3f}".rjust(10)
            + f"{100.0 * total / grand_total:5.1f}%".rjust(8)
        )

    if slowest_k > 0:
        slowest = sorted(
            decomps, key=lambda d: -d["e2e_s"]
        )[:slowest_k]
        lines.append("")
        lines.append(f"slowest {len(slowest)} windows:")
        for d in slowest:
            dom = _dominant(d)
            lines.append(
                f"  window {d['window_id']}{_flags(d)}: "
                f"{d['e2e_s']:.3f}s"
                + (f", dominant phase {dom}" if dom else "")
            )
        for d in slowest:
            lines.append("")
            lines.extend(waterfall(d))
    return "\n".join(lines)


def lineage(args) -> int:
    """Entry point for `elasticdl lineage`."""
    evts = events.read_events(args.event_log)
    spans = [
        e for e in evts if e.get("event") == events.WINDOW_SPAN
    ]
    if not spans:
        print(
            f"elasticdl lineage: no window_span events in "
            f"{args.event_log!r}"
        )
        return 1
    window_id = getattr(args, "window", None)
    print(render(
        evts,
        slowest_k=getattr(args, "slowest", 3),
        window_id=window_id if window_id is not None else None,
    ))
    return 0
