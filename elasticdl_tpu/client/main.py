"""The `elasticdl` CLI.

Parity: reference elasticdl_client/main.py (SURVEY.md C18):

    elasticdl train    --model_zoo ... --model_def pkg.fn --training_data ...
    elasticdl evaluate --model_zoo ... --validation_data ...
    elasticdl predict  --model_zoo ... --prediction_data ...
    elasticdl zoo init|build|push

Flag surface mirrors the reference (SURVEY.md C21) so zoo jobs launch
unchanged; TPU-specific flags (--use_bf16, mesh axes) extend it.
"""

from __future__ import annotations

import argparse
import sys

from elasticdl_tpu.common import args as args_lib


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="elasticdl",
        description="elasticdl-tpu: elastic distributed training on TPU",
    )
    subparsers = parser.add_subparsers(dest="command")

    train_parser = subparsers.add_parser("train", help="submit a training job")
    args_lib.add_common_params(train_parser)
    args_lib.add_model_params(train_parser)
    args_lib.add_train_params(train_parser)
    train_parser.set_defaults(func="train")

    eval_parser = subparsers.add_parser("evaluate", help="run evaluation")
    args_lib.add_common_params(eval_parser)
    args_lib.add_model_params(eval_parser)
    args_lib.add_train_params(eval_parser)
    eval_parser.set_defaults(func="evaluate")

    predict_parser = subparsers.add_parser("predict", help="run prediction")
    args_lib.add_common_params(predict_parser)
    args_lib.add_model_params(predict_parser)
    args_lib.add_train_params(predict_parser)
    predict_parser.set_defaults(func="predict")

    serve_parser = subparsers.add_parser(
        "serve", help="serve an exported model or live checkpoint dir"
    )
    args_lib.add_model_params(serve_parser)
    args_lib.add_serve_params(serve_parser)
    serve_parser.set_defaults(func="serve")

    top_parser = subparsers.add_parser(
        "top", help="live cluster table from a master's /varz endpoint"
    )
    top_parser.add_argument(
        "master_varz",
        help="master telemetry address: host:port or http URL "
        "(--telemetry_port of the master)",
    )
    top_parser.add_argument(
        "--serving_addr", default="",
        help="optionally also scrape a serving replica's telemetry "
        "address for a serving summary row",
    )
    top_parser.add_argument(
        "--watch", action="store_true",
        help="refresh continuously instead of printing one frame",
    )
    top_parser.add_argument(
        "--interval_s", type=float, default=2.0,
        help="refresh interval with --watch",
    )
    top_parser.set_defaults(func="top")

    slo_parser = subparsers.add_parser(
        "slo", help="SLO report (state, burn rates, window evidence) "
        "from a master's /varz endpoint"
    )
    slo_parser.add_argument(
        "master_varz",
        help="master telemetry address: host:port or http URL "
        "(--telemetry_port of the master)",
    )
    slo_parser.add_argument(
        "--json", action="store_true",
        help="dump the raw SLO snapshot as JSON instead of the table",
    )
    slo_parser.set_defaults(func="slo")

    programs_parser = subparsers.add_parser(
        "programs",
        help="XLA program observatory (compiles, retraces, cost ledger, "
        "live MFU) from any role's /varz endpoint",
    )
    programs_parser.add_argument(
        "varz_addr",
        help="telemetry address of any role: host:port or http URL "
        "(--telemetry_port of a master, worker, or serving replica)",
    )
    programs_parser.add_argument(
        "--json", action="store_true",
        help="dump the raw program ledger as JSON instead of the table",
    )
    programs_parser.set_defaults(func="programs")

    trace_parser = subparsers.add_parser(
        "trace",
        help="convert an --event_log JSONL to Chrome trace JSON "
        "(Perfetto / chrome://tracing) or print a latency summary",
    )
    args_lib.add_trace_params(trace_parser)
    trace_parser.set_defaults(func="trace")

    lineage_parser = subparsers.add_parser(
        "lineage",
        help="per-window ingest->first-serve freshness waterfalls from "
        "an --event_log JSONL (the train-path twin of `trace`)",
    )
    args_lib.add_lineage_params(lineage_parser)
    lineage_parser.set_defaults(func="lineage")

    incident_parser = subparsers.add_parser(
        "incident",
        help="list incident flight-recorder bundles (--incident_dir of "
        "the master) or render one into a postmortem report",
    )
    args_lib.add_incident_params(incident_parser)
    incident_parser.set_defaults(func="incident")

    zoo_parser = subparsers.add_parser("zoo", help="model zoo image tools")
    zoo_sub = zoo_parser.add_subparsers(dest="zoo_command")
    zoo_init = zoo_sub.add_parser("init", help="scaffold a model zoo dir")
    zoo_init.add_argument("--model_zoo", default="model_zoo")
    zoo_init.add_argument("--base_image", default="python:3.12")
    zoo_init.set_defaults(func="zoo_init")
    zoo_build = zoo_sub.add_parser("build", help="build the job image")
    zoo_build.add_argument("--model_zoo", default="model_zoo")
    zoo_build.add_argument("--image", required=True)
    zoo_build.set_defaults(func="zoo_build")
    zoo_push = zoo_sub.add_parser("push", help="push the job image")
    zoo_push.add_argument("image")
    zoo_push.set_defaults(func="zoo_push")
    return parser


def main(argv=None) -> int:
    parser = _build_parser()
    # Strict parsing: a typo'd flag must error, not silently fall back to
    # a default (the master/worker argv wire format stays tolerant via
    # parse_known_args in common/args.py; the human-facing CLI does not).
    args = parser.parse_args(argv)
    if not getattr(args, "func", None):
        parser.print_help()
        return 2

    from elasticdl_tpu.client import api, image_builder

    if args.func in ("train", "evaluate", "predict", "serve"):
        try:
            return getattr(api, args.func)(args)
        except (ImportError, ModuleNotFoundError) as exc:
            print(
                f"elasticdl {args.func}: cannot load --model_def "
                f"{args.model_def!r} from --model_zoo {args.model_zoo!r}: "
                f"{exc}",
                file=sys.stderr,
            )
            return 1
        except ValueError as exc:
            print(f"elasticdl {args.func}: {exc}", file=sys.stderr)
            return 1
    if args.func == "top":
        from elasticdl_tpu.client.top import top

        return top(args)
    if args.func == "slo":
        from elasticdl_tpu.client.slo import slo

        return slo(args)
    if args.func == "programs":
        from elasticdl_tpu.client.programs import programs

        return programs(args)
    if args.func == "trace":
        from elasticdl_tpu.client.trace import trace

        return trace(args)
    if args.func == "lineage":
        from elasticdl_tpu.client.lineage import lineage

        return lineage(args)
    if args.func == "incident":
        from elasticdl_tpu.client.incident import incident

        return incident(args)
    if args.func == "zoo_init":
        return image_builder.init_zoo(args.model_zoo, args.base_image)
    if args.func == "zoo_build":
        return image_builder.build_image(args.model_zoo, args.image)
    if args.func == "zoo_push":
        return image_builder.push_image(args.image)
    parser.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
