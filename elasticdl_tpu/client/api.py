"""Client API: job construction and submission.

Parity: reference elasticdl_client/api.py (SURVEY.md C18, call stack §3.1).
`Local` strategy runs master + worker in-process (no cluster); cluster
strategies build the master pod spec (command = `python -m
elasticdl_tpu.master.main` with all flags re-serialized as argv — argv is
the config wire format, as in the reference) and submit it through the
Kubernetes client.
"""

from __future__ import annotations

import threading

from elasticdl_tpu.common import args as args_lib
from elasticdl_tpu.common.constants import DistributionStrategy, PodType
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)


def train(args) -> int:
    if args.distribution_strategy == DistributionStrategy.LOCAL:
        return _train_local(args)
    return _submit_master_pod(args, job_type="train")


def evaluate(args) -> int:
    if args.distribution_strategy == DistributionStrategy.LOCAL:
        return _train_local(args, job_type="evaluate")
    return _submit_master_pod(args, job_type="evaluate")


def predict(args) -> int:
    if args.distribution_strategy == DistributionStrategy.LOCAL:
        return _train_local(args, job_type="predict")
    return _submit_master_pod(args, job_type="predict")


def _train_local(args, job_type: str = "train") -> int:
    """Master + worker(s) in one process: the zero-cluster path (and the
    dev loop for model-zoo modules)."""
    from elasticdl_tpu.common.model_handler import get_model_spec
    from elasticdl_tpu.common.virtual_mesh import (
        apply_compilation_cache_config,
    )

    apply_compilation_cache_config(
        getattr(args, "compilation_cache_dir", "")
    )
    from elasticdl_tpu.data.reader import create_data_reader
    from elasticdl_tpu.master.main import Master
    from elasticdl_tpu.proto.service import InProcessMasterClient
    from elasticdl_tpu.worker.worker import Worker

    spec = get_model_spec(
        args.model_zoo,
        args.model_def,
        model_params=args.model_params,
        dataset_fn=args.dataset_fn,
        loss=args.loss,
        optimizer=args.optimizer,
        eval_metrics_fn=args.eval_metrics_fn,
        prediction_outputs_processor=getattr(
            args, "prediction_outputs_processor", ""
        ),
        arena_dtype=getattr(args, "arena_dtype", ""),
        store_cache_dtype=getattr(args, "store_cache_dtype", ""),
    )
    args.job_type = job_type
    if job_type in ("evaluate", "predict") and not args.checkpoint_dir_for_init:
        raise ValueError(
            f"elasticdl {job_type} requires --checkpoint_dir_for_init "
            "(evaluating/predicting with random weights is meaningless)"
        )
    # Same observability surface as the cluster path (master/main.py):
    # span tracing via --event_log and /metrics + /healthz + /varz via
    # --telemetry_port.  One process here, so one telemetry server and
    # one event stream cover master and workers together.
    from elasticdl_tpu.common import events

    if getattr(args, "event_log", ""):
        events.configure(args.event_log, role="local")
    else:
        events.configure_from_env(role="local")
    master = Master(args)
    master.start_telemetry(getattr(args, "telemetry_port", 0))
    # The Local path never calls Master.start() (nothing to place on a
    # cluster), so the metric-history/SLO loops must start here for
    # --history_interval/--slo_interval to cover dev runs too.
    if master.metric_history is not None and master.metric_history.start():
        logger.info(
            "Metric history sampling every %.1fs",
            master.metric_history.interval_s,
        )
    if master.slo_evaluator is not None and master.slo_evaluator.start():
        logger.info(
            "SLO evaluator ticking every %.1fs",
            master.slo_evaluator.interval_s,
        )
    client = InProcessMasterClient(master.servicer)
    data_origin = {
        "train": args.training_data,
        "evaluate": args.validation_data,
        "predict": args.prediction_data,
    }[job_type]
    def make_reader():
        # One reader PER worker thread: the built-in readers are
        # thread-safe (pread-based), but zoo-contributed readers carry no
        # such contract, so never share an instance across workers.
        if spec.custom_data_reader is not None:
            return spec.custom_data_reader(data_origin=data_origin)
        return create_data_reader(data_origin)

    reader = make_reader()

    from elasticdl_tpu.common.save_utils import CheckpointSaver

    init_saver = None
    if job_type in ("evaluate", "predict"):
        init_saver = CheckpointSaver(args.checkpoint_dir_for_init)
        if init_saver.latest_step() is None:
            raise ValueError(
                f"--checkpoint_dir_for_init "
                f"{args.checkpoint_dir_for_init!r} contains no checkpoint"
            )

    def make_saver():
        # evaluate/predict: restore from the init checkpoint; train:
        # periodic checkpointing (optionally warm-started from
        # checkpoint_dir_for_init).
        if job_type in ("evaluate", "predict"):
            return init_saver
        if args.checkpoint_dir:
            return CheckpointSaver(
                args.checkpoint_dir, keep_max=args.keep_checkpoint_max
            )
        if args.checkpoint_dir_for_init:
            return CheckpointSaver(args.checkpoint_dir_for_init)
        return None

    # ONE model for the whole job: all worker threads share a ModelOwner
    # (trainer + state + update lock), so every task's gradients land in
    # the same params — the consistency the reference provided via its
    # PS/AllReduce machinery.  Per-worker private replicas would silently
    # train N diverging models on 1/N of the data each.
    from elasticdl_tpu.worker.sync import ModelOwner
    from elasticdl_tpu.worker.trainer import Trainer

    owner = ModelOwner(
        Trainer(
            model=spec.model,
            optimizer=spec.optimizer,
            loss_fn=spec.loss,
            use_bf16=args.use_bf16,
            param_sharding_fn=spec.param_sharding,
        ),
        checkpoint_saver=make_saver(),
        checkpoint_steps=args.checkpoint_steps,
    )

    # Tiered embedding store (elasticdl_tpu/store): a zoo module that
    # exports build_tiered_store() opts into the host-RAM bulk tier +
    # device hot-row cache.  The Local path never calls Master.start()
    # (the PR 10 gotcha), so the store's background threads — cold-miss
    # prefetcher, host-fold worker — must start HERE.
    tiered_store = None
    build_tiered_store = getattr(spec.module, "build_tiered_store", None)
    if build_tiered_store is not None and job_type == "train":
        if args.validation_data:
            raise ValueError(
                "tiered embedding store does not support mid-train "
                "evaluation yet: the eval path prepares admission plans "
                "it never applies, corrupting the cache map — drop "
                "--validation_data for tiered runs"
            )
        # Default registry so /metrics serves store_* next to the worker
        # families; the worker's PhaseTimer so cold-gather time lands in
        # worker_step_phase_seconds{phase="cold_gather"}.
        from elasticdl_tpu.common import metrics as metrics_lib
        from elasticdl_tpu.worker.worker import _phase_timer

        tiered_store = build_tiered_store(
            registry=metrics_lib.default_registry(),
            phase_timer=_phase_timer,
        )
        if getattr(args, "steps_per_execution", 1) != 1:
            # Fused multi-step (ISSUE 18c): the K steps run as one
            # uninterruptible scan, so per-batch eager plans are
            # impossible — the trainer plans ONE admission block over
            # the union of the K batches' rows at train time, which
            # requires the raw sparse batches (deferred mode) rather
            # than pre-planned slots.
            tiered_store.enable_deferred_prepare()
            logger.info(
                "Tiered store: deferred block planning for "
                "steps_per_execution=%d", args.steps_per_execution,
            )
        if args.num_workers != 1:
            # Multi-worker path: N feed producers cannot keep the strict
            # batch-order invariant eager planning needs, so planning is
            # DEFERRED to the trainer's step-serialized critical section
            # (ModelOwner's lock) — prepare+apply run in step order there
            # regardless of producer interleaving.  Costs the async
            # cold-gather overlap; see docs/PERF.md §4.  Row-range
            # sharding across workers is store/sharding.py.
            tiered_store.enable_deferred_prepare()
            logger.info(
                "Tiered store: deferred planning for %d workers",
                args.num_workers,
            )
        spec.feed = tiered_store.wrap_feed(spec.feed)
        spec.feed_bulk = tiered_store.wrap_feed(spec.feed_bulk)
        owner.trainer.tiered_store = tiered_store
        # Mesh-sharded seam (ISSUE 18b): declare the model-axis size so
        # plans carry per-chip sub-plans and per-chip byte accounting
        # matches the row-sharded cache tables XLA actually partitions.
        model_shards = int(dict(owner.trainer.mesh.shape).get("model", 1))
        if model_shards > 1:
            tiered_store.set_mesh_shards(model_shards)
        if owner.checkpoint_saver is not None:
            owner.checkpoint_saver.attach_tiered_store(tiered_store)
        tiered_store.start()
        logger.info(
            "Tiered embedding store active: cache_rows=%d host_dtype=%s "
            "cache_dtype=%s mesh_shards=%d",
            tiered_store.cache_rows, tiered_store.host.host_dtype,
            tiered_store.cache_dtype, tiered_store.mesh_shards,
        )

    # A restored task journal may already be terminal; the finish check
    # must run once proactively (it also injects the final-eval round for
    # the restored model) since no training report will ever drain the
    # queue.
    master.task_manager.maybe_finish_if_drained()

    workers = []
    threads = []
    for wid in range(args.num_workers):
        tb_dir = ""
        if getattr(args, "tensorboard_log_dir", ""):
            import os

            tb_dir = os.path.join(
                args.tensorboard_log_dir, f"worker-{wid}"
            )
        worker = Worker(
            worker_id=wid,
            master_client=client,
            data_reader=reader if wid == 0 else make_reader(),
            spec=spec,
            minibatch_size=args.minibatch_size,
            model_owner=owner,
            steps_per_execution=getattr(args, "steps_per_execution", 1),
            compact_wire=getattr(args, "compact_wire", False),
            wire_format=getattr(args, "wire_format", ""),
            tensorboard_dir=tb_dir,
            # one process, one profiler: only worker 0 may trace
            profile_dir=(
                getattr(args, "profile_dir", "") if wid == 0 else ""
            ),
        )
        workers.append(worker)
        thread = threading.Thread(target=worker.run, daemon=True)
        threads.append(thread)
        thread.start()
    ok = master.wait()
    for thread in threads:
        thread.join(timeout=60)
    if tiered_store is not None:
        # drain pending eviction write-backs, then stop both threads
        tiered_store.stop()
    if master.slo_evaluator is not None:
        master.slo_evaluator.stop()
    if master.metric_history is not None:
        master.metric_history.stop()
    if owner.checkpoint_saver is not None:
        # flush any in-flight async checkpoint writes
        owner.checkpoint_saver.wait_until_finished()
    metrics = master.evaluation_service.latest_metrics()
    if metrics:
        logger.info("Final metrics: %s", metrics)
    if job_type == "predict" and args.output:
        import numpy as np

        # per-task arrays keyed by task_id (rerun-safe); merge in task
        # order so the row order is deterministic across runs
        by_task = {}
        for w in workers:
            by_task.update(getattr(w, "predictions", {}) or {})
        if by_task:
            os_path = args.output
            if not os_path.endswith(".npy"):
                import os

                os.makedirs(os_path, exist_ok=True)
                os_path = f"{os_path}/predictions.npy"
            np.save(
                os_path,
                np.concatenate([by_task[t] for t in sorted(by_task)]),
            )
            logger.info("Wrote predictions to %s", os_path)
    elif args.output and owner.state is not None:
        from elasticdl_tpu.common.export import export_model

        export_model(
            owner.state, spec, args.output,
            saved_model=bool(getattr(args, "export_saved_model", False)),
            sample_features=owner.sample_features,
        )
        logger.info("Exported model to %s", args.output)
    logger.info("Job %s: %s", "succeeded" if ok else "failed",
                master.task_manager.snapshot())
    return 0 if ok else 1


def serve(args) -> int:
    """`elasticdl serve`: gRPC online inference for a zoo model, from a
    params.msgpack export (--export_dir) or a live checkpoint directory
    (--checkpoint_dir, with hot reload).  docs/SERVING.md."""
    from elasticdl_tpu.common import events

    if getattr(args, "event_log", ""):
        events.configure(args.event_log, role="serving")
    else:
        events.configure_from_env(role="serving")
    server = build_serving_server(args)
    port = server.start(args.port)
    logger.info(
        "serving %s on port %d (ctrl-c to stop)", args.model_def, port
    )
    try:
        server.wait()
    except KeyboardInterrupt:
        logger.info("shutting down")
    finally:
        server.stop()
    return 0


def build_serving_server(args):
    """Assemble (but do not start) the engine/batcher/reloader/server
    stack from parsed `elasticdl serve` args — split from serve() so
    tests and embedders drive the lifecycle themselves."""
    import json
    import os

    import numpy as np

    from elasticdl_tpu.common.model_handler import get_model_spec
    from elasticdl_tpu.serving.batcher import DynamicBatcher
    from elasticdl_tpu.serving.engine import ServingEngine
    from elasticdl_tpu.serving.reloader import CheckpointReloader
    from elasticdl_tpu.serving.server import ServingServer

    if bool(args.export_dir) == bool(args.checkpoint_dir):
        raise ValueError(
            "elasticdl serve needs exactly one of --export_dir or "
            "--checkpoint_dir"
        )
    spec = get_model_spec(
        args.model_zoo, args.model_def, model_params=args.model_params,
        arena_dtype=getattr(args, "arena_dtype", ""),
        store_cache_dtype=getattr(args, "store_cache_dtype", ""),
    )
    buckets = tuple(
        int(b) for b in str(args.batch_buckets).split(",") if b.strip()
    )
    reloader = None
    if args.export_dir:
        engine = ServingEngine.from_export(
            args.export_dir, spec, buckets=buckets
        )
    else:
        feature_spec = args.feature_spec
        if not feature_spec:
            raise ValueError(
                "--checkpoint_dir serving needs --feature_spec (inline "
                "JSON or a path to an export_meta.json)"
            )
        if os.path.exists(feature_spec):
            with open(feature_spec) as f:
                meta = json.load(f)
            feature_spec = meta.get("features", meta)
        else:
            feature_spec = json.loads(feature_spec)
        sample = {
            name: np.zeros(
                (1, *leaf["shape"]), np.dtype(leaf["dtype"])
            )
            for name, leaf in feature_spec.items()
        }
        from elasticdl_tpu.common.export import SINGLE_FEATURE_KEY

        if set(sample) == {SINGLE_FEATURE_KEY}:
            sample = sample[SINGLE_FEATURE_KEY]
        engine = ServingEngine.from_checkpoint(
            args.checkpoint_dir, spec, sample, buckets=buckets
        )
        reloader = CheckpointReloader(
            engine, args.checkpoint_dir,
            poll_interval_s=args.reload_poll_seconds,
        )
    batcher = DynamicBatcher(
        engine,
        max_latency_s=args.max_batch_latency_ms / 1000.0,
        max_queue_rows=args.max_queue_rows or None,
        reject_oversized=args.reject_oversized,
    )
    return ServingServer(
        engine, batcher, reloader,
        telemetry_port=getattr(args, "telemetry_port", 0),
    )


def _submit_master_pod(args, job_type: str) -> int:
    """Cluster mode: create the master pod through the Kubernetes API."""
    from elasticdl_tpu.common.k8s_client import (
        K8sClient,
        PodSpec,
        parse_volumes,
    )

    master_args = args_lib.build_arguments_from_parsed_result(
        args, filter_args={"func"}
    )
    command = (
        ["python", "-m", "elasticdl_tpu.master.main"]
        + master_args
        + ["--job_type", job_type]
    )
    client = K8sClient(namespace=args.namespace, job_name=args.job_name)
    master_name = f"{args.job_name}-master"
    client.create_pod(
        PodSpec(
            name=master_name,
            pod_type=PodType.MASTER,
            image=args.image_name,
            command=command,
            resources={},
            volumes=parse_volumes(getattr(args, "volume", "")),
        )
    )
    # Worker pods dial `{job_name}-master:{port}`; that DNS name only
    # exists if a Service fronts the master pod (selector = the labels
    # K8sClient.create_pod stamps on it).
    client.create_service(
        master_name,
        selector={
            "elasticdl-job": args.job_name,
            "elasticdl-type": PodType.MASTER,
        },
        port=args.port,
    )
    logger.info(
        "Submitted master pod %s-master to namespace %s",
        args.job_name, args.namespace,
    )
    return 0
