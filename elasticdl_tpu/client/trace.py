"""`elasticdl trace`: event log -> Chrome trace-event JSON / summaries.

The span-event log (common/events.py, --event_log) already carries every
timestamp needed to reconstruct a task's life across processes; this
module only re-shapes that JSONL into the Chrome trace-event format so
Perfetto (https://ui.perfetto.dev) or chrome://tracing renders the whole
cluster on one timeline:

  * one process track per role (master / worker / serving), one thread
    track per worker id;
  * every completed task as a duration slice on its worker's track,
    with nested child slices splitting dispatch->claim (queue + RPC),
    claim->trained (training) and trained->reported (report RPC);
  * checkpoint saves/restores, serving hot-reloads, straggler flags and
    per-window step-phase breakdowns as instant events;
  * each elastic-recovery outage as a slice on the master track;
  * every stream window's lifecycle (`window_span` lineage stamps) as
    one slice per window on the "windows" track with nested phase
    segments, dropped/replayed windows flagged in the slice name.

`--summary` skips the JSON and prints per-worker task-latency quantiles,
the slowest K tasks, and the aggregate step-phase breakdown — the
numbers an operator wants before deciding whether to open the trace UI.

stdlib-only, like `elasticdl top`: it must run anywhere the log file is
readable.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from elasticdl_tpu.common import events
from elasticdl_tpu.common import lineage as lineage_lib

# Task-lifecycle chain, in causal order.  A task slice needs at least
# the first and one later timestamp to have an extent.
_CHAIN = (
    events.TASK_DISPATCHED,
    events.TASK_CLAIMED,
    events.TASK_TRAINED,
    events.TASK_REPORTED,
)
# Child-slice names for consecutive chain segments.
_SEGMENTS = ("claim_wait", "train", "report_wait")

_ROLE_PIDS = {"master": 1, "worker": 2, "serving": 3}
_INSTANT_EVENTS = frozenset({
    events.CHECKPOINT_SAVED,
    events.CHECKPOINT_RESTORED,
    events.SERVING_RELOADED,
    events.STRAGGLER_DETECTED,
    events.STEP_PHASES,
    events.SLO_BREACH,
    events.SLO_RECOVERED,
    events.INCIDENT_CAPTURED,
})

#: Serve-path phase rendering order (the request's causal hop order —
#: a subset of events.SPAN_PHASES may be present on any one span).
_PHASE_ORDER = (
    "route", "queue_wait", "batch_form", "pad", "compute", "unpack",
    "respond",
)


def _role_pid(role: str) -> int:
    return _ROLE_PIDS.get(role, 9)


def _us(ts: float, t0: float) -> float:
    """Seconds-since-epoch -> microseconds relative to the log start."""
    return round((ts - t0) * 1e6, 3)


def _task_spans(evts: List[dict]) -> Dict[int, Dict[str, dict]]:
    """task_id -> {event_name: first event record} for chain events."""
    spans: Dict[int, Dict[str, dict]] = {}
    for e in evts:
        name = e.get("event")
        task_id = e.get("task_id")
        if name in _CHAIN and isinstance(task_id, int):
            spans.setdefault(task_id, {}).setdefault(name, e)
    return spans


def task_durations(evts: List[dict]) -> List[Tuple[int, int, float]]:
    """Completed tasks as (task_id, worker_id, dispatch->report seconds).
    Tasks missing either endpoint (in flight when the log was read, or
    lost to a crash) are skipped."""
    out = []
    for task_id, chain in sorted(_task_spans(evts).items()):
        first = chain.get(events.TASK_DISPATCHED)
        last = chain.get(events.TASK_REPORTED)
        if not first or not last:
            continue
        worker_id = _worker_of(chain)
        out.append(
            (task_id, worker_id, float(last["ts"]) - float(first["ts"]))
        )
    return out


def _request_spans(evts: List[dict]) -> Dict[str, dict]:
    """request_id -> one merged serve-request span.  A routed request
    can emit up to two predict_span halves — the servicer's (queue/
    batch/compute/respond phases) and the router's (the route phase +
    the routing outcome) — correlated here by request_id.  Requests the
    sampler skipped never minted a wire request_id, so they are simply
    absent."""
    spans: Dict[str, dict] = {}
    for e in evts:
        if e.get("event") != events.PREDICT_SPAN:
            continue
        request_id = e.get("request_id")
        if not request_id or not isinstance(e.get("ts"), (int, float)):
            continue
        span = spans.setdefault(str(request_id), {
            "request_id": str(request_id),
            "end_ts": float(e["ts"]),
            "reason": "sampled",
            "phases": {},
        })
        span["end_ts"] = max(span["end_ts"], float(e["ts"]))
        reason = e.get("reason")
        # the router's outcome (error/shed/failover) outranks the
        # servicer half's default "sampled"
        if reason and reason != "sampled":
            span["reason"] = str(reason)
        phases = e.get("phases_s")
        if isinstance(phases, dict):
            for phase, seconds in phases.items():
                span["phases"][phase] = max(
                    span["phases"].get(phase, 0.0), float(seconds)
                )
        for key in ("code", "model_step", "rows", "error"):
            if key in e:
                span.setdefault(key, e[key])
    return spans


def _worker_of(chain: Dict[str, dict]) -> int:
    for name in _CHAIN:
        e = chain.get(name)
        if e is not None and e.get("worker_id") is not None:
            return int(e["worker_id"])
    return -1


def build_chrome_trace(evts: List[dict]) -> dict:
    """Re-shape parsed span events into a Chrome trace-event document.
    Timestamps are microseconds relative to the earliest event, so the
    UI opens at t=0 instead of the unix epoch."""
    evts = sorted(
        (e for e in evts if isinstance(e.get("ts"), (int, float))),
        key=lambda e: e["ts"],
    )
    out: List[dict] = []
    if not evts:
        return {"traceEvents": out, "displayTimeUnit": "ms"}
    t0 = float(evts[0]["ts"])

    seen_tracks = set()

    def track(role: str, worker_id: Optional[int]) -> Tuple[int, int]:
        pid = _role_pid(role or "")
        tid = int(worker_id) if worker_id is not None else 0
        if (pid, tid) not in seen_tracks:
            seen_tracks.add((pid, tid))
            out.append({
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "args": {"name": role or "unknown"},
            })
            thread = (
                f"worker {tid}" if role == "worker" else (role or "main")
            )
            out.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": thread},
            })
        return pid, tid

    # Task lifecycle -> nested duration slices on the worker's track.
    for task_id, chain in sorted(_task_spans(evts).items()):
        stamps = [
            (name, float(chain[name]["ts"]))
            for name in _CHAIN if name in chain
        ]
        if len(stamps) < 2:
            continue  # no extent to draw
        worker_id = _worker_of(chain)
        pid, tid = track("worker", worker_id)
        start, end = stamps[0][1], stamps[-1][1]
        args = {"task_id": task_id, "worker_id": worker_id}
        trained = chain.get(events.TASK_TRAINED)
        if trained is not None and "records" in trained:
            args["records"] = trained["records"]
        out.append({
            "ph": "X", "name": f"task {task_id}", "cat": "task",
            "pid": pid, "tid": tid,
            "ts": _us(start, t0), "dur": _us(end, t0) - _us(start, t0),
            "args": args,
        })
        by_name = dict(stamps)
        for seg, (a, b) in zip(
            _SEGMENTS, zip(_CHAIN[:-1], _CHAIN[1:])
        ):
            if a in by_name and b in by_name:
                out.append({
                    "ph": "X", "name": seg, "cat": "task",
                    "pid": pid, "tid": tid,
                    "ts": _us(by_name[a], t0),
                    "dur": _us(by_name[b], t0) - _us(by_name[a], t0),
                    "args": {"task_id": task_id},
                })

    # Routed serve requests -> nested duration slices on the serving
    # track, one child slice per recorded phase in causal hop order.
    # The span event stamps the END of the request; the extent is the
    # sum of its phase durations laid back-to-back up to that stamp.
    for request_id, span in sorted(_request_spans(evts).items()):
        phases = [
            (phase, span["phases"][phase])
            for phase in _PHASE_ORDER if phase in span["phases"]
        ]
        pid, tid = track("serving", None)
        total = sum(seconds for _, seconds in phases)
        end = span["end_ts"]
        args = {
            k: span[k]
            for k in ("request_id", "reason", "code", "model_step",
                      "rows", "error")
            if k in span
        }
        if total <= 0.0:
            # no timed extent (e.g. a decode rejection): still visible
            out.append({
                "ph": "i", "name": f"request {request_id}",
                "cat": "request", "s": "t", "pid": pid, "tid": tid,
                "ts": _us(end, t0), "args": args,
            })
            continue
        out.append({
            "ph": "X", "name": f"request {request_id}", "cat": "request",
            "pid": pid, "tid": tid,
            "ts": _us(end - total, t0), "dur": round(total * 1e6, 3),
            "args": args,
        })
        cursor = end - total
        for phase, seconds in phases:
            out.append({
                "ph": "X", "name": phase, "cat": "request",
                "pid": pid, "tid": tid,
                "ts": _us(cursor, t0), "dur": round(seconds * 1e6, 3),
                "args": {"request_id": request_id},
            })
            cursor += seconds

    # Window lifecycle -> one slice per stream window on the "windows"
    # process track (one thread row per window id), nested phase
    # segments in life order, dropped/replayed windows flagged in the
    # slice name.  Lineage stamps ride the components' INJECTABLE clock
    # (`at_unix_s`), which under a fake-clock chaos run is a different
    # epoch from the emit wall time — so window slices are positioned
    # against the earliest window stamp (under a real clock the two
    # epochs coincide and the tracks line up with everything else).
    states = lineage_lib.from_events(evts)
    window_anchors = [
        s["ingest_unix_s"] for s in states.values()
        if s["ingest_unix_s"] is not None
    ]
    if window_anchors:
        win_pid = 4
        out.append({
            "ph": "M", "name": "process_name", "pid": win_pid, "tid": 0,
            "args": {"name": "windows"},
        })
        t0w = min(window_anchors)
        for wid, state in sorted(states.items()):
            start = state["ingest_unix_s"]
            if start is None:
                continue
            decomp = lineage_lib.decompose(state)
            phases = [
                (p, decomp["phases"][p])
                for p in lineage_lib.PHASE_ORDER
                if p in decomp["phases"]
            ]
            tid = int(wid)
            out.append({
                "ph": "M", "name": "thread_name", "pid": win_pid,
                "tid": tid, "args": {"name": f"window {wid}"},
            })
            flags = [
                f for f in ("dropped", "replayed", "rearmed")
                if decomp[f]
            ]
            name = f"window {wid}" + (
                f" [{'+'.join(flags)}]" if flags else ""
            )
            args = {
                "window_id": int(wid),
                "complete": decomp["complete"],
                "dropped": decomp["dropped"],
                "replayed": decomp["replayed"],
                "rearmed": decomp["rearmed"],
                "tasks": decomp["tasks"],
                "records": decomp["records"],
                "e2e_s": decomp["e2e_s"],
            }
            if decomp["blocked_phase"]:
                args["blocked_phase"] = decomp["blocked_phase"]
            total = sum(seconds for _, seconds in phases)
            if total <= 0.0:
                # sealed-only (or dropped at seal): no extent to draw
                out.append({
                    "ph": "i", "name": name, "cat": "window", "s": "t",
                    "pid": win_pid, "tid": tid,
                    "ts": _us(start, t0w), "args": args,
                })
                continue
            out.append({
                "ph": "X", "name": name, "cat": "window",
                "pid": win_pid, "tid": tid,
                "ts": _us(start, t0w), "dur": round(total * 1e6, 3),
                "args": args,
            })
            cursor = start
            for phase, seconds in phases:
                out.append({
                    "ph": "X", "name": phase, "cat": "window",
                    "pid": win_pid, "tid": tid,
                    "ts": _us(cursor, t0w),
                    "dur": round(seconds * 1e6, 3),
                    "args": {"window_id": int(wid)},
                })
                cursor += seconds

    # XLA compiles -> one slice per program_compiled event on the
    # "programs" process track (one thread row per program name); the
    # event stamps the END of the compile and carries its wall seconds,
    # so the slice is laid back from the stamp.  Recompile storms show
    # as flagged instants on the storming program's row.
    compile_evts = [
        e for e in evts
        if e.get("event") in (events.PROGRAM_COMPILED,
                              events.RECOMPILE_STORM)
        and e.get("program")
    ]
    if compile_evts:
        prog_pid = 5
        out.append({
            "ph": "M", "name": "process_name", "pid": prog_pid, "tid": 0,
            "args": {"name": "programs"},
        })
        prog_tids = {
            name: tid for tid, name in enumerate(
                sorted({str(e["program"]) for e in compile_evts}), 1
            )
        }
        for name, tid in sorted(prog_tids.items()):
            out.append({
                "ph": "M", "name": "thread_name", "pid": prog_pid,
                "tid": tid, "args": {"name": name},
            })
        for e in compile_evts:
            name = str(e["program"])
            tid = prog_tids[name]
            ts = float(e["ts"])
            if e["event"] == events.RECOMPILE_STORM:
                out.append({
                    "ph": "i", "name": f"recompile storm: {name}",
                    "cat": "compile", "s": "g", "pid": prog_pid,
                    "tid": tid, "ts": _us(ts, t0),
                    "args": {
                        "program": name,
                        "signatures": e.get("signatures"),
                        "budget": e.get("budget"),
                    },
                })
                continue
            dur = float(e.get("seconds", 0.0))
            args = {
                k: e[k]
                for k in ("program", "signature", "flops", "bytes",
                          "signatures")
                if k in e
            }
            out.append({
                "ph": "X", "name": f"compile {name}", "cat": "compile",
                "pid": prog_pid, "tid": tid,
                "ts": _us(ts - dur, t0), "dur": round(dur * 1e6, 3),
                "args": args,
            })

    # Point events + recovery outage slices.
    for e in evts:
        name = e.get("event")
        ts = float(e["ts"])
        if name in _INSTANT_EVENTS:
            pid, tid = track(e.get("role", ""), e.get("worker_id"))
            args = {
                k: v for k, v in e.items()
                if k not in ("ts", "event", "role", "pid")
            }
            out.append({
                "ph": "i", "name": name, "cat": "ops", "s": "t",
                "pid": pid, "tid": tid, "ts": _us(ts, t0), "args": args,
            })
        elif name == events.RECOVERY_DONE:
            # The outage extent rides the done event (duration_s), so a
            # lost recovery_started line can't orphan the slice.
            dur = float(e.get("duration_s", 0.0))
            pid, tid = track(e.get("role", "master"), None)
            out.append({
                "ph": "X", "name": "elastic recovery", "cat": "ops",
                "pid": pid, "tid": tid,
                "ts": _us(ts - dur, t0), "dur": round(dur * 1e6, 3),
                "args": {},
            })
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def _quantile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def summarize(evts: List[dict], slowest_k: int = 5) -> str:
    """Operator summary: per-worker task-latency quantiles, slowest-K
    tasks, aggregate step-phase breakdown."""
    lines: List[str] = []
    durations = task_durations(evts)
    by_worker: Dict[int, List[float]] = {}
    for _, worker_id, dur in durations:
        by_worker.setdefault(worker_id, []).append(dur)
    lines.append(f"tasks completed: {len(durations)}")
    if by_worker:
        lines.append("")
        lines.append(
            "worker".ljust(8) + "tasks".rjust(7) + "p50_s".rjust(9)
            + "p90_s".rjust(9) + "p99_s".rjust(9) + "mean_s".rjust(9)
        )
        for worker_id in sorted(by_worker):
            vals = sorted(by_worker[worker_id])
            lines.append(
                str(worker_id).ljust(8)
                + str(len(vals)).rjust(7)
                + f"{_quantile(vals, 0.50):.3f}".rjust(9)
                + f"{_quantile(vals, 0.90):.3f}".rjust(9)
                + f"{_quantile(vals, 0.99):.3f}".rjust(9)
                + f"{sum(vals) / len(vals):.3f}".rjust(9)
            )
    if durations and slowest_k > 0:
        lines.append("")
        lines.append(f"slowest {min(slowest_k, len(durations))} tasks:")
        for task_id, worker_id, dur in sorted(
            durations, key=lambda t: -t[2]
        )[:slowest_k]:
            lines.append(
                f"  task {task_id} (worker {worker_id}): {dur:.3f}s"
            )

    # Aggregate phase breakdown across every step_phases flush window.
    phase_totals: Dict[str, float] = {}
    phase_steps = 0
    for e in evts:
        if e.get("event") != events.STEP_PHASES:
            continue
        phases = e.get("phases")
        if not isinstance(phases, dict):
            continue
        phase_steps += int(e.get("steps", 0))
        for phase, seconds in phases.items():
            phase_totals[phase] = (
                phase_totals.get(phase, 0.0) + float(seconds)
            )
    if phase_totals:
        total = sum(phase_totals.values()) or 1.0
        lines.append("")
        lines.append(f"step phases ({phase_steps} steps):")
        for phase in sorted(phase_totals, key=phase_totals.get,
                            reverse=True):
            mean = (
                phase_totals[phase] / phase_steps if phase_steps else 0.0
            )
            lines.append(
                f"  {phase:<10} {phase_totals[phase]:9.3f}s total  "
                f"{mean * 1e3:8.2f} ms/step  "
                f"{100.0 * phase_totals[phase] / total:5.1f}%"
            )

    # Serve-path request spans (predict_span events), per-phase.
    spans = _request_spans(evts)
    if spans:
        outcomes = sorted(
            s["request_id"] for s in spans.values()
            if s["reason"] != "sampled"
        )
        lines.append("")
        lines.append(
            f"serve requests traced: {len(spans)} "
            f"({len(outcomes)} forensic: error/shed/failover)"
        )
        by_phase: Dict[str, List[float]] = {}
        for span in spans.values():
            for phase, seconds in span["phases"].items():
                by_phase.setdefault(phase, []).append(seconds)
        if by_phase:
            lines.append(
                "phase".ljust(12) + "n".rjust(6) + "p50_ms".rjust(10)
                + "p99_ms".rjust(10) + "mean_ms".rjust(10)
            )
            for phase in _PHASE_ORDER:
                if phase not in by_phase:
                    continue
                vals = sorted(by_phase[phase])
                lines.append(
                    phase.ljust(12)
                    + str(len(vals)).rjust(6)
                    + f"{_quantile(vals, 0.50) * 1e3:.3f}".rjust(10)
                    + f"{_quantile(vals, 0.99) * 1e3:.3f}".rjust(10)
                    + f"{sum(vals) / len(vals) * 1e3:.3f}".rjust(10)
                )
        for request_id in outcomes[:5]:
            span = spans[request_id]
            lines.append(
                f"  {request_id}: {span['reason']}"
                + (f" code={span['code']}" if "code" in span else "")
                + (f" error={span['error']}" if "error" in span else "")
            )

    # XLA compile summary (program_compiled events): where trace/compile
    # wall time went, per program, plus any storms.
    compiles: Dict[str, List[float]] = {}
    storms: Dict[str, int] = {}
    for e in evts:
        if e.get("event") == events.PROGRAM_COMPILED and e.get("program"):
            compiles.setdefault(str(e["program"]), []).append(
                float(e.get("seconds", 0.0))
            )
        elif (e.get("event") == events.RECOMPILE_STORM
                and e.get("program")):
            storms[str(e["program"])] = storms.get(str(e["program"]), 0) + 1
    if compiles:
        lines.append("")
        lines.append(
            "xla compiles: {n} across {p} programs, "
            "{s:.3f}s total".format(
                n=sum(len(v) for v in compiles.values()),
                p=len(compiles),
                s=sum(sum(v) for v in compiles.values()),
            )
        )
        for name in sorted(compiles, key=lambda n: -sum(compiles[n])):
            vals = compiles[name]
            storm_text = (
                f"  STORMS={storms[name]}" if name in storms else ""
            )
            lines.append(
                f"  {name:<24} {len(vals):3d} compiles  "
                f"{sum(vals):8.3f}s total  "
                f"{max(vals):7.3f}s max{storm_text}"
            )

    stragglers = [
        e for e in evts if e.get("event") == events.STRAGGLER_DETECTED
    ]
    if stragglers:
        lines.append("")
        lines.append(f"straggler flags: {len(stragglers)}")
        for e in stragglers[-5:]:
            lines.append(
                "  worker {w}: {m:.3f}s/task vs fleet median "
                "{md:.3f}s ({r:.1f}x)".format(
                    w=e.get("worker_id", "?"),
                    m=float(e.get("mean_task_s", 0.0)),
                    md=float(e.get("median_task_s", 0.0)),
                    r=float(e.get("ratio", 0.0)),
                )
            )
    return "\n".join(lines)


def trace(args) -> int:
    """Entry point for `elasticdl trace`."""
    evts = events.read_events(args.event_log)
    if not evts:
        print(f"elasticdl trace: no events in {args.event_log!r}")
        return 1
    wrote = False
    if getattr(args, "chrome", ""):
        doc = build_chrome_trace(evts)
        with open(args.chrome, "w") as fh:
            json.dump(doc, fh)
        slices = sum(
            1 for e in doc["traceEvents"] if e.get("cat") == "task"
        )
        print(
            f"wrote {args.chrome}: {len(doc['traceEvents'])} trace "
            f"events ({slices} task slices) — open in "
            "https://ui.perfetto.dev or chrome://tracing"
        )
        wrote = True
    if getattr(args, "summary", False) or not wrote:
        print(summarize(evts, slowest_k=getattr(args, "slowest", 5)))
    return 0
