"""`elasticdl incident`: postmortem reports from flight-recorder bundles.

The master's incident flight recorder (common/flight.py) writes one
self-contained JSON bundle per trigger under `--incident_dir`; this
command is the read side.  With just the directory it lists every
bundle (seq, trigger, counts); with `--bundle` it renders one into the
report an operator reads first in a postmortem: what tripped the
capture, which SLOs were burning, the decisions leading up to the
incident, the slowest request spans caught in the ring, the window
lineage tail with its dominant freshness phase, and any fault
injections that were active.

stdlib-only, like `elasticdl top` and `elasticdl trace`: it must run
anywhere the bundle directory is readable.
"""

from __future__ import annotations

from typing import Dict, List

from elasticdl_tpu.common import events
from elasticdl_tpu.common import flight
from elasticdl_tpu.common import lineage as lineage_lib


def _window_decompositions(records: List[dict]) -> List[dict]:
    """Per-window freshness decompositions from the bundle's lineage
    ring, window-id order.  Open windows are charged up to the newest
    stamp in the ring, attributed to the phase they are blocked in —
    that is what lets a mid-stall bundle name the guilty phase."""
    states = lineage_lib.from_events(records)
    stamps = [
        float(r["at_unix_s"]) for r in records
        if r.get("event") == events.WINDOW_SPAN
        and r.get("at_unix_s") is not None
    ]
    now = max(stamps) if stamps else None
    return [
        lineage_lib.decompose(states[wid], now=now)
        for wid in sorted(states)
    ]


def _span_total_s(span: dict) -> float:
    phases = span.get("phases_s")
    if not isinstance(phases, dict):
        return 0.0
    return sum(float(v) for v in phases.values())


def format_listing(bundles: List[dict]) -> str:
    """One row per bundle, capture order."""
    lines = [
        "bundle".ljust(34) + "trigger".ljust(18)
        + "spans".rjust(7) + "decisions".rjust(11) + "lineage".rjust(9)
    ]
    for manifest in bundles:
        counts = manifest.get("counts", {})
        lines.append(
            str(manifest.get("bundle", "?")).ljust(34)
            + str(manifest.get("trigger", "?")).ljust(18)
            + str(counts.get("spans", 0)).rjust(7)
            + str(counts.get("decisions", 0)).rjust(11)
            + str(counts.get("lineage", 0)).rjust(9)
        )
    return "\n".join(lines)


def format_report(bundle: Dict[str, object], spans_k: int = 10) -> str:
    """The postmortem report for one loaded bundle."""
    manifest = bundle.get("manifest", {})
    lines: List[str] = []
    lines.append(f"incident {manifest.get('bundle', '?')}")
    lines.append(f"  trigger: {manifest.get('trigger', '?')}")
    evidence = manifest.get("evidence") or {}
    if evidence:
        detail = ", ".join(
            f"{k}={evidence[k]}" for k in sorted(evidence)
            if k not in ("event",)
        )
        lines.append(f"  evidence: {detail}")

    # SLO states at capture time (the master snapshot's slo section).
    master = bundle.get("master") or {}
    slo = master.get("slo") if isinstance(master, dict) else None
    if isinstance(slo, dict):
        lines.append("")
        lines.append("slo states at capture:")
        for row in slo.get("slos", []):
            if not isinstance(row, dict) or "state" not in row:
                continue
            lines.append(
                f"  {row.get('slo', '?'):<24} {row.get('state', '?'):<9}"
                f" fast_burn={row.get('fast_burn', 0.0)}"
                f" slow_burn={row.get('slow_burn', 0.0)}"
            )

    decisions = bundle.get("decisions") or []
    if decisions:
        lines.append("")
        lines.append(f"decisions before the incident ({len(decisions)}):")
        for record in decisions[-10:]:
            if not isinstance(record, dict):
                continue
            event = record.get("event", "?")
            detail = ", ".join(
                f"{k}={record[k]}" for k in sorted(record)
                if k not in ("event", "role", "worker_id")
            )
            lines.append(f"  {event}: {detail}")

    spans = [s for s in (bundle.get("spans") or []) if isinstance(s, dict)]
    if spans:
        forensic = [s for s in spans if s.get("reason") != "sampled"]
        lines.append("")
        lines.append(
            f"request spans in the ring: {len(spans)} "
            f"({len(forensic)} forensic: error/shed/failover)"
        )
        slowest = sorted(spans, key=_span_total_s, reverse=True)
        for span in slowest[:spans_k]:
            phases = span.get("phases_s") or {}
            detail = " ".join(
                f"{phase}={float(phases[phase]) * 1e3:.2f}ms"
                for phase in sorted(phases)
            )
            lines.append(
                f"  {span.get('request_id', '?')}"
                f" [{span.get('reason', '?')}]"
                f" total={_span_total_s(span) * 1e3:.2f}ms {detail}"
            )

    lineage_records = [
        r for r in (bundle.get("lineage") or []) if isinstance(r, dict)
    ]
    if lineage_records:
        decomps = _window_decompositions(lineage_records)
        if decomps:
            complete = sum(1 for d in decomps if d["complete"])
            open_ = sum(1 for d in decomps if not d["complete"])
            dropped = sum(1 for d in decomps if d["dropped"])
            lines.append("")
            lines.append(
                f"window lineage in the ring: {len(decomps)} windows "
                f"({complete} complete, {open_} open, {dropped} dropped)"
            )
            dominant = lineage_lib.dominant_phase(decomps)
            if dominant:
                lines.append(f"  dominant phase: {dominant}")
            for d in decomps[-5:]:
                flags = "+".join(
                    f for f in ("dropped", "replayed", "rearmed") if d[f]
                )
                phases = d.get("phases") or {}
                dom = max(phases, key=phases.get) if phases else None
                state = (
                    "" if d["complete"]
                    else f", blocked in {d['blocked_phase'] or '?'}"
                )
                lines.append(
                    f"  window {d['window_id']}"
                    + (f" [{flags}]" if flags else "")
                    + f": {d['e2e_s']:.3f}s"
                    + (f", dominant {dom}" if dom else "")
                    + state
                )

    faults = bundle.get("faults") or {}
    if isinstance(faults, dict) and faults.get("injected"):
        lines.append("")
        lines.append(
            f"fault injections active: {faults.get('injected', 0)}"
            f"/{faults.get('planned', 0)} planned"
        )
        by_action = faults.get("by_action")
        if isinstance(by_action, dict) and by_action:
            lines.append("  " + ", ".join(
                f"{action}={by_action[action]}"
                for action in sorted(by_action)
            ))
    return "\n".join(lines)


def incident(args) -> int:
    """Entry point for `elasticdl incident`."""
    bundles = flight.list_bundles(args.incident_dir)
    if not bundles:
        print(
            f"elasticdl incident: no bundles under {args.incident_dir!r}"
        )
        return 1
    wanted = getattr(args, "bundle", "")
    if not wanted:
        print(format_listing(bundles))
        return 0
    matches = [
        m for m in bundles
        if str(m.get("bundle", "")).startswith(wanted)
    ]
    if not matches:
        print(
            f"elasticdl incident: no bundle matches {wanted!r} "
            f"(have: {', '.join(str(m.get('bundle')) for m in bundles)})"
        )
        return 1
    if len(matches) > 1:
        print(
            f"elasticdl incident: {wanted!r} is ambiguous "
            f"({', '.join(str(m.get('bundle')) for m in matches)})"
        )
        return 1
    bundle = flight.load_bundle(matches[0]["path"])
    print(format_report(bundle, spans_k=getattr(args, "spans", 10)))
    return 0
