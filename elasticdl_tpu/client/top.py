"""`elasticdl top`: live cluster table from the master's /varz endpoint.

The master aggregates everything `top` shows — task progress, per-worker
step rates (peeled from task-report exec_counters), pod churn, recovery
durations, retry/fault counters — into Master.snapshot(), which its
telemetry server republishes as JSON on /varz (docs/OBSERVABILITY.md).
`top` is therefore a pure HTTP client: point it at the master's
--telemetry_port (and optionally a serving replica's) and it renders a
refreshing table.  stdlib-only on purpose — it must run from any box
that can reach the port.
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Optional


def fetch_varz(url: str, timeout_s: float = 5.0) -> dict:
    """GET a telemetry /varz endpoint.  `url` may be 'host:port' or a
    full http URL (with or without the /varz path)."""
    if "://" not in url:
        url = f"http://{url}"
    if not url.rstrip("/").endswith("/varz"):
        url = url.rstrip("/") + "/varz"
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _dominant_phase(entry: dict) -> str:
    """Where this worker's step time goes: the largest of the cumulative
    `phase_<name>_ms` telemetry counters, with its share.  '-' until the
    worker has reported phase telemetry."""
    phases = {
        key[len("phase_"):-len("_ms")]: value
        for key, value in entry.items()
        if key.startswith("phase_") and key.endswith("_ms") and value
    }
    total = sum(phases.values())
    if not total:
        return "-"
    name = max(phases, key=phases.get)
    return f"{name} {100 * phases[name] / total:.0f}%"


def _fmt(value, width: int) -> str:
    if isinstance(value, float):
        text = f"{value:.2f}"
    else:
        text = str(value)
    return text.rjust(width)


def render(varz: dict, serving_varz: Optional[dict] = None,
           clock=time.time) -> str:
    """One refresh frame: cluster summary + per-worker table (+ serving
    row when a serving /varz was scraped).  `clock` is injectable so
    tests render deterministic "ago" columns."""
    lines = []
    snapshot = varz.get("snapshot", {})
    tasks = snapshot.get("tasks", {})
    counters = tasks.get("counters", {})
    metrics = varz.get("metrics", {})
    lines.append(
        f"elasticdl top — master pid={varz.get('pid', '?')} "
        f"role={varz.get('role', '?')} "
        f"at {time.strftime('%H:%M:%S')}"
    )
    lines.append(
        "tasks: todo={todo} doing={doing} finished={fin} failed={fail} "
        "recovered={rec} expired={exp} records={records} "
        "epoch={epoch}/{epochs}".format(
            todo=tasks.get("todo", 0),
            doing=tasks.get("doing", 0),
            fin=counters.get("finished", 0),
            fail=counters.get("failed", 0),
            rec=counters.get("recovered", 0),
            exp=counters.get("expired", 0),
            records=counters.get("records_done", 0),
            epoch=tasks.get("epoch", 0),
            epochs=tasks.get("num_epochs", 0),
        )
    )
    online = snapshot.get("online")
    if online:
        lines.append(
            "online: window={win} lag={lag:.2f}s armed={armed} "
            "tasks_rearmed={rearmed} rearm_faults={faults} "
            "last_reload_step={reload}".format(
                win=online.get("window", -1),
                lag=online.get("watermark_lag_s", 0.0),
                armed=online.get("windows_armed", 0),
                rearmed=online.get("tasks_rearmed", 0),
                faults=online.get("rearm_faults", 0),
                reload=online.get("last_reload_step", "-"),
            )
        )
    pods = snapshot.get("pods")
    if pods:
        lines.append(
            f"pods: alive={pods.get('alive', 0)} "
            f"losses={pods.get('losses_seen', 0)} "
            f"relaunches={pods.get('relaunches', 0)} "
            f"evictions={pods.get('evictions', 0)}"
        )
    policy = snapshot.get("policy")
    if policy:
        decisions = policy.get("decisions", [])
        last = decisions[-1] if decisions else None
        last_text = (
            f" last={last['action']}/{last['reason']}@t{last['tick']}"
            if last else ""
        )
        state = (
            "off" if policy.get("interval_s", 0) <= 0
            else f"every {policy['interval_s']:.0f}s"
        )
        lines.append(
            f"policy [{state}]: ticks={policy.get('ticks', 0)} "
            f"backlog/worker={policy.get('backlog_per_worker', 0.0):.2f} "
            f"data_wait={policy.get('data_wait_ratio', 0.0):.2f} "
            f"evictions={policy.get('evictions_used', 0)}"
            f"/{policy.get('eviction_budget', 0)}{last_text}"
        )
    fleet = snapshot.get("serving_fleet")
    if fleet:
        slo = fleet.get("step_skew_slo", 0)
        lines.append(
            f"fleet: replicas={len(fleet.get('replicas', {}))} "
            f"relaunches={fleet.get('relaunches', 0)} "
            f"reload_steps={fleet.get('reload_steps', 0)} "
            f"skew={fleet.get('model_step_skew', 0)}"
            f"/slo={slo if slo else '-'}"
        )
    serving_policy = snapshot.get("serving_policy")
    if serving_policy:
        last = serving_policy.get("last_decision")
        last_text = (
            f" last={last['action']}/{last['reason']}@t{last['tick']}"
            if last else ""
        )
        offered = metrics.get("traffic_offered_per_sec")
        offered_text = (
            f"offered={offered:.1f}/s " if offered is not None else ""
        )
        lines.append(
            f"traffic: {offered_text}"
            f"shed_ratio={serving_policy.get('shed_ratio', 0.0):.3f} "
            f"burn={serving_policy.get('burn', 0.0):.2f}x "
            f"fleet={serving_policy.get('live_replicas', 0)}"
            f"[{serving_policy.get('min_replicas', 0)}"
            f"-{serving_policy.get('max_replicas', 0)}]"
            f" hold={serving_policy.get('hold_ticks', 0)}{last_text}"
        )
    slo = snapshot.get("slo")
    if slo:
        states = slo.get("states", {})
        burns = {
            row.get("slo"): row.get("fast_burn", 0.0)
            for row in slo.get("slos", [])
        }
        lines.append(
            "slo: " + " ".join(
                f"{name}={states[name]}"
                + (f"({burns[name]:.1f}x)" if burns.get(name) else "")
                for name in sorted(states)
            )
        )
    freshness = snapshot.get("freshness")
    if freshness:
        lines.append(
            "freshness: latest_step={step} staleness "
            "p50={p50:.2f}s p99={p99:.2f}s obs={obs}".format(
                step=freshness.get("latest_step", 0),
                p50=freshness.get("staleness_p50_s", 0.0),
                p99=freshness.get("staleness_p99_s", 0.0),
                obs=freshness.get("observations", 0),
            )
        )
    lineage = snapshot.get("lineage")
    if lineage:
        p99 = lineage.get("e2e_p99_s")
        lines.append(
            "lineage: windows={tr} open={op} replayed={rep} "
            "dropped={drop} e2e_p99={p99} dominant={dom}".format(
                tr=lineage.get("windows_traced", 0),
                op=lineage.get("windows_open", 0),
                rep=lineage.get("replayed", 0),
                drop=lineage.get("dropped", 0),
                p99=f"{p99:.2f}s" if p99 is not None else "-",
                dom=lineage.get("dominant_phase") or "-",
            )
        )
    recovery = snapshot.get("recovery")
    if recovery:
        durations = recovery.get("recovery_durations_s", [])
        tail = (
            " last={:.2f}s".format(durations[-1]) if durations else ""
        )
        lines.append(
            f"recovery: losses={recovery.get('losses', 0)} "
            f"recovered={recovery.get('recoveries', 0)}"
            f"{' PENDING' if recovery.get('pending') else ''}{tail}"
        )
    programs = varz.get("programs")
    if programs and programs.get("programs"):
        lines.append(
            "programs: n={n} compiles={compiles} sigs={sigs} "
            "storms={storms} mfu={mfu:.3f} "
            "bw={bw:.2e}B/s".format(
                n=programs.get("programs", 0),
                compiles=programs.get("compiles_total", 0),
                sigs=programs.get("signatures_total", 0),
                storms=programs.get("storms_total", 0),
                mfu=programs.get("mfu", 0.0),
                bw=programs.get("bytes_per_sec", 0.0),
            )
        )
    resilience = snapshot.get("resilience", {})
    fault_stats = snapshot.get("faults", {})
    lines.append(
        f"rpc: retries={resilience.get('retries', 0)} "
        f"giveups={resilience.get('giveups', 0)} "
        f"faults_injected={fault_stats.get('injected', 0)}"
    )
    workers = snapshot.get("workers", {})
    if workers:
        lines.append("")
        lines.append(
            "worker".ljust(8)
            + "steps".rjust(10)
            + "steps/s".rjust(10)
            + "model_step".rjust(12)
            + "last_report".rjust(14)
            + "top_phase".rjust(16)
            + "flag".rjust(14)
        )
        now = clock()
        for wid in sorted(workers, key=lambda w: int(w)):
            entry = workers[wid]
            ago = now - entry.get("last_report_unix_s", now)
            lines.append(
                str(wid).ljust(8)
                + _fmt(entry.get("steps_total", 0), 10)
                + _fmt(entry.get("steps_per_sec_milli", 0) / 1000.0, 10)
                + _fmt(entry.get("model_step", 0), 12)
                + _fmt(f"{ago:.0f}s ago", 14)
                + _fmt(_dominant_phase(entry), 16)
                + _fmt(
                    "STRAGGLER {:.0f}s".format(
                        entry.get("flagged_for_s", 0.0)
                    )
                    if entry.get("straggler") else "-",
                    14,
                )
            )
    if fleet and fleet.get("replicas"):
        lines.append("")
        lines.append(
            "replica".ljust(8)
            + "addr".ljust(26)
            + "healthy".rjust(8)
            + "model_step".rjust(12)
            + "fill".rjust(8)
            + "shed".rjust(8)
            + "qwait_p99".rjust(11)
            + "comp_p99".rjust(10)
            + "relaunched".rjust(12)
        )
        for rid in sorted(fleet["replicas"], key=lambda r: int(r)):
            entry = fleet["replicas"][rid]
            lines.append(
                str(rid).ljust(8)
                + str(entry.get("addr", "-")).ljust(26)
                + _fmt("yes" if entry.get("healthy") else "NO", 8)
                + _fmt(entry.get("model_step", 0), 12)
                + _fmt(entry.get("fill_ratio", 0.0), 8)
                + _fmt(entry.get("shed", 0), 8)
                + _fmt(
                    "{:.1f}ms".format(
                        entry.get("queue_wait_p99_s", 0.0) * 1e3
                    ), 11,
                )
                + _fmt(
                    "{:.1f}ms".format(
                        entry.get("compute_p99_s", 0.0) * 1e3
                    ), 10,
                )
                + _fmt(entry.get("incarnation", 0), 12)
            )
    if serving_varz is not None:
        smetrics = serving_varz.get("metrics", {})
        lines.append("")
        lines.append(
            "serving: rows={rows:.0f} shed={shed:.0f} "
            "p50={p50:.4f}s p99={p99:.4f}s reloads={reloads:.0f} "
            "model_step={step:.0f}".format(
                rows=smetrics.get("serving_batch_rows_total", 0.0),
                shed=smetrics.get(
                    "serving_requests_rejected_total", 0.0
                ),
                p50=smetrics.get("serving_batch_latency_seconds_p50", 0.0),
                p99=smetrics.get("serving_batch_latency_seconds_p99", 0.0),
                reloads=smetrics.get("serving_reloads_total", 0.0),
                step=smetrics.get("serving_model_step", 0.0),
            )
        )
    return "\n".join(lines)


def top(args, clock=time.time, sleep=time.sleep,
        max_frames: Optional[int] = None) -> int:
    """Render the cluster table; --watch redraws in place until
    interrupted.  `clock`/`sleep` are injectable and `max_frames`
    bounds the watch loop so tests run one deterministic iteration."""
    interval = getattr(args, "interval_s", 2.0)
    watch = getattr(args, "watch", False)
    serving_addr = getattr(args, "serving_addr", "")
    frames = 0
    while True:
        try:
            varz = fetch_varz(args.master_varz)
        except Exception as exc:
            print(f"elasticdl top: cannot scrape {args.master_varz}: {exc}")
            return 1
        serving_varz = None
        if serving_addr:
            try:
                serving_varz = fetch_varz(serving_addr)
            except Exception:
                pass  # serving replica down: keep showing the master
        frame = render(varz, serving_varz, clock=clock)
        if not watch:
            print(frame)
            return 0
        # In-place redraw: wipe the screen once, then home the cursor,
        # repaint, and clear whatever a previously-taller frame left
        # below — no scrollback spam between refreshes.
        prefix = "\033[2J\033[H" if frames == 0 else "\033[H"
        print(prefix + frame + "\033[J", flush=True)
        frames += 1
        if max_frames is not None and frames >= max_frames:
            return 0
        sleep(interval)
