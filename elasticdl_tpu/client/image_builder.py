"""Job-image tooling: `elasticdl zoo init|build|push`.

Parity: reference elasticdl_client image builder (SURVEY.md C18): generate
a Dockerfile embedding the model zoo, build and push via the docker CLI
(gated — absent docker, the generated Dockerfile is still written so CI
images can be built elsewhere).
"""

from __future__ import annotations

import os
import shutil
import subprocess

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)

# Build context is the model zoo's PARENT directory, so the COPY source is
# always the context-relative zoo basename (absolute paths are forbidden
# COPY sources).  The framework itself is pip-installed into the image.
_DOCKERFILE = """\
FROM {base_image}
RUN pip install --no-cache-dir jax[tpu] flax optax orbax-checkpoint \\
    grpcio protobuf numpy elasticdl-tpu
COPY {zoo_basename} /app/model_zoo
WORKDIR /app
ENV PYTHONPATH=/app
ENTRYPOINT ["python", "-m", "elasticdl_tpu.master.main"]
"""


def init_zoo(model_zoo: str, base_image: str = "python:3.12") -> int:
    os.makedirs(model_zoo, exist_ok=True)
    path = os.path.join(model_zoo, "Dockerfile")
    zoo_basename = os.path.basename(os.path.abspath(model_zoo))
    with open(path, "w") as f:
        f.write(_DOCKERFILE.format(base_image=base_image,
                                   zoo_basename=zoo_basename))
    logger.info("Wrote %s", path)
    return 0


def build_image(model_zoo: str, image: str) -> int:
    dockerfile = os.path.join(model_zoo, "Dockerfile")
    if not os.path.exists(dockerfile):
        init_zoo(model_zoo)
    context = os.path.dirname(os.path.abspath(model_zoo)) or "."
    if shutil.which("docker") is None:
        logger.error(
            "docker CLI not found; Dockerfile is at %s — build it on a "
            "machine with docker (`docker build -f %s -t %s %s`)",
            dockerfile, dockerfile, image, context,
        )
        return 1
    return subprocess.call(
        ["docker", "build", "-f", dockerfile, "-t", image, context]
    )


def push_image(image: str) -> int:
    if shutil.which("docker") is None:
        logger.error("docker CLI not found; cannot push %s", image)
        return 1
    return subprocess.call(["docker", "push", image])
