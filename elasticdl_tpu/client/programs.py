"""`elasticdl programs`: the XLA program observatory from a /varz endpoint.

Every role's telemetry server republishes its process-wide
ProgramRegistry (common/programs.py) summary under the "programs" varz
key: per-program compile counts, distinct aval signatures vs declared
budget, recompile storms, compile-time quantiles, and the XLA cost
model (flops / bytes per execution) joined with live step rate into
MFU and bandwidth attribution.  Like `elasticdl top` this is a pure
HTTP client; `render_programs` is also callable directly on a summary
dict so in-process tests render the exact bytes the CLI prints.
"""

from __future__ import annotations

import json
import sys

from elasticdl_tpu.client.top import fetch_varz


def _eng(value: float) -> str:
    """Compact engineering notation for flops/bytes columns."""
    value = float(value or 0.0)
    if value <= 0:
        return "-"
    for unit, scale in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if value >= scale:
            return f"{value / scale:.2f}{unit}"
    return f"{value:.0f}"


def render_programs(summary: dict) -> str:
    """One report frame from a ProgramRegistry.summary() dict: headline
    totals + live roofline ratios, then a row per named program."""
    lines = [
        "elasticdl programs — {n} programs, {c} compiles, "
        "{s} signatures, {st} storms".format(
            n=summary.get("programs", 0),
            c=summary.get("compiles_total", 0),
            s=summary.get("signatures_total", 0),
            st=summary.get("storms_total", 0),
        ),
        "live: mfu={mfu:.3f} hbm={hbm:.3f} bytes/s={bw}".format(
            mfu=summary.get("mfu", 0.0),
            hbm=summary.get("hbm_utilization", 0.0),
            bw=_eng(summary.get("bytes_per_sec", 0.0)),
        ),
        "program".ljust(24) + "compiles".rjust(9) + "sigs".rjust(6)
        + "budget".rjust(7) + "storms".rjust(7) + "c_p50".rjust(9)
        + "c_p99".rjust(9) + "flops/x".rjust(9) + "bytes/x".rjust(9),
    ]
    ledger = summary.get("ledger", {})
    for name in sorted(ledger):
        rec = ledger[name]
        budget = rec.get("budget")
        lines.append(
            str(name).ljust(24)
            + str(rec.get("compiles", 0)).rjust(9)
            + str(rec.get("signatures", 0)).rjust(6)
            + (str(budget) if budget is not None else "-").rjust(7)
            + str(rec.get("storms", 0)).rjust(7)
            + "{:.3f}s".format(
                rec.get("compile_seconds_p50", 0.0)
            ).rjust(9)
            + "{:.3f}s".format(
                rec.get("compile_seconds_p99", 0.0)
            ).rjust(9)
            + _eng(rec.get("flops_per_execution", 0.0)).rjust(9)
            + _eng(rec.get("bytes_per_execution", 0.0)).rjust(9)
        )
        avals = rec.get("avals", "")
        if avals:
            lines.append("  " + avals)
    if not ledger:
        lines.append("(no programs registered — has the role jitted "
                     "anything yet?)")
    return "\n".join(lines)


def programs(args) -> int:
    """Fetch a role's /varz and render the program observatory."""
    try:
        varz = fetch_varz(args.varz_addr)
    except Exception as exc:
        print(
            f"elasticdl programs: cannot scrape {args.varz_addr}: {exc}",
            file=sys.stderr,
        )
        return 1
    payload = varz.get("programs")
    if not payload:
        print(
            "elasticdl programs: endpoint exposes no \"programs\" varz "
            "key (pre-observatory build?)",
            file=sys.stderr,
        )
        return 1
    if getattr(args, "json", False):
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        print(render_programs(payload))
    return 0
