"""`elasticdl slo`: SLO report from the master's /varz endpoint.

The master's SLO evaluator (common/slo.py) publishes its judgment —
per-SLO state, fast/slow burn rates, and the window evidence behind
them — inside Master.snapshot() under the "slo" key, which the
telemetry server republishes on /varz.  Like `elasticdl top` this is a
pure HTTP client; `render_slo` is also callable directly on a snapshot
dict so in-process tests (and bench.py) render the exact bytes the CLI
would print.
"""

from __future__ import annotations

import json
import sys

from elasticdl_tpu.client.top import fetch_varz

_STATE_MARK = {"ok": "OK", "breach": "BREACH", "no_data": "no-data"}


def render_slo(slo: dict) -> str:
    """One report frame from a Master.snapshot()["slo"] dict: a row per
    shipped SLO with state, current burn rates, and window evidence."""
    lines = [
        "elasticdl slo — evaluator ticks={ticks} breaches={breaches}".format(
            ticks=slo.get("ticks", 0),
            breaches=sum(
                1 for d in slo.get("decisions", [])
                if d.get("event") == "slo_breach"
            ),
        ),
        "slo".ljust(22) + "state".ljust(9) + "fast_burn".rjust(10)
        + "slow_burn".rjust(10) + "objective".rjust(11)
        + "target".rjust(8) + "windows".rjust(12),
    ]
    for row in slo.get("slos", []):
        state = row.get("state", "no_data")
        lines.append(
            str(row.get("slo", "?")).ljust(22)
            + _STATE_MARK.get(state, state).ljust(9)
            + f"{row.get('fast_burn', 0.0):.2f}".rjust(10)
            + f"{row.get('slow_burn', 0.0):.2f}".rjust(10)
            + f"{row.get('objective', 0.0):g}".rjust(11)
            + f"{row.get('target', 0.0):g}".rjust(8)
            + "{:.0f}s/{:.0f}s".format(
                row.get("fast_window_s", 0.0),
                row.get("slow_window_s", 0.0),
            ).rjust(12)
        )
    decisions = slo.get("decisions", [])
    if decisions:
        lines.append("")
        lines.append("transitions (oldest first):")
        for decision in decisions:
            lines.append(
                "  t{tick} {slo}: {event} fast_burn={fast} "
                "slow_burn={slow}".format(
                    tick=decision.get("tick", "?"),
                    slo=decision.get("slo", "?"),
                    event=decision.get("event", "?"),
                    fast=decision.get("fast_burn", 0.0),
                    slow=decision.get("slow_burn", 0.0),
                )
            )
    history = slo.get("history")
    if history:
        lines.append("")
        lines.append(
            "history: {series} series, {hist} histograms, "
            "{samples} samples (capacity {cap}/series)".format(
                series=history.get("series", 0),
                hist=history.get("histograms", 0),
                samples=history.get("samples", 0),
                cap=history.get("capacity", 0),
            )
        )
        if "stream_lag_samples" in history:
            # online (perpetual) jobs: the armed-watermark lag gauge is
            # part of the evaluator's evidence — show its coverage
            lines.append(
                "  stream lag: {n} samples "
                "(master_stream_watermark_lag_seconds)".format(
                    n=history.get("stream_lag_samples", 0),
                )
            )
    return "\n".join(lines)


def slo(args) -> int:
    """Fetch the master's /varz and render the SLO report."""
    try:
        varz = fetch_varz(args.master_varz)
    except Exception as exc:
        print(f"elasticdl slo: cannot scrape {args.master_varz}: {exc}",
              file=sys.stderr)
        return 1
    payload = varz.get("snapshot", {}).get("slo")
    if not payload:
        print(
            "elasticdl slo: master has no SLO evaluator — start it with "
            "--history_interval/--slo_interval > 0",
            file=sys.stderr,
        )
        return 1
    if getattr(args, "json", False):
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        print(render_slo(payload))
    return 0
