"""Feature-preprocessing layers.

Parity: reference elasticdl_preprocessing/layers/ (SURVEY.md C19): the same
layer set with the same semantics — feature engineering expressed as
composable layers so train and serve share code.  Host-facing layers
(strings) run in `feed` on numpy; numeric layers are jnp-traceable and can
also sit inside the jitted model.

Layers: Hashing, IndexLookup, Discretization, ToNumber, RoundIdentity,
LogRound, ConcatenateWithOffset, SparseEmbedding.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

try:  # jnp where available; every numeric layer also accepts numpy
    import jax.numpy as jnp
except ImportError:  # pragma: no cover
    jnp = np


def fnv1a_hash(value: str) -> int:
    """Stable 31-bit FNV-1a string hash (Python's hash() is per-process
    salted; feature hashing must agree across workers and across
    train/serve)."""
    h = 2166136261
    for byte in str(value).encode():
        h = ((h ^ byte) * 16777619) & 0xFFFFFFFF
    return h & 0x7FFFFFFF


_fnv1a = fnv1a_hash  # internal alias


class Hashing:
    """Hash strings/ints into [0, num_bins).  Stable across processes
    (FNV-1a, not Python's salted hash)."""

    def __init__(self, num_bins: int):
        if num_bins <= 0:
            raise ValueError("num_bins must be positive")
        self.num_bins = num_bins

    def __call__(self, x):
        arr = np.asarray(x)
        if arr.dtype.kind in ("U", "S", "O"):
            flat = np.array(
                [_fnv1a(v) % self.num_bins for v in arr.reshape(-1)],
                dtype=np.int32,
            )
            return flat.reshape(arr.shape)
        return (arr.astype(np.int64) % self.num_bins).astype(np.int32)


class IndexLookup:
    """Map vocabulary strings to indices; out-of-vocabulary -> num_oov
    buckets appended after the vocab (reference semantics: OOV id =
    len(vocabulary) when num_oov_indices == 1)."""

    def __init__(self, vocabulary: Sequence[str], num_oov_indices: int = 1):
        self.vocabulary = list(vocabulary)
        self.num_oov_indices = max(1, num_oov_indices)
        self._table = {v: i for i, v in enumerate(self.vocabulary)}

    @property
    def vocab_size(self) -> int:
        return len(self.vocabulary) + self.num_oov_indices

    def __call__(self, x):
        arr = np.asarray(x)

        def lookup(value):
            idx = self._table.get(str(value))
            if idx is not None:
                return idx
            oov = _fnv1a(str(value)) % self.num_oov_indices
            return len(self.vocabulary) + oov

        flat = np.array(
            [lookup(v) for v in arr.reshape(-1)], dtype=np.int32
        )
        return flat.reshape(arr.shape)


class Discretization:
    """Bucket floats by boundaries: x -> index in [0, len(bins)]."""

    def __init__(self, bin_boundaries: Sequence[float]):
        self.bin_boundaries = list(bin_boundaries)

    def __call__(self, x):
        boundaries = jnp.asarray(self.bin_boundaries)
        return jnp.searchsorted(
            boundaries, jnp.asarray(x, dtype=boundaries.dtype), side="right"
        ).astype(jnp.int32)


class ToNumber:
    """Strings -> numbers with a default for empty/unparseable values."""

    def __init__(self, out_type=np.float32, default_value=0):
        self.out_type = out_type
        self.default_value = default_value

    def __call__(self, x):
        arr = np.asarray(x)
        if arr.dtype.kind not in ("U", "S", "O"):
            return arr.astype(self.out_type)

        def convert(value):
            text = str(value).strip()
            if not text:
                return self.default_value
            try:
                return float(text)
            except ValueError:
                return self.default_value

        flat = np.array(
            [convert(v) for v in arr.reshape(-1)], dtype=self.out_type
        )
        return flat.reshape(arr.shape)


class RoundIdentity:
    """Round a numeric feature to an integer id, clipped to
    [0, max_value)."""

    def __init__(self, max_value: int):
        self.max_value = max_value

    def __call__(self, x):
        x = jnp.asarray(x, jnp.float32)
        return jnp.clip(
            jnp.round(x), 0, self.max_value - 1
        ).astype(jnp.int32)


class LogRound:
    """round(log_base(x)) as an id for power-law numerics, clipped to
    [0, max_value)."""

    def __init__(self, max_value: int, base: float = np.e):
        self.max_value = max_value
        self.base = base

    def __call__(self, x):
        x = jnp.asarray(x, jnp.float32)
        safe = jnp.maximum(x, 1.0)
        ids = jnp.round(jnp.log(safe) / np.log(self.base))
        return jnp.clip(ids, 0, self.max_value - 1).astype(jnp.int32)


class ConcatenateWithOffset:
    """Concatenate id columns, offsetting each so they index disjoint
    ranges of one shared embedding table."""

    def __init__(self, offsets: Sequence[int], axis: int = -1):
        self.offsets = list(offsets)
        self.axis = axis

    def __call__(self, inputs: List):
        if len(inputs) != len(self.offsets):
            raise ValueError(
                f"{len(inputs)} inputs vs {len(self.offsets)} offsets"
            )
        shifted = [
            jnp.asarray(x, jnp.int32) + offset
            for x, offset in zip(inputs, self.offsets)
        ]
        return jnp.concatenate(shifted, axis=self.axis)


def SparseEmbedding(input_dim: int, output_dim: int, combiner: str = "sum",
                    **kwargs):
    """Reference `SparseEmbedding` == bag-combining distributed embedding;
    alias over layers.DistributedEmbedding (table sharded on the mesh)."""
    from elasticdl_tpu.layers.embedding import DistributedEmbedding

    return DistributedEmbedding(
        input_dim=input_dim, output_dim=output_dim, combiner=combiner,
        **kwargs,
    )
