from elasticdl_tpu.preprocessing.layers import (  # noqa: F401
    ConcatenateWithOffset,
    Discretization,
    Hashing,
    IndexLookup,
    LogRound,
    RoundIdentity,
    SparseEmbedding,
    ToNumber,
)
