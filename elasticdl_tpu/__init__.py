"""elasticdl-tpu: a TPU-native elastic distributed training framework.

A from-scratch rebuild of the capabilities of ElasticDL
(workingloong/elasticdl) designed TPU-first:

- the reference's TF2-eager parameter-server and Horovod/NCCL AllReduce
  data-parallel paths are replaced by XLA-compiled JAX train steps whose
  gradients are reduced with mesh collectives over ICI;
- the gRPC parameter server (Python + Go/Eigen) is replaced by sharded
  on-device state: dense params via NamedSharding/pjit, sparse embedding
  tables sharded across the mesh with id-hash routing (shard_map);
- the Master's dynamic data-shard task dispatcher, shard-rerun fault
  tolerance, Kubernetes pod management, evaluation service and elastic
  rendezvous are preserved as a pure-Python gRPC control plane;
- checkpointing is Orbax (async, sharded, preemption-aware).

See SURVEY.md at the repo root for the component-by-component mapping.
"""

__version__ = "0.1.0"
