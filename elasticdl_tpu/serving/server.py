"""gRPC front-end for the serving subsystem (serving.proto).

ServingServicer translates between the wire (PredictRequest /
PredictResponse, raw-bytes tensors) and the batcher's
ServingResult — it holds NO serving logic beyond decode/encode, so the
in-process client (proto/service.py InProcessServingClient) and a real
socket exercise identical code.  Status rides in-band as ServingCode:
overload/shutdown are expected outcomes, not transport failures (see
serving.proto).
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import numpy as np

from elasticdl_tpu.common import events
from elasticdl_tpu.common import metrics as metrics_lib
from elasticdl_tpu.common import telemetry as telemetry_lib
from elasticdl_tpu.common.export import SINGLE_FEATURE_KEY
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.proto import serving_pb2 as spb
from elasticdl_tpu.serving import batcher as batcher_lib

logger = get_logger(__name__)

# ServingResult.code values coincide with the proto enum by construction
# (batcher.py) — asserted here so a drift in either is an import error,
# not a wrong status on the wire.
assert batcher_lib.OK == spb.SERVING_OK
assert batcher_lib.OVERLOADED == spb.SERVING_OVERLOADED
assert batcher_lib.SHUTTING_DOWN == spb.SERVING_SHUTTING_DOWN
assert batcher_lib.INVALID == spb.SERVING_INVALID
assert batcher_lib.INTERNAL == spb.SERVING_INTERNAL


def to_tensor_proto(arr: np.ndarray) -> spb.TensorProto:
    arr = np.ascontiguousarray(arr)
    return spb.TensorProto(
        dtype=str(arr.dtype),
        shape=list(arr.shape),
        data=arr.tobytes(),
    )


def from_tensor_proto(tp: spb.TensorProto) -> np.ndarray:
    """Decode a wire tensor; raises ValueError with a client-facing
    message on anything malformed (mapped to SERVING_INVALID)."""
    try:
        dtype = np.dtype(tp.dtype)
    except TypeError:
        raise ValueError(f"unknown tensor dtype {tp.dtype!r}")
    if dtype.hasobject:
        raise ValueError(f"object dtype {tp.dtype!r} is not servable")
    shape = tuple(int(d) for d in tp.shape)
    if any(d < 0 for d in shape):
        raise ValueError(f"negative dimension in shape {shape}")
    expected = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    if len(tp.data) != expected:
        raise ValueError(
            f"tensor data is {len(tp.data)} bytes but shape {shape} "
            f"dtype {dtype} needs {expected}"
        )
    return np.frombuffer(tp.data, dtype=dtype).reshape(shape)


def decode_features(request: spb.PredictRequest) -> dict:
    if not request.inputs:
        raise ValueError("request has no input tensors")
    features = {}
    for named in request.inputs:
        if not named.name:
            raise ValueError("input tensor with empty name")
        if named.name in features:
            raise ValueError(f"duplicate input tensor {named.name!r}")
        features[named.name] = from_tensor_proto(named.tensor)
    return features


def make_predict_request(features) -> spb.PredictRequest:
    """Client-side helper: dict of arrays (or one bare array, sent under
    the single-input key) -> PredictRequest."""
    if not isinstance(features, dict):
        features = {SINGLE_FEATURE_KEY: features}
    request = spb.PredictRequest()
    for name, arr in features.items():
        named = request.inputs.add()
        named.name = str(name)
        named.tensor.CopyFrom(to_tensor_proto(np.asarray(arr)))
    return request


class ServingServicer:
    """predict/health handlers; register with
    proto.service.add_serving_servicer_to_server or call directly via
    InProcessServingClient."""

    def __init__(self, engine, batcher, reloader=None,
                 request_timeout_s: float = 30.0):
        self._engine = engine
        self._batcher = batcher
        self._reloader = reloader
        self._request_timeout_s = request_timeout_s

    def predict(self, request, context) -> spb.PredictResponse:
        # Trace context: a non-empty request_id means the router sampled
        # this request in; it rides the batcher, stamps the span, and is
        # echoed on the response for client-side correlation.
        request_id = getattr(request, "request_id", "")
        try:
            features = decode_features(request)
        except ValueError as exc:
            if request_id:
                events.emit(
                    events.PREDICT_SPAN, request_id=request_id,
                    reason="invalid", code=int(spb.SERVING_INVALID),
                )
            return spb.PredictResponse(
                code=spb.SERVING_INVALID, error=str(exc),
                request_id=request_id,
            )
        rows = int(next(iter(features.values())).shape[0])
        result = self._batcher.submit(
            features, request_id=request_id
        ).result(timeout=self._request_timeout_s)
        clock = getattr(self._engine, "clock", None) or time.perf_counter
        encode_start = clock()
        response = spb.PredictResponse(
            code=result.code, error=result.error,
            model_step=result.model_step, request_id=request_id,
        )
        if result.predictions is not None:
            response.predictions.CopyFrom(
                to_tensor_proto(result.predictions)
            )
        respond_s = max(0.0, clock() - encode_start)
        self._batcher.metrics.record_phase("respond", respond_s)
        if request_id:
            phases = dict(result.phases_s or {})
            phases["respond"] = respond_s
            events.emit(
                events.PREDICT_SPAN, request_id=request_id,
                reason="sampled", code=int(result.code),
                model_step=int(result.model_step), rows=rows,
                phases_s=phases,
            )
        return response

    def health(self, request, context) -> spb.HealthResponse:
        response = spb.HealthResponse(
            serving=True,
            model_step=self._engine.step,
            buckets=list(self._engine.buckets),
            queue_depth=self._batcher.queue_depth,
            compile_count=self._engine.compile_count,
        )
        metrics = dict(self._batcher.metrics.snapshot())
        metrics["swap_count"] = float(self._engine.swap_count)
        # producer wall-time stamp of the served checkpoint (0.0 when
        # unknown) — rides the scalar-metric list so the fleet manager's
        # probe can trace end-to-end freshness without a proto change
        produced = getattr(self._engine, "produced_unix_s", None)
        if produced is not None:
            metrics["produced_unix_s"] = float(produced)
        if self._reloader is not None:
            metrics["reload_count"] = float(self._reloader.reload_count)
            metrics["reload_rejected"] = float(
                self._reloader.rejected_count
            )
        for name in sorted(metrics):
            m = response.metrics.add()
            m.name = name
            m.value = float(metrics[name])
        return response


class ServingServer:
    """Owns the grpc.Server plus the batcher/reloader lifecycle."""

    def __init__(self, engine, batcher, reloader=None, workers: int = 16,
                 request_timeout_s: float = 30.0,
                 telemetry_port: Optional[int] = 0):
        self._engine = engine
        self._batcher = batcher
        self._reloader = reloader
        self.servicer = ServingServicer(
            engine, batcher, reloader,
            request_timeout_s=request_timeout_s,
        )
        self._workers = workers
        self._server = None
        self.port: Optional[int] = None
        self._telemetry_port = telemetry_port
        self.telemetry: Optional[telemetry_lib.TelemetryServer] = None

    def telemetry_registries(self) -> list:
        """All registries this role exposes on /metrics: the process-wide
        default plus each per-component registry."""
        registries = [metrics_lib.default_registry()]
        registry = getattr(self._batcher, "metrics", None)
        if registry is not None:
            registries.append(registry.registry)
        engine_registry = getattr(self._engine, "metrics_registry", None)
        if engine_registry is not None:
            registries.append(engine_registry)
        if self._reloader is not None:
            registries.append(self._reloader.metrics_registry)
        return registries

    def _start_telemetry(self) -> None:
        if self._telemetry_port is None or self.telemetry is not None:
            return
        self.telemetry = telemetry_lib.TelemetryServer(
            registries=self.telemetry_registries(),
            role="serving",
            port=self._telemetry_port,
            healthz_fn=lambda: {
                "model_step": int(self._engine.step),
                "queue_depth": int(self._batcher.queue_depth),
            },
            varz_fn=lambda: {"grpc_port": self.port},
        )
        try:
            self.telemetry.start()
            logger.info("serving telemetry on port %d", self.telemetry.port)
        except Exception:
            logger.exception("telemetry server failed to start")
            self.telemetry = None

    def start(self, port: int = 0) -> int:
        """Bind (port 0 = ephemeral), start serving; returns the port."""
        import grpc
        from concurrent import futures as _futures

        from elasticdl_tpu.proto.service import (
            add_serving_servicer_to_server,
        )

        self._server = grpc.server(
            _futures.ThreadPoolExecutor(
                max_workers=self._workers,
                thread_name_prefix="serving-rpc",
            )
        )
        add_serving_servicer_to_server(self.servicer, self._server)
        self.port = self._server.add_insecure_port(f"[::]:{port}")
        if self.port == 0:
            raise RuntimeError(f"could not bind serving port {port}")
        if self._reloader is not None:
            self._reloader.start()
        self._server.start()
        self._start_telemetry()
        logger.info("serving on port %d", self.port)
        return self.port

    def stop(self, grace: float = 5.0) -> None:
        """Drain order: stop intake (gRPC), drain the batcher, stop the
        reloader — queued requests complete before the process exits."""
        if self._server is not None:
            self._server.stop(grace).wait()
            self._server = None
        self._batcher.shutdown()
        if self._reloader is not None:
            self._reloader.stop()
        if self.telemetry is not None:
            self.telemetry.stop()
            self.telemetry = None

    def wait(self) -> None:
        if self._server is not None:
            self._server.wait_for_termination()
