"""Zero-downtime checkpoint hot-reload for the serving engine.

A trainer keeps writing steps into its checkpoint directory; the
reloader watches that directory and swaps the serving engine onto newer
steps with the double-buffer discipline:

1. restore the candidate step into FRESH host buffers (the served
   variables are untouched — both generations coexist briefly);
2. gate on the integrity manifest (save_utils.verify_step) — a
   truncated or bit-flipped checkpoint never reaches the engine;
3. `engine.swap()` atomically republishes the reference.  In-flight
   batches finish on the generation they already read, so no request is
   dropped or served a half-loaded tree.

Any failure — injected (faults.POINT_SERVING_RELOAD), integrity, or a
real restore error — leaves the engine on its current params and is
counted in `rejected_count`; the SAME step is never retried (a corrupt
step stays corrupt; retrying would melt the poll loop), but newer steps
are still considered.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from elasticdl_tpu.common import events, faults
from elasticdl_tpu.common import metrics as metrics_lib
from elasticdl_tpu.common import save_utils
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.save_utils import CheckpointSaver
from elasticdl_tpu.worker.trainer import run_device_serialized

logger = get_logger(__name__)


class CheckpointReloader:
    def __init__(
        self,
        engine,
        checkpoint_dir: str,
        template: Any = None,
        poll_interval_s: float = 1.0,
    ):
        template = template if template is not None \
            else engine.state_template
        if template is None:
            raise ValueError(
                "reloader needs the abstract TrainState template the "
                "checkpoints restore into — build the engine with "
                "ServingEngine.from_checkpoint, or pass template= "
                "(serving/engine.py build_state_template)"
            )
        self._engine = engine
        self._template = template
        self._dir = checkpoint_dir
        self._saver = CheckpointSaver(checkpoint_dir, async_save=False)
        self._poll_interval_s = poll_interval_s
        self._rejected_steps = set()
        self.metrics_registry = metrics_lib.MetricsRegistry()
        self._reloads = self.metrics_registry.counter(
            "serving_reloads_total",
            "successful checkpoint hot-swaps onto the serving engine",
        )
        self._rejected = self.metrics_registry.counter(
            "serving_reloads_rejected_total",
            "hot-reload attempts rejected (integrity, restore, injected)",
        )
        self.last_error: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def check_once(self) -> bool:
        """One poll: swap to the newest verified step if it is newer than
        what the engine serves.  True on a successful swap."""
        self._saver.reload()
        latest = self._saver.latest_step()
        if latest is None or latest <= self._engine.step \
                or latest in self._rejected_steps:
            return False
        # Pin across the whole verify/restore/swap window: the trainer's
        # keep-last-K sweep (save_utils) must never delete the step this
        # swap is reading, however long the restore takes.
        save_utils.pin_step(self._dir, latest)
        try:
            faults.fire(faults.POINT_SERVING_RELOAD)
            if not self._saver.verify_step(latest):
                raise RuntimeError(
                    f"step {latest} failed integrity verification"
                )
            restored = run_device_serialized(
                self._saver.restore_step, latest, self._template
            )
            if restored is None:
                raise RuntimeError(f"step {latest} could not be restored")
            produced = self._saver.produced_meta(latest) or {}
            self._engine.swap(
                {**restored.params, **restored.model_state}, latest,
                produced_unix_s=produced.get("produced_unix_s"),
            )
        except Exception as exc:
            self._rejected_steps.add(latest)
            self._rejected.inc()
            self.last_error = str(exc)
            logger.warning(
                "hot-reload of step %d rejected (%s); still serving "
                "step %d", latest, exc, self._engine.step,
            )
            return False
        finally:
            save_utils.unpin_step(self._dir, latest)
        self._reloads.inc()
        self.last_error = None
        events.emit(events.SERVING_RELOADED, step=latest)
        return True

    @property
    def reload_count(self) -> int:
        return int(self._reloads.value())

    @property
    def rejected_count(self) -> int:
        return int(self._rejected.value())

    # ---- poll thread ----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="serving-reloader", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._poll_interval_s):
            try:
                self.check_once()
            except Exception:
                # the poll loop must survive anything — serving continues
                # on current params no matter what the watcher hits
                logger.exception("reloader poll failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        self._saver.close()
