"""Bucketed jit inference engine: the execution layer of online serving.

Design (docs/SERVING.md):

- **Precompiled buckets.**  Requests arrive at arbitrary batch sizes; XLA
  wants static shapes.  The engine compiles the forward pass once per
  configured bucket size at startup and pads every batch up to the
  nearest bucket, so no request ever triggers a compile on the hot path.
  `compile_count` counts traces of the jitted forward — the e2e test
  pins it `<= len(buckets)` to prove the no-recompile property.
- **Export mode.**  The forward is traced under
  `mesh_lib.export_mode()`, the same switch the SavedModel exporter
  uses: mesh-manual ops (ring attention, GPipe schedule, Pallas flash)
  fall back to their single-device lax formulations, so any zoo model —
  including ones trained with pipeline/sequence parallelism — serves on
  a plain CPU/TPU device with the identical param tree.
- **Atomic hot swap.**  `swap()` replaces the variables reference under
  a lock after validating tree structure/shape/dtype against the
  current set.  In-flight batches keep executing against the reference
  they already read — zero dropped requests across a reload (the
  reloader's contract, serving/reloader.py).
- **Serialized device execution.**  All device work funnels through
  `run_device_serialized` (worker/trainer.py): the virtual multi-device
  CPU backend used in tests corrupts state under concurrent execution,
  and real deployments lose nothing — a single accelerator executes one
  program at a time anyway.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np

from elasticdl_tpu.common import metrics as metrics_lib
from elasticdl_tpu.common import programs
from elasticdl_tpu.common.export import (
    SINGLE_FEATURE_KEY,
    feature_meta,
    load_exported,
    read_export_meta,
)
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.worker.trainer import (
    model_has_train_kwarg,
    run_device_serialized,
)

logger = get_logger(__name__)

DEFAULT_BUCKETS = (1, 4, 16, 64)


def _zeros_features(feature_spec: Dict[str, dict], rows: int) -> dict:
    return {
        name: np.zeros((rows, *leaf["shape"]), np.dtype(leaf["dtype"]))
        for name, leaf in feature_spec.items()
    }


def packed_leaf_spec(leaf: dict) -> Optional[dict]:
    """The uint24-packed wire variant of an integer id feature leaf, or
    None when the leaf has no packed form.  An int32/int64 feature of
    per-row shape (F,) may instead arrive as (F, 3) uint8 little-endian
    triples (data/wire.py pack_int_to_uint24) — 3 bytes/id on the
    request payload instead of 4.  Zoo models on the CTR record format
    auto-unpack inside the jitted forward (deepfm sparse_ids), so the
    engine only needs to ACCEPT the shape; it never converts."""
    if np.dtype(leaf["dtype"]) not in (np.dtype(np.int32),
                                       np.dtype(np.int64)):
        return None
    return {"shape": [*leaf["shape"], 3], "dtype": "uint8"}


def packed_feature_spec(feature_spec: Dict[str, dict]) -> Dict[str, dict]:
    """The signature a bandwidth-conscious Predict client should send:
    every integer id feature in its uint24-packed form, everything else
    native.  Serialize ids with data/wire.py `pack_int_to_uint24`."""
    return {
        name: packed_leaf_spec(leaf) or dict(leaf)
        for name, leaf in feature_spec.items()
    }


class ServingEngine:
    """Executes a model's forward pass over precompiled batch buckets.

    `feature_spec` is the export-meta signature ({name: {shape, dtype}},
    common/export.py); features passed to `predict` are always a dict
    keyed by it — models whose feed yields a bare array use the single
    reserved key (SINGLE_FEATURE_KEY) and the engine unpacks it before
    `model.apply`.
    """

    def __init__(
        self,
        model,
        variables: Dict[str, Any],
        step: int,
        feature_spec: Dict[str, dict],
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        precompile: bool = True,
        state_template: Any = None,
        produced_unix_s: Optional[float] = None,
        pad_to_bucket: bool = True,
    ):
        if not buckets or any(b <= 0 for b in buckets):
            raise ValueError(f"buckets must be positive: {buckets}")
        self._model = model
        self._variables = variables
        self._step = int(step)
        # wall time the producer stamped into the checkpoint manifest
        # (None for exports / pre-freshness checkpoints); rides the
        # Health RPC so the master can trace end-to-end staleness
        self._produced_unix_s = produced_unix_s
        self._feature_spec = dict(feature_spec)
        self._buckets = tuple(sorted(set(int(b) for b in buckets)))
        self._single = set(self._feature_spec) == {SINGLE_FEATURE_KEY}
        # storm-drill seam: disabling bucket padding makes every distinct
        # request size a fresh trace, driving the registered program past
        # its signature budget (tests only — production always pads)
        self._pad_to_bucket = bool(pad_to_bucket)
        self._has_train = model_has_train_kwarg(model)
        self._lock = threading.Lock()
        # phase-timing clock (docs/OBSERVABILITY.md "Request tracing");
        # public so deterministic tests can inject a fake
        self.clock = time.perf_counter
        # Per-instance registry (common/metrics.py): compile/swap counts
        # live ONLY here; the properties below and the Health RPC read
        # the same series the /metrics exposition renders.
        self.metrics_registry = metrics_lib.MetricsRegistry()
        self._compiles = self.metrics_registry.counter(
            "serving_engine_compiles_total",
            "traces of the jitted forward (== distinct compiled buckets)",
        )
        self._swaps = self.metrics_registry.counter(
            "serving_engine_swaps_total",
            "hot swaps of the served variables (checkpoint reloads)",
        )
        self.metrics_registry.gauge_fn(
            "serving_model_step", lambda: self.step,
            "training step of the currently served variables",
        )
        # kept for the reloader: the abstract TrainState this engine's
        # checkpoint restores into (None for export-loaded engines)
        self.state_template = state_template

        def forward(variables, feats):
            # trace-time side effect: runs once per compile, never on the
            # hot path — this IS the compile counter
            self._compiles.inc()
            x = feats[SINGLE_FEATURE_KEY] if self._single else feats
            kwargs = {"train": False} if self._has_train else {}
            with mesh_lib.export_mode():
                return self._model.apply(variables, x, **kwargs)

        # Registered program (common/programs.py): every bucket trace is
        # a recorded compile in the process-wide ledger, and the bucket
        # count IS the declared signature budget — one more distinct
        # shape than the buckets within the storm window means requests
        # are missing the buckets (a recompile storm).
        self._forward = programs.registered_jit(
            "serving_forward", forward,
            signature_budget=len(self._buckets),
        )
        if precompile:
            self.warmup()

    # ---- construction ---------------------------------------------------

    @classmethod
    def from_export(
        cls,
        export_dir: str,
        spec,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        sample_features: Any = None,
        precompile: bool = True,
    ) -> "ServingEngine":
        """Load a `params.msgpack` export (common/export.py).

        The serving signature comes from export_meta.json; passing
        `sample_features` additionally cross-checks the export's feature
        keys against the model actually being served (load_exported's
        drift guard)."""
        meta = read_export_meta(export_dir)
        feature_spec = meta.get("features")
        if feature_spec is None:
            if sample_features is None:
                raise ValueError(
                    f"export at {export_dir} predates feature signatures "
                    "(no 'features' in export_meta.json) — pass "
                    "sample_features to describe the model's inputs"
                )
            feature_spec = feature_meta(sample_features)
        elif sample_features is not None:
            # cross-check the served model's signature against the
            # export's BEFORE tracing model.init with it — a drifted
            # sample would otherwise fail inside the model with an
            # unrelated shape/attribute error
            load_exported(
                export_dir, template=None,
                expected_features=list(feature_meta(sample_features)),
                check_only=True,
            )
        sample = _zeros_features(feature_spec, rows=1)
        x = sample[SINGLE_FEATURE_KEY] \
            if set(feature_spec) == {SINGLE_FEATURE_KEY} else sample
        kwargs = {"train": False} if model_has_train_kwarg(spec.model) \
            else {}
        init_shapes = jax.eval_shape(
            lambda: spec.model.init(jax.random.PRNGKey(0), x, **kwargs)
        )
        init_shapes = dict(init_shapes)
        template = {
            "params": {"params": init_shapes.pop("params")},
            "model_state": init_shapes,
        }
        loaded = load_exported(
            export_dir, template,
            expected_features=list(feature_spec),
        )
        variables = {**loaded["params"], **loaded["model_state"]}
        return cls(
            spec.model, variables, step=int(meta.get("step", 0)),
            feature_spec=feature_spec, buckets=buckets,
            precompile=precompile,
        )

    @classmethod
    def from_checkpoint(
        cls,
        checkpoint_dir: str,
        spec,
        sample_features: Any,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        step: Optional[int] = None,
        precompile: bool = True,
        arena_convert: bool = False,
    ) -> "ServingEngine":
        """Serve straight from a training checkpoint directory
        (manifest-verified via CheckpointSaver; the optimizer state is
        restored as part of the TrainState and discarded).

        `arena_convert=True` lets a checkpoint whose arena storage
        dtype differs from the configured model's migrate on restore —
        e.g. serve an int8-trained checkpoint through an fp32 config
        (the export direction) or vice versa; without it a mismatch
        raises `ArenaDtypeMismatch` (save_utils)."""
        from elasticdl_tpu.common.save_utils import CheckpointSaver

        template = build_state_template(spec, sample_features)
        saver = CheckpointSaver(checkpoint_dir, async_save=False)
        try:
            if step is None:
                step = saver.latest_step()
            if step is None:
                raise ValueError(
                    f"no checkpoints found in {checkpoint_dir}"
                )
            restored = run_device_serialized(
                lambda: saver.restore_step(
                    step, template, arena_convert=arena_convert
                )
            )
            if restored is None:
                raise ValueError(
                    f"checkpoint step {step} in {checkpoint_dir} failed "
                    "integrity verification or does not exist"
                )
            produced = saver.produced_meta(step) or {}
        finally:
            saver.close()
        variables = {**restored.params, **restored.model_state}
        return cls(
            spec.model, variables, step=int(step),
            feature_spec=feature_meta(sample_features), buckets=buckets,
            precompile=precompile, state_template=template,
            produced_unix_s=produced.get("produced_unix_s"),
        )

    # ---- introspection --------------------------------------------------

    @property
    def buckets(self) -> Tuple[int, ...]:
        return self._buckets

    @property
    def max_bucket(self) -> int:
        return self._buckets[-1]

    @property
    def feature_spec(self) -> Dict[str, dict]:
        return dict(self._feature_spec)

    @property
    def compile_count(self) -> int:
        return int(self._compiles.value())

    @property
    def swap_count(self) -> int:
        return int(self._swaps.value())

    @property
    def step(self) -> int:
        with self._lock:
            return self._step

    @property
    def produced_unix_s(self) -> Optional[float]:
        """Producer wall-time stamp of the served checkpoint, or None."""
        with self._lock:
            return self._produced_unix_s

    def bucket_for(self, rows: int) -> Optional[int]:
        for b in self._buckets:
            if b >= rows:
                return b
        return None

    def validate(self, features: Dict[str, np.ndarray]) -> Optional[str]:
        """None when `features` matches the serving signature, else a
        client-facing error string (SERVING_INVALID).  Integer id
        features are accepted in EITHER the native form or the
        uint24-packed wire form (`packed_feature_spec`) — per feature,
        so a client may pack only its large id planes."""
        if not isinstance(features, dict):
            return "features must be a dict of named arrays"
        if set(features) != set(self._feature_spec):
            return (
                f"feature keys {sorted(map(str, features))} do not match "
                f"the model signature {sorted(self._feature_spec)}"
            )
        rows = None
        for name, leaf in self._feature_spec.items():
            arr = np.asarray(features[name])
            packed = packed_leaf_spec(leaf)

            def matches(spec):
                return (
                    arr.dtype == np.dtype(spec["dtype"])
                    and arr.ndim == 1 + len(spec["shape"])
                    and list(arr.shape[1:]) == list(spec["shape"])
                )

            if not matches(leaf) and not (packed and matches(packed)):
                accepted = (
                    f"(rows, {', '.join(map(str, leaf['shape']))}) "
                    f"{leaf['dtype']}"
                )
                if packed:
                    accepted += (
                        f" or uint24-packed (rows, "
                        f"{', '.join(map(str, packed['shape']))}) uint8"
                    )
                return (
                    f"feature '{name}' has shape {arr.shape} dtype "
                    f"{arr.dtype}, expected {accepted}"
                )
            if rows is None:
                rows = arr.shape[0]
            elif arr.shape[0] != rows:
                return (
                    "feature row counts disagree: "
                    f"'{name}' has {arr.shape[0]}, others have {rows}"
                )
        if not rows:
            return "empty request (0 rows)"
        return None

    # ---- execution ------------------------------------------------------

    def warmup(self) -> None:
        """Compile every bucket up front so no request pays a compile."""
        for b in self._buckets:
            self.predict(_zeros_features(self._feature_spec, b), b)
        logger.info(
            "serving engine warm: buckets=%s compiles=%d",
            self._buckets, self.compile_count,
        )

    def predict(
        self, features: Dict[str, np.ndarray], rows: int,
        phase_out: Optional[Dict[str, float]] = None,
    ) -> Tuple[np.ndarray, int]:
        """Run the forward pass on `rows` leading rows of `features`,
        padding up to the nearest bucket; returns (predictions, step).
        When `phase_out` is given it receives the engine-side phase
        durations {"pad", "compute", "unpack"} in seconds — the batcher
        folds them into per-request spans and the
        `serving_request_phase_seconds{phase}` histogram.

        Oversized batches are the batcher's job to split; this raises."""
        bucket = self.bucket_for(rows)
        if bucket is None:
            raise ValueError(
                f"batch of {rows} rows exceeds largest bucket "
                f"{self.max_bucket}"
            )
        if not self._pad_to_bucket:
            bucket = rows
        t0 = self.clock()
        padded = {}
        for name, arr in features.items():
            arr = np.asarray(arr)
            if arr.shape[0] != bucket:
                pad = np.zeros(
                    (bucket - arr.shape[0],) + arr.shape[1:], arr.dtype
                )
                arr = np.concatenate([arr, pad], axis=0)
            padded[name] = arr
        with self._lock:
            variables, step = self._variables, self._step
        t1 = self.clock()
        out = run_device_serialized(self._forward, variables, padded)
        t2 = self.clock()
        # host transfer + row slice: the dequant/unpack leg of the span
        result = np.asarray(out)[:rows]
        if phase_out is not None:
            t3 = self.clock()
            phase_out["pad"] = max(0.0, t1 - t0)
            phase_out["compute"] = max(0.0, t2 - t1)
            phase_out["unpack"] = max(0.0, t3 - t2)
        return result, step

    # ---- hot reload -----------------------------------------------------

    def swap(self, variables: Dict[str, Any], step: int,
             produced_unix_s: Optional[float] = None) -> None:
        """Atomically replace the served variables.  The new tree must
        match the current one in structure/shape/dtype — the jitted
        buckets were compiled against those avals, and a mismatch would
        force a recompile (or worse, wrong results) mid-traffic.
        `produced_unix_s` is the manifest's producer stamp (freshness
        tracing); None keeps no stamp for the new generation."""
        new_shapes = jax.eval_shape(lambda t: t, variables)
        # Check-and-set under one lock hold: reading self._variables for
        # the shape check outside it would let two concurrent swaps
        # validate against the same old tree (GL-LOCK).  eval_shape is
        # abstract — no device work happens in the critical section.
        with self._lock:
            old_shapes = jax.eval_shape(lambda t: t, self._variables)
            if old_shapes != new_shapes:
                raise ValueError(
                    "swap rejected: new variables do not match the "
                    "served tree (structure/shape/dtype drift); restart "
                    "serving with the new model instead of hot-swapping"
                )
            self._variables = variables
            self._step = int(step)
            self._produced_unix_s = produced_unix_s
        self._swaps.inc()
        logger.info("serving engine swapped to step %d", step)


def build_state_template(spec, sample_features) -> Any:
    """Abstract TrainState (ShapeDtypeStructs, no device work) matching
    what training checkpoints of this model contain — the restore target
    for checkpoint-backed serving and hot reload."""
    import jax.numpy as jnp

    from elasticdl_tpu.worker.trainer import TrainState

    features = jax.tree.map(np.asarray, sample_features)
    kwargs = {"train": False} if model_has_train_kwarg(spec.model) else {}

    def make():
        variables = dict(
            spec.model.init(jax.random.PRNGKey(0), features, **kwargs)
        )
        params = {"params": variables.pop("params")}
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=spec.optimizer.init(params),
            model_state=variables,
        )

    return jax.eval_shape(make)
