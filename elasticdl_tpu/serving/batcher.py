"""Dynamic micro-batcher: admission control + batch assembly for serving.

Requests land on a bounded row queue; a single dispatch thread gathers
them into the largest batch that fits a bucket, cutting either when
`max_batch` rows are ready or when the OLDEST queued request has waited
`max_latency_s` (latency cutoff beats fill: an idle service answers a
lone request within one deadline, never waiting for traffic that may not
come).  The engine pads the gathered rows to the nearest bucket, so the
batch-fill ratio (`rows / bucket`) is the efficiency metric — exported
through health and the serving bench.

Overload policy is shed-at-admission: when the queue is full the request
completes IMMEDIATELY with OVERLOADED instead of queueing into a
deadline it cannot meet.  Clients see an explicit in-band status
(serving.proto ServingCode) and can back off; latency of accepted
requests stays bounded.

Oversized requests (rows > largest bucket) are split into bucket-sized
chunks that ride the queue independently and re-assemble on completion —
or are rejected up front with INVALID when `reject_oversized` is set
(deployments that want clients to respect the contract).

Shutdown drains: queued requests complete, then later submissions get
SHUTTING_DOWN.
"""

from __future__ import annotations

import inspect
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

from elasticdl_tpu.common import metrics as metrics_lib
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)

# In-band status codes, value-for-value the serving.proto ServingCode
# enum (the proto module stays optional here: the batcher is usable —
# and unit-tested — without grpc/protobuf in the process).
OK = 0
OVERLOADED = 1
SHUTTING_DOWN = 2
INVALID = 3
INTERNAL = 4


@dataclass
class ServingResult:
    """What a submission resolves to; maps 1:1 onto PredictResponse."""

    code: int
    error: str = ""
    predictions: Optional[np.ndarray] = None
    model_step: int = 0
    # Trace context (docs/OBSERVABILITY.md "Request tracing"): the
    # request_id echoed from submit(), and per-phase durations
    # (queue_wait/batch_form/pad/compute/unpack) the span exporter and
    # the `serving_request_phase_seconds{phase}` histogram both read.
    request_id: str = ""
    phases_s: Optional[Dict[str, float]] = None


def _merge_phases(results) -> Optional[Dict[str, float]]:
    """Worst-case per-phase durations across split-request chunks — the
    chunk that waited longest is the one the caller experienced."""
    merged: Dict[str, float] = {}
    for r in results:
        for phase, seconds in (r.phases_s or {}).items():
            merged[phase] = max(merged.get(phase, 0.0), seconds)
    return merged or None


@dataclass
class _Item:
    features: Dict[str, np.ndarray]
    rows: int
    future: Future
    enqueued_at: float
    request_id: str = ""
    # for split oversized requests: (aggregate, chunk_index)
    aggregate: Optional["_Aggregate"] = None
    chunk_index: int = 0


@dataclass
class _Aggregate:
    """Re-assembles a split oversized request in chunk order."""

    future: Future
    pending: int
    chunks: list = field(default_factory=list)
    lock: threading.Lock = field(default_factory=threading.Lock)

    def complete_chunk(self, index: int, result: ServingResult) -> None:
        with self.lock:
            self.chunks.append((index, result))
            self.pending -= 1
            if self.pending > 0:
                return
            chunks = sorted(self.chunks)
        failed = [r for _, r in chunks if r.code != OK]
        if failed:
            self.future.set_result(failed[0])
            return
        self.future.set_result(ServingResult(
            code=OK,
            predictions=np.concatenate(
                [r.predictions for _, r in chunks], axis=0
            ),
            model_step=min(r.model_step for _, r in chunks),
            request_id=chunks[0][1].request_id,
            phases_s=_merge_phases(r for _, r in chunks),
        ))


def _resolved(code: int, error: str = "") -> Future:
    f = Future()
    f.set_result(ServingResult(code=code, error=error))
    return f


class BatcherMetrics:
    """Registry-backed serving metrics (common/metrics.py): the registry
    holds the only copy of every counter, and the Health RPC, the serving
    bench, and the /metrics exposition all read it.  `snapshot()` keeps
    its historical keys so existing consumers (tests, bench, health
    probers) are unaffected by the storage change.

    Per-instance registry: each batcher's numbers are its own (many
    engines/batchers coexist in one test process); the serving server
    composes this registry into its telemetry surface."""

    def __init__(self, registry: Optional[metrics_lib.MetricsRegistry] = None):
        self.registry = registry or metrics_lib.MetricsRegistry()
        self._rows = self.registry.counter(
            "serving_batch_rows_total",
            "rows served successfully, summed over executed batches",
        )
        self._batches = self.registry.counter(
            "serving_batches_total", "batches executed on the engine"
        )
        self._fill_sum = self.registry.counter(
            "serving_batch_fill_sum_total",
            "sum of per-batch fill fractions rows/bucket; divide by "
            "serving_batches_total for the mean fill ratio",
        )
        self._rejected = self.registry.counter(
            "serving_requests_rejected_total",
            "requests resolved without serving, by reason",
            labelnames=("reason",),
        )
        self.latency = self.registry.histogram(
            "serving_batch_latency_seconds",
            "enqueue-to-completion latency per request row group",
        )
        self.phase = self.registry.histogram(
            "serving_request_phase_seconds",
            "per-request serve-path phase latency "
            "(queue_wait/batch_form/pad/compute/unpack/respond)",
            labelnames=("phase",),
        )
        self.registry.gauge_fn(
            "serving_batch_fill_ratio",
            self._mean_fill,
            "mean batch fill fraction (served rows / bucket capacity)",
        )

    def _mean_fill(self) -> float:
        batches = self._batches.value()
        return self._fill_sum.value() / batches if batches else 0.0

    def record_batch(self, rows: int, bucket: int) -> None:
        self._batches.inc()
        self._rows.inc(rows)
        self._fill_sum.inc(rows / bucket)

    def record_shed(self) -> None:
        self._rejected.labels(reason="shed").inc()

    def record_invalid(self) -> None:
        self._rejected.labels(reason="invalid").inc()

    def record_internal(self) -> None:
        self._rejected.labels(reason="internal").inc()

    def record_phase(self, phase: str, seconds: float) -> None:
        self.phase.labels(phase=phase).record(max(0.0, seconds))

    def snapshot(self) -> dict:
        lat = self.latency.snapshot()
        queue_wait = self.phase.labels(phase="queue_wait").snapshot()
        compute = self.phase.labels(phase="compute").snapshot()
        return {
            # per-phase serve latency (docs/OBSERVABILITY.md "Request
            # tracing"): rides Health RPC scalars so `elasticdl top`'s
            # fleet table can show overload without a trace dump
            "phase_queue_wait_p99_s": queue_wait["p99_s"],
            "phase_compute_p99_s": compute["p99_s"],
            "ok_rows": self._rows.value(),
            "batches": self._batches.value(),
            "batch_fill_ratio": self._mean_fill(),
            "shed": self._rejected.labels(reason="shed").value(),
            "invalid": self._rejected.labels(reason="invalid").value(),
            "internal": self._rejected.labels(reason="internal").value(),
            "latency_p50_s": lat["p50_s"],
            "latency_p99_s": lat["p99_s"],
            "latency_mean_s": lat["mean_s"],
        }


class DynamicBatcher:
    def __init__(
        self,
        engine,
        max_latency_s: float = 0.01,
        max_batch: Optional[int] = None,
        max_queue_rows: Optional[int] = None,
        reject_oversized: bool = False,
        clock=time.monotonic,
    ):
        self._engine = engine
        self._max_latency_s = float(max_latency_s)
        self._max_batch = int(max_batch or engine.max_bucket)
        if self._max_batch > engine.max_bucket:
            raise ValueError(
                f"max_batch={self._max_batch} exceeds largest engine "
                f"bucket {engine.max_bucket}"
            )
        # default queue bound: a few full batches of headroom — deep
        # queues only convert overload into latency, never into goodput
        self._max_queue_rows = int(
            max_queue_rows if max_queue_rows is not None
            else 4 * self._max_batch
        )
        self._reject_oversized = reject_oversized
        self._clock = clock
        # engines predating the tracing contract (or test fakes) may not
        # accept phase_out=; probe once and skip phase capture for them
        try:
            params = inspect.signature(engine.predict).parameters
            self._engine_traces = "phase_out" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values()
            )
        except (TypeError, ValueError):
            self._engine_traces = False
        self.metrics = BatcherMetrics()
        self.metrics.registry.gauge_fn(
            "serving_queue_depth_rows",
            lambda: self.queue_depth,
            "rows currently waiting in the batcher queue",
        )
        self._queue: deque = deque()
        self._queued_rows = 0
        self._cond = threading.Condition()
        self._stopped = False
        self._thread = threading.Thread(
            target=self._dispatch_loop, name="serving-batcher", daemon=True
        )
        self._thread.start()

    # ---- submission -----------------------------------------------------

    @property
    def queue_depth(self) -> int:
        """Rows currently queued (health metric)."""
        with self._cond:
            return self._queued_rows

    def submit(self, features: Dict[str, np.ndarray],
               request_id: str = "") -> Future:
        """Returns a Future resolving to ServingResult.  Never raises and
        never blocks: invalid/overload/shutdown resolve immediately.
        `request_id` is the router-minted trace context; it is echoed on
        the result and stamped into the per-request span."""
        error = self._engine.validate(features)
        if error is not None:
            self.metrics.record_invalid()
            return _resolved(INVALID, error)
        rows = int(next(iter(features.values())).shape[0])
        if rows > self._max_batch:
            if self._reject_oversized:
                self.metrics.record_invalid()
                return _resolved(
                    INVALID,
                    f"request of {rows} rows exceeds the batch limit "
                    f"{self._max_batch} "
                    "(oversized requests are rejected by policy)",
                )
            return self._submit_split(features, rows, request_id)
        return self._enqueue(features, rows, request_id)

    def _submit_split(self, features, rows: int,
                      request_id: str = "") -> Future:
        chunk = self._max_batch
        n_chunks = (rows + chunk - 1) // chunk
        agg = _Aggregate(future=Future(), pending=n_chunks)
        # admission-check the WHOLE request before enqueuing any chunk:
        # partially admitting an oversized request sheds its own tail
        with self._cond:
            if self._stopped:
                return _resolved(SHUTTING_DOWN, "server is shutting down")
            if self._queued_rows + rows > self._max_queue_rows:
                self.metrics.record_shed()
                return _resolved(
                    OVERLOADED,
                    f"queue full ({self._queued_rows} rows queued)",
                )
            now = self._clock()
            for i in range(n_chunks):
                lo, hi = i * chunk, min((i + 1) * chunk, rows)
                part = {k: v[lo:hi] for k, v in features.items()}
                item = _Item(
                    features=part, rows=hi - lo, future=Future(),
                    enqueued_at=now, request_id=request_id,
                    aggregate=agg, chunk_index=i,
                )
                self._queue.append(item)
                self._queued_rows += item.rows
            self._cond.notify()
        return agg.future

    def _enqueue(self, features, rows: int, request_id: str = "") -> Future:
        with self._cond:
            if self._stopped:
                return _resolved(SHUTTING_DOWN, "server is shutting down")
            if self._queued_rows + rows > self._max_queue_rows:
                self.metrics.record_shed()
                return _resolved(
                    OVERLOADED,
                    f"queue full ({self._queued_rows} rows queued)",
                )
            item = _Item(
                features=features, rows=rows, future=Future(),
                enqueued_at=self._clock(), request_id=request_id,
            )
            self._queue.append(item)
            self._queued_rows += rows
            self._cond.notify()
            return item.future

    # ---- dispatch -------------------------------------------------------

    def _dispatch_loop(self) -> None:
        while True:
            batch = self._gather()
            if batch is None:
                return  # stopped and drained
            self._execute(batch)

    def _gather(self):
        """Block until a batch is due: max_batch rows ready, or the
        oldest request's latency deadline has passed, or shutdown."""
        with self._cond:
            while True:
                if self._queue:
                    deadline = (
                        self._queue[0].enqueued_at + self._max_latency_s
                    )
                    if (
                        self._queued_rows >= self._max_batch
                        or self._clock() >= deadline
                        or self._stopped  # draining: don't wait out
                    ):                    # deadlines nobody benefits from
                        return self._pop_batch()
                    self._cond.wait(
                        timeout=max(0.0, deadline - self._clock())
                    )
                elif self._stopped:
                    return None
                else:
                    self._cond.wait()

    def _pop_batch(self):
        """Called under the lock: pop queued items that fit max_batch."""
        batch, rows = [], 0
        while self._queue and rows + self._queue[0].rows <= self._max_batch:
            item = self._queue.popleft()
            rows += item.rows
            batch.append(item)
        self._queued_rows -= rows
        return batch

    def _execute(self, batch) -> None:
        # Packed-payload clients (engine.packed_feature_spec ships id
        # planes as uint24 triples) may share the queue with native
        # ones; differently-shaped arrays can't concatenate, so run one
        # engine call per run of same-form items (arrival order kept).
        def form(item):
            return tuple(
                (k, np.asarray(item.features[k]).dtype.str,
                 np.asarray(item.features[k]).ndim)
                for k in sorted(item.features)
            )

        groups = []
        for item in batch:
            f = form(item)
            if groups and groups[-1][0] == f:
                groups[-1][1].append(item)
            else:
                groups.append((f, [item]))
        for _, group in groups:
            self._execute_uniform(group)

    def _execute_uniform(self, batch) -> None:
        rows = sum(item.rows for item in batch)
        # phase clock starts when the batch is cut: queue_wait ends
        # here, batch_form covers assembly, pad/compute/unpack come
        # back from the engine (docs/OBSERVABILITY.md "Request tracing")
        popped_at = self._clock()
        queue_waits = {
            id(item): max(0.0, popped_at - item.enqueued_at)
            for item in batch
        }
        for wait in queue_waits.values():
            self.metrics.record_phase("queue_wait", wait)
        features = {
            k: np.concatenate(
                [np.asarray(item.features[k]) for item in batch], axis=0
            )
            for k in batch[0].features
        }
        batch_form_s = max(0.0, self._clock() - popped_at)
        self.metrics.record_phase("batch_form", batch_form_s)
        engine_phases: Dict[str, float] = {}

        def item_phases(item):
            phases = {"queue_wait": queue_waits[id(item)],
                      "batch_form": batch_form_s}
            phases.update(engine_phases)
            return phases

        try:
            if self._engine_traces:
                preds, step = self._engine.predict(
                    features, rows, phase_out=engine_phases
                )
            else:
                preds, step = self._engine.predict(features, rows)
        except Exception as exc:  # engine failure: fail THIS batch only
            logger.exception("serving batch execution failed")
            self.metrics.record_internal()
            for item in batch:
                self._finish(item, ServingResult(
                    code=INTERNAL, error=f"execution failed: {exc}",
                    request_id=item.request_id,
                    phases_s=item_phases(item),
                ))
            return
        for phase, seconds in engine_phases.items():
            self.metrics.record_phase(phase, seconds)
        bucket = self._engine.bucket_for(rows)
        self.metrics.record_batch(rows, bucket)
        now = self._clock()
        offset = 0
        for item in batch:
            self.metrics.latency.record(max(0.0, now - item.enqueued_at))
            self._finish(item, ServingResult(
                code=OK,
                predictions=preds[offset:offset + item.rows],
                model_step=step,
                request_id=item.request_id,
                phases_s=item_phases(item),
            ))
            offset += item.rows

    @staticmethod
    def _finish(item: _Item, result: ServingResult) -> None:
        if item.aggregate is not None:
            item.aggregate.complete_chunk(item.chunk_index, result)
        else:
            item.future.set_result(result)

    # ---- lifecycle ------------------------------------------------------

    def shutdown(self, timeout: Optional[float] = 30.0) -> None:
        """Stop accepting work, drain everything queued, stop the
        dispatch thread.  Idempotent."""
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout)
