"""TPU-native online serving: bucketed jit engine, dynamic micro-batcher,
gRPC front-end, zero-downtime checkpoint hot-reload.  See docs/SERVING.md.

Import the submodules directly (`serving.engine`, `serving.batcher`,
`serving.server`, `serving.reloader`) — this package init stays
import-light so the batcher can be unit-tested without grpc/protobuf.
"""
