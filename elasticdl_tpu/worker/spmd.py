"""Multi-process SPMD worker: one global mesh, one model, one train step.

This is the cluster-mode replacement for BOTH reference topologies
(SURVEY.md §3.3 PS mode, §3.4 Horovod AllReduce): instead of N workers
training private replicas synchronized through a parameter server or an
allreduce ring, every process joins a single `jax.distributed` runtime,
the devices form one global `Mesh`, and all ranks enter the SAME jitted
collective train step per global batch — XLA emits the gradient reduction
over ICI/DCN from the shardings.  Consistency is by construction: there is
only one logical computation, so no rank can diverge.

Task flow (the part the reference's design survives intact): the master
still owns the shard queue; ranks fetch the group-synchronized assignment
for (epoch, seq) via get_spmd_task (master/spmd_assigner.py) so everyone
trains the same shard in the same order.  Each rank reads the whole shard
from shared storage and builds the full global batch host-side; only the
locally-addressable slice is transferred to devices
(mesh.make_global_batch).  Rank 0 alone reports task completion and
model versions.

Elasticity: a membership change bumps the rendezvous epoch; get_spmd_task
answers `epoch_stale`, every rank tears down and re-initialises
jax.distributed for the new topology, restores state from the latest
checkpoint (Orbax handles cross-topology resharding) and resumes at
seq=0 — the task queue re-leases whatever the old group held, so no
step-exact replay is needed (SURVEY.md §7 hard part 1).
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import numpy as np

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.model_handler import ModelSpec
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.worker.task_data_service import TaskDataService
from elasticdl_tpu.worker.trainer import Trainer

logger = get_logger(__name__)


class SPMDWorker:
    """One rank of a multi-process SPMD training job."""

    def __init__(
        self,
        worker_id: int,
        master_client,
        data_reader,
        spec: ModelSpec,
        minibatch_size: int = 64,  # GLOBAL batch size
        process_id: int = 0,
        num_processes: int = 1,
        coordinator_address: str = "",
        use_bf16: bool = False,
        seed: int = 0,
        checkpoint_saver=None,
        checkpoint_steps: int = 0,
        wait_sleep_s: float = 0.2,
        initial_epoch: int = 0,
    ):
        self.worker_id = worker_id
        self.spec = spec
        self.minibatch_size = minibatch_size
        self.process_id = process_id
        self.num_processes = num_processes
        self._coordinator = coordinator_address
        self._client = master_client
        self._data_service = TaskDataService(
            master_client, data_reader, worker_id
        )
        self._reader = data_reader
        self._use_bf16 = use_bf16
        self._seed = seed
        self._saver = checkpoint_saver
        self._checkpoint_steps = checkpoint_steps
        self._wait_sleep_s = wait_sleep_s
        self._epoch = initial_epoch
        self.state = None
        self.trainer: Optional[Trainer] = None
        self.mesh = None
        self.last_loss = None
        self.remesh_count = 0

    # ---- runtime lifecycle --------------------------------------------

    def setup(self) -> None:
        """Join the distributed runtime and build the global mesh."""
        if self.num_processes > 1 and not jax.distributed.is_initialized():
            jax.distributed.initialize(
                coordinator_address=self._coordinator,
                num_processes=self.num_processes,
                process_id=self.process_id,
            )
        self.mesh = mesh_lib.create_mesh(jax.devices())
        self.trainer = Trainer(
            model=self.spec.model,
            optimizer=self.spec.optimizer,
            loss_fn=self.spec.loss,
            mesh=self.mesh,
            use_bf16=self._use_bf16,
            param_sharding_fn=self.spec.param_sharding,
        )
        logger.info(
            "SPMD rank %d/%d up: %d global devices, mesh %s",
            self.process_id, self.num_processes,
            len(jax.devices()), dict(self.mesh.shape),
        )

    def _ensure_state(self, batch) -> None:
        if self.state is not None:
            return
        self.state = self.trainer.init_state_global(
            jax.random.PRNGKey(self._seed), batch["features"]
        )
        if self._saver is not None:
            restored = self._saver.maybe_restore(self.state)
            if restored is not None:
                self.state = restored
                logger.info(
                    "Rank %d restored checkpoint at step %d",
                    self.process_id, int(self.state.step),
                )

    @property
    def is_leader(self) -> bool:
        return self.process_id == 0

    # ---- main loop -----------------------------------------------------

    def run(self) -> bool:
        if self.trainer is None:
            self.setup()
        seq = 0
        while True:
            try:
                resp = self._client.get_spmd_task(
                    pb.GetSpmdTaskRequest(
                        worker_id=self.worker_id,
                        rendezvous_id=self._epoch,
                        seq=seq,
                    )
                )
            except Exception as exc:
                logger.warning("get_spmd_task failed: %s; retrying", exc)
                time.sleep(self._wait_sleep_s)
                continue
            if resp.job_finished:
                logger.info(
                    "Job finished; SPMD rank %d exiting", self.process_id
                )
                return True
            if resp.epoch_stale:
                logger.info(
                    "Rank %d: epoch %d stale; re-rendezvous",
                    self.process_id, self._epoch,
                )
                if not self._re_rendezvous():
                    return False
                seq = 0
                continue
            task = resp.task
            if task.task_id < 0 or task.type == pb.WAIT:
                time.sleep(self._wait_sleep_s)
                continue
            self._process_task(task)
            seq += 1

    def _process_task(self, task: pb.Task) -> None:
        # No per-rank failure reporting: if any rank's collective step
        # dies the whole group is wedged and recovery is the elastic
        # epoch-bump path, not a task retry.
        if task.type == pb.TRAINING:
            records = self._train_task(task)
            if self.is_leader:
                self._data_service.report_task(task, records=records)
                try:
                    self._client.report_version(
                        pb.ReportVersionRequest(
                            worker_id=self.worker_id,
                            model_version=int(self.state.step),
                        )
                    )
                except Exception:
                    pass
        elif task.type == pb.EVALUATION:
            if not self._has_trained_state():
                # Same guard as Worker._evaluate_task: never report metrics
                # from randomly initialised params.  The condition is
                # deterministic across ranks (state/step identical), so all
                # ranks skip together; the leader re-queues the task.
                if self.is_leader:
                    self._data_service.report_task(
                        task,
                        err="no trained state for evaluation",
                        transient=True,
                    )
                return
            records = self._evaluate_task(task)
            if self.is_leader:
                self._data_service.report_task(task, records=records)
        elif task.type == pb.PREDICTION:
            records = self._predict_task(task)
            if self.is_leader:
                self._data_service.report_task(task, records=records)
        elif task.type == pb.SAVE_MODEL:
            self._save(force=True)
            if self.is_leader:
                from elasticdl_tpu.worker.worker import export_for_task

                # Params are replicated => fully addressable on every
                # host; the leader alone writes the export.  No trained
                # state (deterministic across ranks) => report failure so
                # the task re-queues instead of silently skipping.
                try:
                    export_for_task(self.state, self.spec, task)
                except RuntimeError as exc:
                    self._data_service.report_task(task, err=str(exc))
                else:
                    self._data_service.report_task(task, records=0)
        else:
            logger.warning("SPMD worker ignoring task type %s", task.type)
            if self.is_leader:
                self._data_service.report_task(task, records=0)

    def _train_task(self, task: pb.Task) -> int:
        records = 0
        for batch, real in self._data_service.batches_for_task(
            task, self.minibatch_size, self._feed
        ):
            self._ensure_state(batch)
            global_batch = mesh_lib.make_global_batch(batch, self.mesh)
            self.state, loss = self.trainer.train_on_global_batch(
                self.state, global_batch
            )
            self.last_loss = loss
            records += real
            self._maybe_checkpoint()
        return records

    def _evaluate_task(self, task: pb.Task) -> int:
        from elasticdl_tpu.worker.sync import state_at_version

        records = 0
        all_labels, all_preds = [], []
        eval_state, actual_version = None, None
        for batch, real in self._data_service.batches_for_task(
            task, self.minibatch_size, self._feed
        ):
            self._ensure_state(batch)
            if actual_version is None:
                # Deterministic across ranks (same state/saver contents),
                # so every rank restores — or falls back — together.
                eval_state, actual_version = state_at_version(
                    self.state, self._saver, task.model_version
                )
            features = mesh_lib.make_global_batch(
                batch["features"], self.mesh
            )
            preds = self.trainer.predict_on_global_batch(
                eval_state, features
            )
            # Data-sharded output: gather the full array onto every host
            # so metric fns (host-side, e.g. AUC) see all rows.
            preds = _allgather(preds)
            all_labels.append(np.asarray(batch["labels"])[:real])
            all_preds.append(np.asarray(preds)[:real])
            records += real
        if records and self.is_leader:
            labels = np.concatenate(all_labels)
            preds = np.concatenate(all_preds)
            req = pb.ReportEvaluationMetricsRequest(
                worker_id=self.worker_id,
                model_version=actual_version
                if actual_version is not None and actual_version >= 0
                else int(self.state.step),
                num_examples=records,
            )
            for name, fn in self.spec.eval_metrics.items():
                req.metrics[name] = float(fn(labels, preds))
            self._client.report_evaluation_metrics(req)
        return records

    def _predict_task(self, task: pb.Task) -> int:
        records = 0
        self.predictions = getattr(self, "predictions", [])
        for batch, real in self._data_service.batches_for_task(
            task, self.minibatch_size, self._feed
        ):
            self._ensure_state(batch)
            features = mesh_lib.make_global_batch(
                batch["features"], self.mesh
            )
            preds = _allgather(
                self.trainer.predict_on_global_batch(self.state, features)
            )
            self.predictions.append(np.asarray(preds)[:real])
            records += real
        return records

    def _has_trained_state(self) -> bool:
        if self.state is not None and int(self.state.step) > 0:
            return True
        return (
            self._saver is not None
            and self._saver.latest_step() is not None
        )

    # ---- elasticity ----------------------------------------------------

    def _re_rendezvous(self) -> bool:
        """Membership changed: rejoin with the new topology and restore
        state from the latest checkpoint."""
        spec = self._client.get_cluster_spec(
            pb.GetClusterSpecRequest(
                worker_id=self.worker_id, known_rendezvous_id=self._epoch
            )
        )
        me = next(
            (w for w in spec.workers if w.worker_id == self.worker_id), None
        )
        if me is None or spec.world_size == 0:
            logger.warning(
                "Worker %d evicted at epoch %d; exiting",
                self.worker_id, spec.rendezvous_id,
            )
            return False
        self._epoch = spec.rendezvous_id
        if jax.distributed.is_initialized():
            jax.distributed.shutdown()
        self.process_id = me.rank
        self.num_processes = spec.world_size
        self._coordinator = spec.coordinator_address or self._coordinator
        self.state = None  # re-init + checkpoint restore on next batch
        self.setup()
        self.remesh_count += 1
        return True

    # ---- helpers -------------------------------------------------------

    def save_checkpoint_and_flush(self) -> None:
        """Synchronous final checkpoint (preemption hook: the process is
        about to die, so wait for the write to land)."""
        self._save(force=True)
        if self._saver is not None:
            self._saver.wait_until_finished()

    def _save(self, force: bool = False) -> None:
        # Orbax distributed save: EVERY rank participates (each writes its
        # addressable shards); the decision is deterministic on step so all
        # ranks enter together.
        if self._saver is not None and self.state is not None:
            self._saver.save(self.state, force=force)

    def _maybe_checkpoint(self) -> None:
        if (
            self._saver is not None
            and self._checkpoint_steps
            and int(self.state.step) % self._checkpoint_steps == 0
        ):
            self._saver.save(self.state)

    def _feed(self, records):
        return self.spec.feed(records, getattr(self._reader, "metadata", {}))


def _allgather(x):
    """Full-array gather onto every host (jax multihost utils; no-op in
    single-process mode)."""
    if jax.process_count() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(x, tiled=True)
