"""Multi-process SPMD worker: one global mesh, one model, one train step.

This is the cluster-mode replacement for BOTH reference topologies
(SURVEY.md §3.3 PS mode, §3.4 Horovod AllReduce): instead of N workers
training private replicas synchronized through a parameter server or an
allreduce ring, every process joins a single `jax.distributed` runtime,
the devices form one global `Mesh`, and all ranks enter the SAME jitted
collective train step per global batch — XLA emits the gradient reduction
over ICI/DCN from the shardings.  Consistency is by construction: there is
only one logical computation, so no rank can diverge.

Task flow (the part the reference's design survives intact): the master
still owns the shard queue; ranks fetch the group-synchronized assignment
for (epoch, seq) via get_spmd_task (master/spmd_assigner.py) so everyone
trains the same shard in the same order.  Each rank reads the whole shard
from shared storage and builds the full global batch host-side; only the
locally-addressable slice is transferred to devices
(mesh.make_global_batch).  Rank 0 alone reports task completion and
model versions.

Elasticity: a membership change bumps the rendezvous epoch; get_spmd_task
answers `epoch_stale`, every rank tears down and re-initialises
jax.distributed for the new topology, restores state from the latest
checkpoint (Orbax handles cross-topology resharding) and resumes at
seq=0 — the task queue re-leases whatever the old group held, so no
step-exact replay is needed (SURVEY.md §7 hard part 1).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

import jax
import numpy as np

from elasticdl_tpu.common import metrics as metrics_lib
from elasticdl_tpu.common import profiler as profiler_lib
from elasticdl_tpu.common import programs as programs_lib
from elasticdl_tpu.common import resilience
from elasticdl_tpu.common.jax_compat import distributed_is_initialized
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.model_handler import ModelSpec, resolve_wire_format
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.worker.task_data_service import TaskDataService
from elasticdl_tpu.worker.trainer import Trainer

logger = get_logger(__name__)

# Step-phase attribution: shares the labeled histogram FAMILY with the
# threaded worker (default_registry get-or-create), but keeps its own
# timer — SPMD cluster mode runs one rank per process, so per-process
# totals are per-rank totals.  Module-level for __new__ scaffolding.
_phase_timer = profiler_lib.PhaseTimer(
    histogram=metrics_lib.default_registry().histogram(
        "worker_step_phase_seconds",
        "per-step wall time attributed to a phase "
        "(profiler.STEP_PHASES)",
        labelnames=("phase",),
    )
)


def wait_for_confirmed_epoch(
    client,
    worker_id: int,
    poll_s: float = 0.5,
    timeout_s: Optional[float] = None,
    rpc_policy: Optional[resilience.RetryPolicy] = None,
):
    """Block until this worker is a member of a SETTLED and GROUP-CONFIRMED
    epoch; returns (cluster_spec, my_worker_spec), or (None, None) on
    timeout.

    Three gates, in order:
    1. membership — I appear in the spec;
    2. settled — world_size matches the pod manager's published target
       (expected_world_size), NOT the static --num_workers flag (which
       would deadlock replacements after scale-down/budget exhaustion);
       with no published target (unmanaged rendezvous), any nonzero world
       counts as settled;
    3. confirmed — every member's MAIN thread has confirmed this exact
       epoch.  This is the anti-cascade barrier: a rank wedged in a
       collective with a dead peer cannot confirm, so nobody initializes
       a mesh containing it; its watchdog restarts it, the epoch moves,
       and the survivors re-confirm the new epoch.  Without the barrier,
       staggered deaths bump the epoch faster than replacements can boot
       and every joiner suicides on arrival (observed live in
       tests/test_elastic_cluster.py's first iterations).
    """
    import time as _time

    from elasticdl_tpu.proto import elasticdl_pb2 as pb

    if rpc_policy is None:
        rpc_policy = resilience.default_policy()
    deadline = None if timeout_s is None else _time.time() + timeout_s
    confirm = 0
    while True:
        # Each poll gets the full per-call retry budget; a master that
        # stays dead past it raises RetryBudgetExhausted out of the wait
        # (worker/main.py turns that into exit code 45).
        spec = rpc_policy.call(
            lambda: client.get_cluster_spec(
                pb.GetClusterSpecRequest(
                    worker_id=worker_id, confirm_epoch=confirm
                )
            ),
            description="get_cluster_spec",
        )
        me = next(
            (w for w in spec.workers if w.worker_id == worker_id), None
        )
        settled = me is not None and (
            spec.world_size == spec.expected_world_size
            or (spec.expected_world_size == 0 and spec.world_size > 0)
        )
        if settled and spec.all_confirmed and confirm == spec.rendezvous_id:
            return spec, me
        # (re-)confirm whatever epoch we currently observe; recorded on
        # the NEXT poll
        confirm = spec.rendezvous_id if settled else 0
        if deadline is not None and _time.time() > deadline:
            return None, None
        _time.sleep(poll_s)


class SPMDWorker:
    # class-level defaults (same rationale as Worker: bare __new__
    # construction in tests)
    wire_format = "plain"
    compact_wire = False

    """One rank of a multi-process SPMD training job."""

    def __init__(
        self,
        worker_id: int,
        master_client,
        data_reader,
        spec: ModelSpec,
        minibatch_size: int = 64,  # GLOBAL batch size
        process_id: int = 0,
        num_processes: int = 1,
        coordinator_address: str = "",
        use_bf16: bool = False,
        seed: int = 0,
        checkpoint_saver=None,
        checkpoint_saver_factory=None,
        checkpoint_steps: int = 0,
        wait_sleep_s: float = 0.2,
        initial_epoch: int = 0,
        wedge_grace_s: float = 20.0,
        output_dir: str = "",
        tensorboard_dir: str = "",
        profile_dir: str = "",
        steps_per_execution: int = 1,
        compact_wire: bool = False,
        wire_format: str = "",
        rpc_policy: Optional[resilience.RetryPolicy] = None,
    ):
        self.worker_id = worker_id
        # One policy for every control-plane RPC this rank makes; budget
        # exhaustion propagates to worker/main.py -> exit code 45.
        self._rpc_policy = (
            rpc_policy if rpc_policy is not None
            else resilience.default_policy()
        )
        self.spec = spec
        self.minibatch_size = minibatch_size
        # --wire_format / --compact_wire (same contract as Worker), with
        # one SPMD restriction: the dedup format's padded shapes are
        # governed by each rank's OWN sticky packer caps, which can grow
        # at different steps on different ranks — a collective program
        # shape mismatch.  Degrade dedup to the compact format here.
        if (wire_format or "").strip().lower() == "dedup":
            logger.warning(
                "--wire_format=dedup is not supported under SPMD "
                "slice-local reads (per-rank dedup caps diverge); "
                "using the compact wire format instead"
            )
            wire_format = "compact"
        self.wire_format = resolve_wire_format(
            spec, wire_format, compact_wire, logger
        )
        self.compact_wire = self.wire_format == "compact"
        # >1 dispatches that many collective train steps as one jitted
        # scan over a global (K, B, ...) batch stack (deterministic
        # grouping — identical on every rank)
        self.steps_per_execution = max(1, int(steps_per_execution))
        self.process_id = process_id
        self.num_processes = num_processes
        self._coordinator = coordinator_address
        self._client = master_client
        self._data_service = TaskDataService(
            master_client, data_reader, worker_id
        )
        self._data_service.phase_timer = _phase_timer
        self._reader = data_reader
        self._use_bf16 = use_bf16
        self._seed = seed
        self._saver = checkpoint_saver
        # Orbax construction touches the XLA backend, which must not
        # happen before jax.distributed.initialize — multi-process callers
        # pass a FACTORY and the saver is built in setup(), after init.
        self._saver_factory = checkpoint_saver_factory
        self._checkpoint_steps = checkpoint_steps
        self._wait_sleep_s = wait_sleep_s
        self._epoch = initial_epoch
        self.state = None
        self.trainer: Optional[Trainer] = None
        self.mesh = None
        self.last_loss = None
        self.remesh_count = 0
        self._preempted = False
        self._output_dir = output_dir
        self._recovery_t0: Optional[float] = None
        self._wedge_grace_s = wedge_grace_s
        self._epoch_stale_since: Optional[float] = None
        self._watchdog_started = False
        # Set while the MAIN thread is in the confirmation-barrier poll
        # loop: it is then provably live and epoch-aware, so the watchdog
        # must not shoot it for lagging the epoch.
        self._in_rendezvous_wait = False
        # Leader-only observability: ONE rank writes scalars (every rank
        # holds identical state/loss by construction).
        from elasticdl_tpu.common.profiler import StepTimer
        from elasticdl_tpu.common.summary import SummaryWriter

        self.step_timer = StepTimer()
        # cost x rate join for the live MFU/bandwidth gauges — each rank
        # binds its own process's registry (per-process /metrics)
        programs_lib.default_program_registry().bind_step_rate(
            "worker_train_step_many"
            if self.steps_per_execution > 1 else "worker_train_step",
            lambda: self.step_timer.steps_per_sec,
            steps_per_execution=self.steps_per_execution,
        )
        self._summary = SummaryWriter(
            tensorboard_dir if (tensorboard_dir and process_id == 0) else None
        )
        # one-shot device trace of the first training task (every rank
        # writes its own subdir — in SPMD each process only sees its
        # addressable devices)
        self._profile_dir = profile_dir
        self._profiled = False

    # ---- runtime lifecycle --------------------------------------------

    # jax.distributed.initialize's default 300s join deadline is far too
    # long for an elastic group: a rank that entered initialize with a
    # stale epoch would anchor the whole recovery cascade on it.  The
    # watchdog (started BEFORE initialize) normally restarts such a rank
    # within the grace window; this cap is the backstop.
    INIT_TIMEOUT_S = 60

    def setup(self) -> None:
        """Join the distributed runtime and build the global mesh."""
        if self.num_processes > 1 and not self._watchdog_started:
            # Must start before initialize(): a rank blocked in
            # RegisterTask against a coordinator of a newer epoch can only
            # be saved by the watchdog restarting the process.
            self._watchdog_started = True
            threading.Thread(target=self._watchdog, daemon=True).start()
        if self.num_processes > 1 and not distributed_is_initialized():
            jax.distributed.initialize(
                coordinator_address=self._coordinator,
                num_processes=self.num_processes,
                process_id=self.process_id,
                initialization_timeout=self.INIT_TIMEOUT_S,
            )
        if self._saver is None and self._saver_factory is not None:
            self._saver = self._saver_factory()
        self.mesh = mesh_lib.create_mesh(jax.devices())
        self.trainer = Trainer(
            model=self.spec.model,
            optimizer=self.spec.optimizer,
            loss_fn=self.spec.loss,
            mesh=self.mesh,
            use_bf16=self._use_bf16,
            param_sharding_fn=self.spec.param_sharding,
        )
        # compute / h2d-adjacent dispatch time lands in the phase timer
        self.trainer.phase_timer = _phase_timer
        logger.info(
            "SPMD rank %d/%d up: %d global devices, mesh %s",
            self.process_id, self.num_processes,
            len(jax.devices()), dict(self.mesh.shape),
        )

    def _ensure_state(self, batch, global_rows: Optional[int] = None) -> None:
        if getattr(self, "sample_features", None) is None:
            # one host row, kept for export signatures (SavedModel)
            self.sample_features = jax.tree.map(
                lambda a: np.asarray(a[:1]), batch["features"]
            )
        if self.state is not None:
            return
        features = batch["features"]
        if global_rows is not None:
            # Slice-local data path: ranks hold DIFFERENT local rows, but
            # the jitted init embeds its features as constants — every
            # rank must trace the identical program, so init from zeros
            # of the global batch shape (param init depends on shapes and
            # rng only, never on feature values).
            features = jax.tree.map(
                lambda a: np.zeros(
                    (global_rows,) + np.asarray(a).shape[1:],
                    np.asarray(a).dtype,
                ),
                features,
            )
        self.state = self.trainer.init_state_global(
            jax.random.PRNGKey(self._seed), features
        )
        self._maybe_prewarm(batch, global_rows)
        if self._saver is not None:
            restored = self._saver.maybe_restore(self.state)
            if restored is not None:
                self.state = restored
                logger.info(
                    "Rank %d restored checkpoint at step %d",
                    self.process_id, int(self.state.step),
                )

    def _maybe_prewarm(self, batch, global_rows) -> None:
        """Background-compile the train step for EXPECTED post-failure
        mesh sizes (world-1 and world/2 — SURVEY §7 hard part 1's
        mitigation): the executables land in the persistent compile
        cache, so a post-preemption remesh restores without paying a
        cold XLA compile.  Once, after first init; multi-process only."""
        if self.num_processes <= 1 or getattr(self, "_prewarmed", False):
            return
        self._prewarmed = True
        try:
            per = max(len(jax.devices()) // self.num_processes, 1)
            counts = sorted(
                {
                    (self.num_processes - 1) * per,
                    (self.num_processes // 2) * per,
                }
                - {0, len(jax.devices())}
            )
            if not counts or "labels" not in batch:
                # prediction-only feeds carry no labels; the train step
                # (the thing worth prewarming) is not on their path
                return
            rows = global_rows or self.minibatch_size

            def zeros_like_rows(a):
                a = np.asarray(a)
                return np.zeros((rows,) + a.shape[1:], a.dtype)

            sample = {
                "features": jax.tree.map(
                    zeros_like_rows, batch["features"]
                ),
                "labels": zeros_like_rows(batch["labels"]),
            }
            self.trainer.prewarm_for_device_counts(
                sample, counts, rng=jax.random.PRNGKey(self._seed)
            )
        except Exception:  # advisory path: never fail the task for it
            logger.exception("elastic prewarm setup skipped")

    @property
    def is_leader(self) -> bool:
        return self.process_id == 0

    # ---- wedge watchdog --------------------------------------------------
    # A dead peer does NOT fail a blocking XLA collective — the survivor
    # hangs in it forever (measured: gloo psum blocks >75s after peer
    # death; on a real TPU slice the ICI collective stalls the same way —
    # SURVEY.md §7 hard part 3).  The in-process re-rendezvous path only
    # runs BETWEEN tasks, so a rank stuck INSIDE a collective when the
    # membership epoch moves must be restarted: the watchdog polls the
    # master and, if the epoch has moved past us for longer than the grace
    # window (i.e. the main loop never reached the stale-epoch check),
    # kills the process.  The pod manager relaunches it; the replacement
    # bootstraps at the new epoch and restores from the checkpoint — the
    # restart unit is the process, exactly like a slice-host loss.

    WEDGED_EXIT_CODE = 43

    def _watchdog(self, poll_s: float = 2.0) -> None:
        while True:
            time.sleep(poll_s)
            try:
                spec = self._client.get_cluster_spec(
                    pb.GetClusterSpecRequest(worker_id=self.worker_id)
                )
            except Exception:
                continue  # master briefly unreachable
            if spec.rendezvous_id <= self._epoch or self._in_rendezvous_wait:
                self._epoch_stale_since = None
                continue
            now = time.time()
            if self._epoch_stale_since is None:
                self._epoch_stale_since = now
                continue
            if now - self._epoch_stale_since > self._wedge_grace_s:
                logger.error(
                    "Rank %d wedged: epoch moved %d -> %d but the main "
                    "loop hasn't re-rendezvoused in %.0fs (stuck in a "
                    "collective with a dead peer); restarting process",
                    self.process_id, self._epoch, spec.rendezvous_id,
                    now - self._epoch_stale_since,
                )
                os._exit(self.WEDGED_EXIT_CODE)

    # ---- main loop -----------------------------------------------------

    def drain_and_stop(self) -> None:
        """Maintenance-notice hook (thread-safe): flag-only; the main
        loop drains at its next task boundary (single-process ranks also
        flush a final checkpoint there — doing it from the watcher
        thread would race the training loop)."""
        self._preempted = True

    def run(self) -> bool:
        if self.trainer is None:
            self.setup()
        seq = 0
        while True:
            if self._preempted:
                logger.info(
                    "Rank %d stopping at task boundary (preemption/"
                    "maintenance notice); tasks re-lease and the relaunch "
                    "restores from checkpoint",
                    self.process_id,
                )
                if self.num_processes == 1 and self._saver is not None:
                    # single-process: no collective-save hazard — flush
                    # the freshest state before exiting (multi-process
                    # ranks rely on periodic checkpoints; a drain-time
                    # collective save could enter mismatched programs)
                    self._save(force=True)
                    self._saver.wait_until_finished()
                return False
            # Bounded, jittered retries replace the old fixed-sleep
            # infinite loop; exhaustion raises RetryBudgetExhausted,
            # which worker/main.py maps to exit code 45 so the pod
            # manager relaunches us (charged against the budget).
            resp = self._rpc_policy.call(
                lambda: self._client.get_spmd_task(
                    pb.GetSpmdTaskRequest(
                        worker_id=self.worker_id,
                        rendezvous_id=self._epoch,
                        seq=seq,
                    )
                ),
                description="get_spmd_task",
            )
            if resp.job_finished:
                logger.info(
                    "Job finished; SPMD rank %d exiting", self.process_id
                )
                self._flush_predictions()
                if self.is_leader and self.step_timer.steps_per_sec:
                    self.step_timer.log(f"rank {self.process_id}: ")
                self._summary.close()
                from elasticdl_tpu.worker.worker import invoke_callbacks

                invoke_callbacks(self.spec.callbacks, "on_job_end")
                return True
            if resp.epoch_stale:
                logger.info(
                    "Rank %d: epoch %d stale; re-rendezvous",
                    self.process_id, self._epoch,
                )
                if not self._re_rendezvous():
                    return False
                seq = 0
                continue
            task = resp.task
            if task.task_id < 0 or task.type == pb.WAIT:
                time.sleep(self._wait_sleep_s)
                continue
            self._process_task(task)
            seq += 1

    def _process_task(self, task: pb.Task) -> int:
        # No per-rank failure reporting: if any rank's collective step
        # dies the whole group is wedged and recovery is the elastic
        # epoch-bump path, not a task retry.
        from elasticdl_tpu.worker.worker import invoke_callbacks

        invoke_callbacks(self.spec.callbacks, "on_task_start", task)
        records = 0
        if task.type == pb.TRAINING:
            records = self._train_task(task)
            if self.is_leader:
                with _phase_timer.phase("report"):
                    self._data_service.report_task(
                        task,
                        records=records,
                        model_version=int(self.state.step),
                        telemetry=self._telemetry_payload(),
                    )
                try:
                    self._client.report_version(
                        pb.ReportVersionRequest(
                            worker_id=self.worker_id,
                            model_version=int(self.state.step),
                        )
                    )
                except Exception:
                    pass
        elif task.type == pb.EVALUATION:
            if not self._has_trained_state():
                # Same guard as Worker._evaluate_task: never report metrics
                # from randomly initialised params.  The condition is
                # deterministic across ranks (state/step identical), so all
                # ranks skip together; the leader re-queues the task.  No
                # early return: on_task_end must pair with the
                # on_task_start already fired above.
                if self.is_leader:
                    self._data_service.report_task(
                        task,
                        err="no trained state for evaluation",
                        transient=True,
                    )
            else:
                records = self._evaluate_task(task)
                if self.is_leader:
                    self._data_service.report_task(task, records=records)
        elif task.type == pb.PREDICTION:
            records = self._predict_task(task)
            if self.is_leader:
                self._data_service.report_task(task, records=records)
        elif task.type == pb.SAVE_MODEL:
            self._save(force=True)
            if self.is_leader:
                from elasticdl_tpu.worker.worker import export_for_task

                # Params are replicated => fully addressable on every
                # host; the leader alone writes the export.  No trained
                # state (deterministic across ranks) => report failure so
                # the task re-queues instead of silently skipping.
                try:
                    export_for_task(
                        self.state, self.spec, task,
                        sample_features=getattr(
                            self, "sample_features", None
                        ),
                    )
                except RuntimeError as exc:
                    self._data_service.report_task(task, err=str(exc))
                else:
                    self._data_service.report_task(task, records=0)
        else:
            logger.warning("SPMD worker ignoring task type %s", task.type)
            if self.is_leader:
                self._data_service.report_task(task, records=0)
        invoke_callbacks(self.spec.callbacks, "on_task_end", task, records)
        return records

    def _telemetry_payload(self) -> dict:
        """Leader-rank telemetry piggybacked on task reports (int64 on
        the wire; rates pre-scaled to milli units) — same shape as
        Worker._telemetry_payload so the master's snapshot and
        `elasticdl top` render both worker kinds identically."""
        payload = {
            "steps_per_sec_milli": int(
                self.step_timer.steps_per_sec * 1000
            ),
            "model_step": (
                int(self.state.step) if self.state is not None else 0
            ),
        }
        for phase, ms in _phase_timer.totals_milli().items():
            payload[f"phase_{phase}_ms"] = ms
        return payload

    def _train_task(self, task: pb.Task) -> int:
        if self._profile_dir and not self._profiled:
            self._profiled = True
            from elasticdl_tpu.common import profiler

            with profiler.trace(self._profile_dir):
                with profiler.annotate(f"task-{task.task_id}"):
                    records = self._train_task_inner(task)
                    if self.last_loss is not None:
                        jax.block_until_ready(self.last_loss)
            return records
        return self._train_task_inner(task)

    def _train_task_inner(self, task: pb.Task) -> int:
        records = 0
        # Slice-local reads (SURVEY §3.3 per-worker disjoint reads): each
        # rank reads only its addressable rows of every full global batch
        # — aggregate host IO is O(shard), not O(world_size * shard).
        local = mesh_lib.local_batch_range(self.mesh, self.minibatch_size)
        if local is not None:
            batches = self._data_service.local_batches_for_task(
                task, self.minibatch_size, self._feed,
                self._feed_bulk, local[0], local[1],
            )
        else:  # non-contiguous local rows: every rank reads everything
            if self.steps_per_execution > 1:
                logger.warning(
                    "steps_per_execution=%d ignored: this rank's rows of "
                    "the data axis are not one contiguous range, so "
                    "batches dispatch singly", self.steps_per_execution,
                )
            batches = (
                (batch, real, False)
                for batch, real in self._data_service.batches_for_task(
                    task, self.minibatch_size, self._feed,
                    feed_bulk=self._feed_bulk,
                )
            )
        from elasticdl_tpu.worker.task_data_service import prefetch_batches

        def mark_recovered():
            if self._recovery_t0 is not None:
                # BASELINE.md's headline elasticity metric: preemption
                # (epoch bump observed) -> first post-restore optimizer
                # step.
                logger.info(
                    "elastic recovery: %.2fs (epoch %d, world %d, "
                    "resumed at step %d)",
                    time.time() - self._recovery_t0, self._epoch,
                    self.num_processes, int(self.state.step),
                )
                self._recovery_t0 = None

        def make_gb(one_batch, one_is_local):
            # Global-array assembly = this loop's host->device staging.
            with _phase_timer.phase("h2d_stage"):
                if one_is_local:
                    return mesh_lib.make_global_batch_from_local(
                        one_batch, self.mesh, self.minibatch_size,
                        local[0],
                    )
                return mesh_lib.make_global_batch(one_batch, self.mesh)

        def single_step(one_batch, one_is_local, gb=None):
            if gb is None:
                gb = make_gb(one_batch, one_is_local)
            self.state, loss = self.trainer.train_on_global_batch(
                self.state, gb
            )
            self.last_loss = loss
            mark_recovered()
            self.step_timer.tick()
            _phase_timer.step_done()
            self._maybe_checkpoint()

        # steps_per_execution grouping: full groups of slice-local
        # batches dispatch as ONE scan program over a global (K, B, ...)
        # stack; tails and non-local batches run single-step, so only
        # two program shapes ever compile.  The decision is identical on
        # every rank (same batch stream), keeping the collective in step.
        # The first post-recovery batch always runs single-step so the
        # recovery clock measures loss -> FIRST optimizer step, not
        # loss -> K steps.
        pending = []
        # Second buffering level (single-step dispatch only): the global
        # batch for step k+1 is assembled — shard transfers issued — on
        # the consumer thread while step k's collective executes.  The
        # host batch rides along untouched: _ensure_state and the
        # steps_per_execution grouping path want host arrays.
        device_stage = None
        if self.steps_per_execution == 1:
            def device_stage(item):
                staged_batch, staged_real, staged_is_local = item
                if self.state is None:
                    # init_state_global (first loop iteration) must be
                    # the mesh's FIRST collective program; assembling
                    # global arrays ahead of it breaks the multi-process
                    # CPU backend used in tests.  Nothing to overlap
                    # before step 1 anyway.
                    return item
                return (
                    staged_batch, staged_real, staged_is_local,
                    make_gb(staged_batch, staged_is_local),
                )
        # host read/parse overlaps the collective step (double buffering)
        for item in prefetch_batches(
            batches, device_stage=device_stage, phase_timer=_phase_timer
        ):
            batch, real, is_local = item[:3]
            gb = item[3] if len(item) > 3 else None
            self._ensure_state(batch, global_rows=self.minibatch_size)
            records += real
            if (
                is_local
                and self.steps_per_execution > 1
                and self._recovery_t0 is None
            ):
                pending.append(batch)
                if len(pending) == self.steps_per_execution:
                    with _phase_timer.phase("h2d_stage"):
                        stack = (
                            mesh_lib.make_global_batch_stack_from_local(
                                pending, self.mesh,
                                self.minibatch_size, local[0],
                            )
                        )
                    pending = []
                    self.state, losses = (
                        self.trainer.train_on_global_batch_stack(
                            self.state, stack
                        )
                    )
                    self.last_loss = losses[-1]
                    mark_recovered()
                    for _ in range(self.steps_per_execution):
                        self.step_timer.tick()
                        _phase_timer.step_done()
                    self._maybe_checkpoint(
                        stride=self.steps_per_execution
                    )
                continue
            # preserve data order: a wrap-padded (non-local) tail batch
            # must not train before still-pending grouped batches
            for held in pending:
                single_step(held, True)
            pending = []
            single_step(batch, is_local, gb=gb)
        for batch in pending:  # task tail: single-step program
            single_step(batch, True)
        _phase_timer.flush()
        if self.last_loss is not None:
            self._summary.scalars(
                {
                    "train/loss": float(np.asarray(self.last_loss)),
                    "train/steps_per_sec": self.step_timer.steps_per_sec,
                },
                step=int(self.state.step),
            )
        return records

    def _evaluate_task(self, task: pb.Task) -> int:
        from elasticdl_tpu.worker.sync import state_at_version

        records = 0
        all_labels, all_preds = [], []
        eval_state, actual_version = None, None
        for batch, real in self._data_service.batches_for_task(
            task, self.minibatch_size, self._feed,
            feed_bulk=self._feed_bulk,
        ):
            self._ensure_state(batch)
            if actual_version is None:
                # Deterministic across ranks (same state/saver contents),
                # so every rank restores — or falls back — together.
                eval_state, actual_version = state_at_version(
                    self.state, self._saver, task.model_version
                )
            features = mesh_lib.make_global_batch(
                batch["features"], self.mesh
            )
            preds = self.trainer.predict_on_global_batch(
                eval_state, features
            )
            # Data-sharded output: gather the full array onto every host
            # so metric fns (host-side, e.g. AUC) see all rows.
            preds = _allgather(preds)
            all_labels.append(np.asarray(batch["labels"])[:real])
            all_preds.append(np.asarray(preds)[:real])
            records += real
        if records and self.is_leader:
            from elasticdl_tpu.worker.worker import (
                report_evaluation_with_samples,
            )

            labels = np.concatenate(all_labels)
            preds = np.concatenate(all_preds)
            version = (
                actual_version
                if actual_version is not None and actual_version >= 0
                else int(self.state.step)
            )
            metrics = {
                name: float(fn(labels, preds))
                for name, fn in self.spec.eval_metrics.items()
            }
            report_evaluation_with_samples(
                self._client, self.worker_id, version,
                metrics, records, labels, preds, task_id=task.task_id,
            )
        return records

    def _predict_task(self, task: pb.Task) -> int:
        records = 0
        rows = []
        processor = self.spec.prediction_outputs_processor
        for batch, real in self._data_service.batches_for_task(
            task, self.minibatch_size, self._feed,
            feed_bulk=self._feed_bulk,
        ):
            self._ensure_state(batch)
            features = mesh_lib.make_global_batch(
                batch["features"], self.mesh
            )
            preds = _allgather(
                self.trainer.predict_on_global_batch(self.state, features)
            )
            rows.append(np.asarray(preds)[:real])
            records += real
        if rows and processor is not None and self.is_leader:
            # reference C18 contract; leader-only so the zoo's sink sees
            # each batch once, not once per rank — and buffered per task
            # (ADVICE r3) so a mid-task failure + re-queue cannot deliver
            # partial duplicates.  At-least-once at task granularity.
            for chunk in rows:
                processor.process(chunk, self.worker_id)
        if rows:
            # Keyed by task_id so a task re-processed after a remesh (the
            # lease was recovered before the leader reported) OVERWRITES
            # its rows instead of duplicating them; with an output dir the
            # leader also makes each task's rows durable immediately, so
            # rows reported before a process restart are never lost.
            self.predictions = getattr(self, "predictions", {})
            self.predictions[task.task_id] = np.concatenate(rows)
            if self.is_leader and self._output_dir:
                os.makedirs(self._output_dir, exist_ok=True)
                np.save(
                    os.path.join(
                        self._output_dir, f"part-{task.task_id:05d}.npy"
                    ),
                    self.predictions[task.task_id],
                )
        return records

    def _flush_predictions(self) -> None:
        """Cluster predict jobs: assemble the per-task part files (written
        durably as each task completed) into one predictions.npy — the
        same final artifact local mode produces (client/api.py)."""
        if not self.is_leader or not self._output_dir:
            return
        import glob

        parts = sorted(
            glob.glob(os.path.join(self._output_dir, "part-*.npy"))
        )
        if not parts:
            return
        merged = np.concatenate([np.load(p) for p in parts])
        np.save(os.path.join(self._output_dir, "predictions.npy"), merged)
        logger.info(
            "Merged %d prediction part files (%d rows) into %s",
            len(parts), len(merged),
            os.path.join(self._output_dir, "predictions.npy"),
        )

    def _has_trained_state(self) -> bool:
        if self.state is not None and int(self.state.step) > 0:
            return True
        return (
            self._saver is not None
            and self._saver.latest_step() is not None
        )

    # ---- elasticity ----------------------------------------------------

    # Exit code for a clean topology-change restart (distinct from the
    # watchdog's WEDGED_EXIT_CODE only for log forensics; both relaunch
    # WITHOUT charging the pod manager's failure budget).
    TOPOLOGY_RESTART_EXIT_CODE = 44

    def _restart_for_topology_change(self) -> None:
        """Exit for relaunch at a new topology, best-effort flushing any
        in-flight async checkpoint first.  The flush is time-bounded in a
        side thread: with all peers alive (scale events) it completes and
        preserves up to checkpoint_steps of work; with a dead peer the
        distributed flush cannot complete and we leave after the bound
        (recovery then restores the previous committed step)."""
        saver = self._saver
        if saver is not None:
            flusher = threading.Thread(
                target=lambda: saver.wait_until_finished(), daemon=True
            )
            flusher.start()
            flusher.join(timeout=10.0)
        logger.info(
            "Rank %d: topology change; restarting process for a clean "
            "runtime bootstrap", self.process_id,
        )
        os._exit(self.TOPOLOGY_RESTART_EXIT_CODE)

    def _re_rendezvous(self, settle_timeout_s: float = 60.0) -> bool:
        """Membership changed: rejoin with the new topology and restore
        state from the latest checkpoint.

        MULTI-PROCESS topologies restart the process instead of
        re-initializing in place: an in-process jax.distributed
        shutdown/re-init leaves per-process library state (observed:
        Orbax's distributed-barrier counters) out of sync with
        freshly-booted peers, which can hang the first post-remesh
        collective checkpoint; and a world-1 survivor cannot call
        jax.distributed.initialize at all once its backend exists.  A
        process restart makes every member of the new epoch identically
        fresh — the same, proven path the wedge watchdog and
        coordination-service aborts already take; recovery cost is the
        same restore-from-checkpoint cycle.  Only a topology that stays
        single-process (no distributed runtime involved on either side)
        re-meshes in place."""
        # Restart decision comes BEFORE any barrier participation: a rank
        # that confirmed the new epoch and THEN exited would release the
        # barrier for fresh joiners, who would initialize a world whose
        # members are already gone and wedge until their watchdogs fire.
        if distributed_is_initialized() or self.num_processes > 1:
            self._restart_for_topology_change()
        self._recovery_t0 = time.time()
        # Peek (no confirmation) at the new spec: a single-process worker
        # growing into a multi-process world must also restart — its XLA
        # backend already exists, so jax.distributed.initialize would
        # refuse to run in this process.
        peek = self._rpc_policy.call(
            lambda: self._client.get_cluster_spec(
                pb.GetClusterSpecRequest(worker_id=self.worker_id)
            ),
            description="get_cluster_spec.peek",
        )
        if peek.world_size > 1 or peek.expected_world_size > 1:
            self._restart_for_topology_change()
        # Wait for a settled, group-confirmed epoch (the same barrier as
        # first join).  A timeout means the group never stabilised around
        # us — exit and let the pod manager relaunch a fresh process.
        self._in_rendezvous_wait = True
        try:
            spec, me = wait_for_confirmed_epoch(
                self._client,
                self.worker_id,
                poll_s=self._wait_sleep_s,
                timeout_s=settle_timeout_s,
                rpc_policy=self._rpc_policy,
            )
        finally:
            self._in_rendezvous_wait = False
        if spec is None:
            logger.warning(
                "Worker %d: no confirmed epoch within %.0fs; restarting",
                self.worker_id, settle_timeout_s,
            )
            return False
        if me is None or spec.world_size == 0:
            logger.warning(
                "Worker %d evicted at epoch %d; exiting",
                self.worker_id, spec.rendezvous_id,
            )
            return False
        self._epoch = spec.rendezvous_id
        self.process_id = me.rank
        self.num_processes = spec.world_size
        self._coordinator = spec.coordinator_address or self._coordinator
        self.state = None  # re-init + checkpoint restore on next batch
        self.trainer = None
        self.setup()
        self.remesh_count += 1
        logger.info(
            "Rank %d re-rendezvoused: epoch %d, world %d, coordinator %s "
            "(%.2fs)",
            self.process_id, self._epoch, self.num_processes,
            self._coordinator, time.time() - self._recovery_t0,
        )
        return True

    # ---- helpers -------------------------------------------------------

    def save_checkpoint_and_flush(self) -> None:
        """Synchronous final checkpoint (preemption hook: the process is
        about to die, so wait for the write to land).

        Multi-process mode must NOT save here: the Orbax save is a
        distributed collective, and SIGTERM reaches ranks at arbitrary
        points (possibly mid-step, at different state.step values), so a
        signal-time save can enter mismatched collectives — hanging the
        grace window or corrupting the checkpoint.  Instead the flag stops
        the main loop at the next task boundary; recovery rides the
        periodic checkpoints + task re-lease (the recovery unit is the
        task, not the step)."""
        if self.num_processes > 1:
            self._preempted = True
            logger.info(
                "Rank %d preempted; skipping signal-time collective save "
                "(periodic checkpoints + task re-lease cover recovery)",
                self.process_id,
            )
            return
        self._save(force=True)
        if self._saver is not None:
            self._saver.wait_until_finished()

    def _save(self, force: bool = False) -> None:
        # Orbax distributed save: EVERY rank participates (each writes its
        # addressable shards); the decision is deterministic on step so all
        # ranks enter together.
        if self._saver is not None and self.state is not None:
            self._saver.save(self.state, force=force)

    def _maybe_checkpoint(self, stride: int = 1) -> None:
        # crossing check (not exact modulo): a K-step scan dispatch may
        # jump past a multiple of checkpoint_steps (worker/sync.py has
        # the same rule).  Deterministic on step, so all ranks enter the
        # collective save together.
        if (
            self._saver is not None
            and self._checkpoint_steps
            and int(self.state.step) % self._checkpoint_steps < stride
        ):
            self._saver.save(self.state)

    def _feed(self, records):
        return self.spec.feed(records, getattr(self._reader, "metadata", {}))

    @property
    def _feed_bulk(self):
        """Vectorized-parse closure (same contract as Worker._feed_bulk)."""
        if self.wire_format == "dedup":  # unreachable today; see __init__
            fn = self.spec.feed_bulk_dedup
        elif self.compact_wire:
            fn = self.spec.feed_bulk_compact
        else:
            fn = self.spec.feed_bulk
        if fn is None:
            return None
        metadata = getattr(self._reader, "metadata", {})
        return lambda buf, sizes: fn(buf, sizes, metadata)


from elasticdl_tpu.parallel.collectives import (  # noqa: E402
    host_allgather as _allgather,
)
