"""Turns the stream of leased tasks into a stream of fixed-shape batches.

Parity: reference python/worker/task_data_service.py (SURVEY.md C8) — the
invariant preserved is *task completion ≡ data consumed*: a task is
reported back to the master only after every batch cut from its records has
been yielded to the train loop.  Unlike the reference (tf.data generator),
batches never span task boundaries; the final partial batch of a task is
padded by wrapping records so shapes stay static under jit (no recompiles),
with the true record count carried alongside for metrics.
"""

from __future__ import annotations

import time
from typing import Callable, Iterator, Optional, Tuple

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.proto import elasticdl_pb2 as pb

logger = get_logger(__name__)


class TaskDataService:
    def __init__(self, master_client, data_reader, worker_id: int,
                 wait_sleep_s: float = 0.5):
        self._client = master_client
        self._reader = data_reader
        self._worker_id = worker_id
        self._wait_sleep_s = wait_sleep_s

    def get_task(self, task_type=None) -> Tuple[Optional[pb.Task], bool]:
        """Poll the master for a task.  Returns (task|None, job_finished);
        blocks through WAIT responses with backoff."""
        while True:
            req = pb.GetTaskRequest(worker_id=self._worker_id)
            if task_type is not None:
                req.task_type = task_type
                req.filter_by_type = True
            resp = self._client.get_task(req)
            if resp.job_finished:
                return None, True
            task = resp.task
            if task.task_id < 0 or task.type == pb.WAIT:
                time.sleep(self._wait_sleep_s)
                continue
            return task, False

    def report_task(self, task: pb.Task, err: str = "", records: int = 0):
        req = pb.ReportTaskResultRequest(
            task_id=task.task_id,
            err_message=err,
            worker_id=self._worker_id,
        )
        req.exec_counters["records"] = records
        self._client.report_task_result(req)

    def batches_for_task(
        self,
        task: pb.Task,
        batch_size: int,
        feed: Callable,
    ) -> Iterator[Tuple[dict, int]]:
        """Yield (batch, real_count) for one task.  `feed(records)` maps a
        list of raw records to a batch dict of arrays (zoo contract).  The
        final partial batch is wrap-padded to exactly `batch_size`
        (mesh.pad_to_multiple) so shapes stay static under jit."""
        from elasticdl_tpu.parallel.mesh import pad_to_multiple

        buf = []
        for record in self._reader.read_records(task):
            buf.append(record)
            if len(buf) == batch_size:
                yield feed(buf), batch_size
                buf = []
        if buf:
            yield pad_to_multiple(feed(buf), batch_size)
