"""Turns the stream of leased tasks into a stream of fixed-shape batches.

Parity: reference python/worker/task_data_service.py (SURVEY.md C8) — the
invariant preserved is *task completion ≡ data consumed*: a task is
reported back to the master only after every batch cut from its records has
been yielded to the train loop.  Unlike the reference (tf.data generator),
batches never span task boundaries; the final partial batch of a task is
padded by wrapping records so shapes stay static under jit (no recompiles),
with the true record count carried alongside for metrics.
"""

from __future__ import annotations

import time
from typing import Callable, Iterator, Optional, Tuple

from elasticdl_tpu.common import resilience
from elasticdl_tpu.common.faults import InjectedFault
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.proto import elasticdl_pb2 as pb

logger = get_logger(__name__)


def _is_rpc_error(exc: Exception) -> bool:
    try:
        import grpc

        return isinstance(exc, grpc.RpcError)
    except ImportError:  # pragma: no cover
        return False


def _retryable(exc: BaseException) -> bool:
    """This service's historical contract: ANY RpcError retries (the
    master owns task semantics; every transport failure is transient to
    us), and injected faults behave like transport failures.  Anything
    else — application errors — propagates immediately."""
    return _is_rpc_error(exc) or isinstance(exc, InjectedFault)


def prefetch_batches(iterator, depth: int = 2, device_stage=None,
                     device_depth: int = 1, phase_timer=None):
    """Run a host-side batch iterator (reader IO + feed parsing) in a
    background thread, keeping up to `depth` batches ready while the
    caller's thread drives the device — read/parse overlaps compute (the
    double-buffering every input pipeline wants; measured in bench.py's
    e2e mode).  Pure host work only: the producer never touches device
    APIs, so it is safe on every backend including the virtual CPU mesh
    (scripts/check_host_device_boundary.py enforces this).

    `device_stage`, when given, adds a second buffering level for the
    host->device TRANSFER: up to `device_depth` upcoming batches are
    passed through `device_stage(item)` on the CONSUMER thread before
    the current batch's result is yielded, so batch k+1's device_put
    overlaps the caller's execution of batch k (JAX transfers are async
    — device_put returns as soon as the copy is enqueued).  Staging on
    the consumer thread honors the trainer's single-device-thread
    constraint: only ONE thread ever touches device APIs.

    Exceptions from the iterator re-raise at the consumer; a
    device_stage exception also re-raises at the consumer (in yield
    order, never ahead of earlier un-yielded batches).  Abandoning the
    generator (break / task failure) unblocks and stops the producer.

    `phase_timer` (common/profiler.PhaseTimer), when given, attributes
    the consumer's BLOCKED time on the queue to the `data_wait` phase —
    the signal that says "the input pipeline, not the device, is the
    bottleneck"."""
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    sentinel = object()
    stop = threading.Event()
    error = []

    def produce():
        try:
            for item in iterator:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.5)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as exc:  # re-raised at the consumer
            error.append(exc)
        finally:
            while not stop.is_set():
                try:
                    q.put(sentinel, timeout=0.5)
                    break
                except queue.Full:
                    continue

    thread = threading.Thread(target=produce, daemon=True)
    thread.start()

    def consume():
        while True:
            if phase_timer is None:
                item = q.get()
            else:
                wait_start = time.perf_counter()
                item = q.get()
                phase_timer.add(
                    "data_wait", time.perf_counter() - wait_start
                )
            if item is sentinel:
                if error:
                    raise error[0]
                return
            yield item

    try:
        if device_stage is None:
            yield from consume()
            return
        from collections import deque

        staged: "deque" = deque()
        source = consume()
        while True:
            try:
                item = next(source)
            except StopIteration:
                break
            except BaseException:
                # reader died: batches already staged are good transfers
                # — deliver them before surfacing the failure
                while staged:
                    yield staged.popleft()
                raise
            try:
                staged.append(device_stage(item))
            except BaseException:
                while staged:
                    yield staged.popleft()
                raise
            if len(staged) > device_depth:
                yield staged.popleft()
        while staged:
            yield staged.popleft()
    finally:
        stop.set()


class TaskDataService:
    # Step-phase attribution hook (common/profiler.PhaseTimer): feed /
    # feed_bulk parse time is the `pack` phase.  Class default so bare
    # instances (test scaffolding) run untimed; the worker runtime
    # assigns the process-wide timer.  The wrapped feeds usually run on
    # the prefetch PRODUCER thread — PhaseTimer is thread-safe.
    phase_timer = None

    def __init__(self, master_client, data_reader, worker_id: int,
                 wait_sleep_s: float = 0.5, master_grace_s: float = 30.0,
                 rpc_policy: Optional[resilience.RetryPolicy] = None):
        self._client = master_client
        self._reader = data_reader
        self._worker_id = worker_id
        self._wait_sleep_s = wait_sleep_s
        self.master_grace_s = master_grace_s
        base = (
            rpc_policy if rpc_policy is not None
            else resilience.default_policy()
        )
        # get_task gets the master-grace budget (exhaustion == the job is
        # over or the master is lost); reports get a short budget because
        # the lease reaper re-queues whatever a lost report covered.
        self._get_policy = base.with_overrides(
            max_elapsed_s=master_grace_s,
            initial_backoff_s=min(wait_sleep_s, 0.5),
            retryable=_retryable,
        )
        self._report_policy = base.with_overrides(
            max_elapsed_s=min(10.0, master_grace_s), retryable=_retryable
        )

    def get_task(
        self, task_type=None, should_stop=None
    ) -> Tuple[Optional[pb.Task], bool]:
        """Poll the master for a task.  Returns (task|None, job_finished);
        blocks through WAIT responses with backoff.  Transient RPC failures
        retry under the shared policy (backoff + jitter); a master
        unreachable past the `master_grace_s` budget means the job is over
        (master exits after completion) or lost — either way the worker
        must stop.

        `should_stop`: optional callable checked between WAIT polls; when
        it turns true, returns (None, False) so the caller regains control
        — without it a worker parked on WAIT (e.g. the last shard of an
        epoch leased to another worker) never notices a drain request
        until a task happens to arrive."""
        while True:
            req = pb.GetTaskRequest(worker_id=self._worker_id)
            if task_type is not None:
                req.task_type = task_type
                req.filter_by_type = True
            try:
                resp = self._get_policy.call(
                    lambda: self._client.get_task(req),
                    description="get_task",
                )
            except resilience.RetryBudgetExhausted:
                logger.error(
                    "Master unreachable for %.0fs; worker %d stopping",
                    self.master_grace_s, self._worker_id,
                )
                return None, True
            if resp.job_finished:
                return None, True
            task = resp.task
            if task.task_id < 0 or task.type == pb.WAIT:
                if should_stop is not None and should_stop():
                    return None, False
                time.sleep(self._wait_sleep_s)
                continue
            return task, False

    def report_task(self, task: pb.Task, err: str = "", records: int = 0,
                    transient: bool = False, model_version: int = -1,
                    telemetry: Optional[dict] = None):
        req = pb.ReportTaskResultRequest(
            task_id=task.task_id,
            err_message=err,
            worker_id=self._worker_id,
            transient=transient,
        )
        req.exec_counters["records"] = records
        if model_version >= 0:
            # Model step at completion: the master's task journal pairs a
            # done shard with this version, and on restart trusts it only
            # when a model checkpoint at >= this step exists (step-based
            # durability — no cross-host clock comparison).
            req.exec_counters["model_version"] = model_version
        # Worker telemetry rides the existing map field under a `__`
        # namespace (int64 values — callers pre-scale rates to milli
        # units); the master's servicer peels these into its snapshot
        # instead of treating them as execution counters.
        for key, value in (telemetry or {}).items():
            req.exec_counters[f"__{key}"] = int(value)
        try:
            self._report_policy.call(
                lambda: self._client.report_task_result(req),
                description="report_task_result",
            )
        except Exception as exc:
            if not (_is_rpc_error(exc)
                    or isinstance(exc, (InjectedFault,
                                        resilience.RetryBudgetExhausted))):
                raise
            # Lost report: the master's lease timeout / failure detector
            # re-queues the task (at-least-once contract).
            logger.warning(
                "report_task_result for task %d failed: %s",
                task.task_id, exc,
            )

    def _timed_pack(self, fn: Optional[Callable]) -> Optional[Callable]:
        """Wrap a feed/feed_bulk callable so its parse time lands in the
        `pack` phase.  Identity when no timer is configured."""
        timer = self.phase_timer
        if timer is None or fn is None:
            return fn

        def timed(*args, **kwargs):
            start = time.perf_counter()
            try:
                return fn(*args, **kwargs)
            finally:
                timer.add("pack", time.perf_counter() - start)

        return timed

    # Upper bound on how much of a task's payload the bulk fast path
    # holds in host memory at once (in batches): bounds worker RSS for
    # large records_per_shard zoos without giving up the vectorized
    # parse (ADVICE r4).
    BULK_CHUNK_BATCHES = 16

    @staticmethod
    def _bulk_batches(bulk, batch_size: int, feed_bulk: Callable):
        """Cut one (buffer, sizes) bulk read into per-batch views; the
        tail (if any) is wrap-padded to the static batch shape."""
        import numpy as np

        from elasticdl_tpu.parallel.mesh import pad_to_multiple

        buffer, sizes = bulk
        n = len(sizes)
        bounds = np.zeros(n + 1, np.int64)
        np.cumsum(sizes, out=bounds[1:])
        for i in range(0, n, batch_size):
            j = min(i + batch_size, n)
            batch = feed_bulk(buffer[bounds[i]: bounds[j]], sizes[i:j])
            if j - i == batch_size:
                yield batch, batch_size
            else:
                yield pad_to_multiple(batch, batch_size)

    def batches_for_task(
        self,
        task: pb.Task,
        batch_size: int,
        feed: Callable,
        feed_bulk: Optional[Callable] = None,
    ) -> Iterator[Tuple[dict, int]]:
        """Yield (batch, real_count) for one task.  `feed(records)` maps a
        list of raw records to a batch dict of arrays (zoo contract).  The
        final partial batch is wrap-padded to exactly `batch_size`
        (mesh.pad_to_multiple) so shapes stay static under jit.

        When both the reader exposes a bulk representation
        (`read_records_bulk`) and the zoo a vectorized parser
        (`feed_bulk(buffer, sizes)`), the task's records move as ONE
        contiguous uint8 buffer cut into per-batch views — no per-record
        Python objects on the hot path (at 300K+ examples/s the
        per-record loop was the host bottleneck, VERDICT r3 weak #2)."""
        from elasticdl_tpu.parallel.mesh import pad_to_multiple

        feed = self._timed_pack(feed)
        feed_bulk = self._timed_pack(feed_bulk)
        if feed_bulk is not None:
            reader_bulk = getattr(self._reader, "read_records_bulk", None)
            if reader_bulk is not None:
                # Chunk the bulk read into batch-aligned sub-ranges
                # (ADVICE r4): reading the WHOLE task payload at once
                # spikes worker RSS with large records_per_shard — the
                # buffer held at any moment is now at most
                # BULK_CHUNK_BATCHES batches, and chunk boundaries stay
                # batch-aligned so the only partial batch is the task's
                # own tail (wrap-padded exactly as before).
                shard = task.shard
                total = shard.end - shard.start
                chunk = self.BULK_CHUNK_BATCHES * batch_size
                used_bulk = False
                for off in range(0, total, chunk):
                    sub = pb.Task(
                        task_id=task.task_id,
                        type=task.type,
                        shard=pb.Shard(
                            name=shard.name,
                            start=shard.start + off,
                            end=min(shard.start + off + chunk, shard.end),
                        ),
                    )
                    bulk = reader_bulk(sub)
                    if bulk is None:
                        if used_bulk:
                            # a reader that served earlier chunks must
                            # not silently truncate the task mid-stream
                            raise IOError(
                                f"bulk read failed mid-task at record "
                                f"{off} of {task.task_id}"
                            )
                        # no bulk representation (e.g. unindexed
                        # source): fall to the streaming path
                        break
                    used_bulk = True
                    yield from self._bulk_batches(
                        bulk, batch_size, feed_bulk
                    )
                if used_bulk or total == 0:
                    return
        buf = []
        for record in self._reader.read_records(task):
            buf.append(record)
            if len(buf) == batch_size:
                yield feed(buf), batch_size
                buf = []
        if buf:
            yield pad_to_multiple(feed(buf), batch_size)

    def local_batches_for_task(
        self,
        task: pb.Task,
        batch_size: int,
        feed: Callable,
        feed_bulk: Optional[Callable],
        local_start: int,
        local_stop: int,
    ) -> Iterator[Tuple[dict, int, bool]]:
        """SPMD slice-local variant: yield (batch, global_real, is_local).

        For each FULL global batch of `batch_size` records, this rank
        reads ONLY rows [local_start, local_stop) of the batch (its
        addressable slice of the data axis) — host IO drops from
        O(world_size * shard) to O(shard) in aggregate (SURVEY §3.3:
        per-worker disjoint reads; VERDICT r3 weak #4).  `is_local=True`
        batches hold just the local rows (pair with
        mesh.make_global_batch_from_local).  The task's final partial
        batch — if any — is read in full and wrap-padded identically on
        every rank (`is_local=False`), keeping padding bitwise-consistent
        without cross-rank coordination.
        """
        shard = task.shard
        total = shard.end - shard.start
        full = total // batch_size
        for i in range(full):
            base = shard.start + i * batch_size
            sub = pb.Task(
                task_id=task.task_id,
                type=task.type,
                shard=pb.Shard(
                    name=shard.name,
                    start=base + local_start,
                    end=base + local_stop,
                ),
            )
            for batch, _ in self.batches_for_task(
                sub, local_stop - local_start, feed, feed_bulk=feed_bulk
            ):
                yield batch, batch_size, True
        remaining = total - full * batch_size
        if remaining:
            tail = pb.Task(
                task_id=task.task_id,
                type=task.type,
                shard=pb.Shard(
                    name=shard.name,
                    start=shard.start + full * batch_size,
                    end=shard.end,
                ),
            )
            for batch, real in self.batches_for_task(
                tail, batch_size, feed, feed_bulk=feed_bulk
            ):
                yield batch, real, False
