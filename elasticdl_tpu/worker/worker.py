"""Worker runtime: pull tasks, train/evaluate/predict, report.

Parity: reference python/worker/worker.py (SURVEY.md C7, call stack §3.3).
Differences by design: the hot loop is an XLA-compiled step on the device
mesh instead of eager ops + per-step PS RPCs — the only RPCs left are
per-*shard* get_task/report (the property that kept master load low in the
reference is preserved exactly).

Model state lives in a `ModelOwner` (worker/sync.py).  Workers sharing one
owner train ONE model — the multi-worker consistency the reference provided
via PS/Horovod; a worker given no owner builds a private one (single-worker
jobs, tests).
"""

from __future__ import annotations

import traceback
from typing import Dict, Optional

import numpy as np

from elasticdl_tpu.common import events
from elasticdl_tpu.common import metrics as metrics_lib
from elasticdl_tpu.common import profiler as profiler_lib
from elasticdl_tpu.common import programs as programs_lib
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.model_handler import ModelSpec, resolve_wire_format
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.worker.sync import ModelOwner
from elasticdl_tpu.worker.task_data_service import TaskDataService
from elasticdl_tpu.worker.trainer import Trainer, run_device_serialized

logger = get_logger(__name__)

# Unified registry series (process-wide: one worker per process in
# cluster mode; in-process tests share them, which is what a
# cluster-wide total means anyway).  The same numbers ride task reports
# to the master as `__`-prefixed exec_counters.  Module-level so a
# Worker built without __init__ (test scaffolding) still counts.
_steps_counter = metrics_lib.default_registry().counter(
    "worker_train_steps_total", "optimizer steps completed"
)
_steps_gauge = metrics_lib.default_registry().gauge(
    "worker_steps_per_sec", "rolling step rate (StepTimer window)"
)
_tasks_counter = metrics_lib.default_registry().counter(
    "worker_tasks_total",
    "tasks processed, by outcome",
    labelnames=("result",),
)
# Step-phase attribution (ISSUE 5): one process-wide PhaseTimer feeding
# the labeled histogram, shared by the threaded and SPMD loops.  Module-
# level for the same __new__ reason as the counters above.
_phase_hist = metrics_lib.default_registry().histogram(
    "worker_step_phase_seconds",
    "per-step wall time attributed to a phase "
    "(profiler.STEP_PHASES)",
    labelnames=("phase",),
)
_phase_timer = profiler_lib.PhaseTimer(histogram=_phase_hist)
# Zero-initialize every catalogued phase so /metrics always exposes the
# full vocabulary — phases a given run never exercises (cold_gather is
# tiered-store-only) render with count 0 instead of disappearing.
for _p in profiler_lib.STEP_PHASES:
    _phase_hist.labels(phase=_p)


def _same_batch_shapes(a, b) -> bool:
    """True when two host batches have identical leaf shapes/dtypes —
    the np.stack compatibility the K-step scan program requires.  Only
    the dedup wire format ever produces ragged consecutive batches
    (sticky pad-cap growth, data/wire.py DedupPacker).  Store
    bookkeeping keys are host-side riders the stacked path strips before
    stacking (trainer.train_on_batch_stack plans the block from them) —
    their ragged ranked tuples must not veto an otherwise stackable
    pair."""
    import jax

    def _strip(batch):
        if isinstance(batch, dict) and any(
                k.startswith("__store_") for k in batch):
            return {
                k: v for k, v in batch.items()
                if not k.startswith("__store_")
            }
        return batch

    a, b = _strip(a), _strip(b)
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.shape(x) == np.shape(y)
        and getattr(x, "dtype", None) == getattr(y, "dtype", None)
        for x, y in zip(la, lb)
    )


def invoke_callbacks(callbacks, hook: str, *args) -> None:
    """Fire one zoo-callback hook on every callback that implements it.
    Hook points (reference C14 semantics, SURVEY.md): on_task_start(task),
    on_task_end(task, records), on_job_end().  A raising callback is
    logged, never fatal — user code must not kill the task loop."""
    for cb in callbacks or ():
        fn = getattr(cb, hook, None)
        if fn is None:
            continue
        try:
            fn(*args)
        except Exception:
            logger.exception("callback %r failed in %s", cb, hook)


# ~1MB of floats per report: comfortably under gRPC's 4MB default
# message cap, few round trips per shard.
EVAL_SAMPLE_CHUNK_FLOATS = 1 << 18


def report_evaluation_with_samples(
    client, worker_id: int, model_version: int,
    metrics: Dict[str, float], num_examples: int, labels, preds,
    task_id: int = -1,
) -> None:
    """Report shard metrics PLUS the raw (label, prediction) samples so
    the master can recompute rank metrics (AUC) exactly over the merged
    validation set — per-shard AUC means are biased (VERDICT r3 weak #3).
    Samples are chunked under the gRPC message limit; continuation chunks
    set samples_only so scalars/num_examples are counted once."""
    labels = np.asarray(labels, np.float32)
    preds2 = np.asarray(preds, np.float32).reshape(len(labels), -1)
    width = preds2.shape[1]
    rows_per_chunk = max(1, EVAL_SAMPLE_CHUNK_FLOATS // (1 + width))
    first = True
    for i in range(0, len(labels), rows_per_chunk):
        j = min(i + rows_per_chunk, len(labels))
        req = pb.ReportEvaluationMetricsRequest(
            worker_id=worker_id,
            model_version=model_version,
            pred_width=width,
            samples_only=not first,
            eval_task_key=task_id + 1 if task_id >= 0 else 0,
            final_chunk=j >= len(labels),
        )
        if first:
            req.num_examples = num_examples
            for name, value in metrics.items():
                req.metrics[name] = float(value)
            first = False
        req.eval_labels.extend(labels[i:j].tolist())
        req.eval_preds.extend(preds2[i:j].ravel().tolist())
        client.report_evaluation_metrics(req)


class TransientTaskError(RuntimeError):
    """The task is fine but THIS worker can't serve it yet (e.g. a fresh
    replacement pod leasing an eval task before it has trained state).
    Reported with transient=True: the master re-queues without charging a
    retry."""


class Worker:
    # class-level defaults: tests (and recovery paths) build bare
    # instances via __new__ and set only what they exercise
    wire_format = "plain"
    compact_wire = False

    def __init__(
        self,
        worker_id: int,
        master_client,
        data_reader,
        spec: ModelSpec,
        minibatch_size: int = 64,
        mesh=None,
        use_bf16: bool = False,
        seed: int = 0,
        checkpoint_saver=None,
        checkpoint_steps: int = 0,
        elastic_manager=None,
        model_owner: Optional[ModelOwner] = None,
        tensorboard_dir: str = "",
        profile_dir: str = "",
        steps_per_execution: int = 1,
        compact_wire: bool = False,
        wire_format: str = "",
    ):
        self.worker_id = worker_id
        self.spec = spec
        self.minibatch_size = minibatch_size
        # --wire_format / --compact_wire: ship batches in a reduced device
        # wire format when the zoo provides one (fewer H2D bytes/example);
        # the zoo's model accepts the reduced dtypes by contract.  An
        # unavailable format degrades to the next-best the zoo defines.
        self.wire_format = resolve_wire_format(
            spec, wire_format, compact_wire, logger
        )
        self.compact_wire = self.wire_format == "compact"
        # >1 dispatches that many train steps as ONE jitted lax.scan
        # program (Trainer.train_on_batch_stack) — amortizes per-dispatch
        # overhead, which dominates on remote/tunneled TPU runtimes.
        self.steps_per_execution = max(1, int(steps_per_execution))
        self._client = master_client
        self._data_service = TaskDataService(
            master_client, data_reader, worker_id
        )
        if model_owner is not None and (
            mesh is not None
            or use_bf16
            or seed != 0
            or checkpoint_saver is not None
            or checkpoint_steps != 0
        ):
            raise ValueError(
                "mesh/use_bf16/seed/checkpoint_* are owned by the "
                "ModelOwner; configure them on the owner you pass in"
            )
        if model_owner is None:
            model_owner = ModelOwner(
                Trainer(
                    model=spec.model,
                    optimizer=spec.optimizer,
                    loss_fn=spec.loss,
                    mesh=mesh,
                    use_bf16=use_bf16,
                    param_sharding_fn=spec.param_sharding,
                ),
                seed=seed,
                checkpoint_saver=checkpoint_saver,
                checkpoint_steps=checkpoint_steps,
            )
        self._owner = model_owner
        # Phase attribution: hand the process-wide timer to the layers
        # that own each phase (trainer: h2d_stage/compute; data service:
        # pack; prefetch_batches gets it per-iteration for data_wait).
        self._owner.trainer.phase_timer = _phase_timer
        self._data_service.phase_timer = _phase_timer
        self._reader = data_reader
        # Bounded: device arrays, converted lazily; unbounded growth would
        # pin one device buffer per step for the job's lifetime.
        from collections import deque

        self.losses = deque(maxlen=1024)
        self._elastic = elastic_manager
        # Observability (SURVEY.md §5): rolling step rate + TensorBoard
        # scalars.  Both are cheap no-ops when no tensorboard_dir is set
        # (the timer costs one perf_counter per batch).
        from elasticdl_tpu.common.profiler import StepTimer
        from elasticdl_tpu.common.summary import SummaryWriter

        self.step_timer = StepTimer()
        # Join the live step rate against the per-program cost model
        # (docs/OBSERVABILITY.md "Program observatory"): the dominant
        # train program — fused when steps_per_execution > 1 — feeds the
        # worker_program_bytes_per_sec / worker_mfu_ratio gauges.
        programs_lib.default_program_registry().bind_step_rate(
            "worker_train_step_many"
            if self.steps_per_execution > 1 else "worker_train_step",
            lambda: self.step_timer.steps_per_sec,
            steps_per_execution=self.steps_per_execution,
        )
        self._summary = SummaryWriter(tensorboard_dir or None)
        # --profile_dir: capture ONE task's device trace (Perfetto/XPlane,
        # TensorBoard-readable) then stop — always-on tracing would drag
        # the hot loop.
        self._profile_dir = profile_dir
        self._profiled = False

    # ---- owner passthroughs (tests and the client API read these) ------

    @property
    def state(self):
        return self._owner.state

    @property
    def trainer(self):
        return self._owner.trainer

    @property
    def model_owner(self) -> ModelOwner:
        return self._owner

    @property
    def _checkpoint_saver(self):
        return self._owner.checkpoint_saver

    # ---- loops ---------------------------------------------------------

    def drain_and_stop(self) -> None:
        """Maintenance-notice hook (thread-safe): request a stop at the
        next task boundary.  The MAIN thread does the final checkpoint
        there — saving from the watcher thread would race the training
        loop's state mutation."""
        self._stop_requested = True

    def run(self) -> bool:
        """Main loop until the master declares the job finished.  Returns
        True on clean completion."""
        while True:
            if getattr(self, "_stop_requested", False):
                logger.info(
                    "Worker %d draining at task boundary "
                    "(maintenance/preemption notice); flushing checkpoint",
                    self.worker_id,
                )
                self._owner.save_and_flush()
                return False
            task, finished = self._data_service.get_task(
                should_stop=lambda: getattr(self, "_stop_requested", False)
            )
            if finished:
                logger.info("Job finished; worker %d exiting", self.worker_id)
                if self.step_timer.steps_per_sec:
                    self.step_timer.log(f"worker {self.worker_id}: ")
                self._summary.close()
                invoke_callbacks(self.spec.callbacks, "on_job_end")
                return True
            if task is None:
                # woken out of the WAIT loop by should_stop: loop back so
                # the drain check at the top runs
                continue
            self._maybe_remesh()
            events.emit(
                events.TASK_CLAIMED,
                task_id=task.task_id,
                worker_id=self.worker_id,
                task_type=task.type,
            )
            try:
                invoke_callbacks(self.spec.callbacks, "on_task_start", task)
                records = self._process_task(task)
                events.emit(
                    events.TASK_TRAINED,
                    task_id=task.task_id,
                    worker_id=self.worker_id,
                    records=records,
                )
                _tasks_counter.labels(result="ok").inc()
                with _phase_timer.phase("report"):
                    self._data_service.report_task(
                        task,
                        records=records,
                        model_version=self._owner.step
                        if task.type == pb.TRAINING
                        else -1,
                        telemetry=self._telemetry_payload(),
                    )
                invoke_callbacks(
                    self.spec.callbacks, "on_task_end", task, records
                )
                if task.type == pb.TRAINING:
                    try:
                        self._client.report_version(
                            pb.ReportVersionRequest(
                                worker_id=self.worker_id,
                                model_version=self._owner.step,
                            )
                        )
                    except Exception:
                        pass  # advisory only; eval scheduling catches up
            except TransientTaskError as exc:
                logger.info(
                    "Task %d transiently unserviceable on worker %d: %s",
                    task.task_id, self.worker_id, exc,
                )
                _tasks_counter.labels(result="transient").inc()
                self._data_service.report_task(
                    task, err=str(exc), transient=True
                )
            except Exception as exc:  # report failure; master re-queues
                logger.error(
                    "Task %d failed on worker %d: %s",
                    task.task_id, self.worker_id, exc,
                )
                traceback.print_exc()
                # An exception with an empty str() must still read as a
                # failure on the wire (err_message=="" means success).
                err = str(exc) or type(exc).__name__
                _tasks_counter.labels(result="failed").inc()
                self._data_service.report_task(task, err=err)

    def _telemetry_payload(self) -> Dict[str, int]:
        """Telemetry piggybacked on task reports (int64 on the wire;
        rates pre-scaled to milli units)."""
        payload = {
            "steps_total": int(_steps_counter.value()),
            "steps_per_sec_milli": int(
                self.step_timer.steps_per_sec * 1000
            ),
            "model_step": int(self._owner.step),
        }
        # Cumulative per-phase milliseconds: the master diffs/normalizes
        # these in its snapshot, `elasticdl top` renders the dominant
        # phase per worker.
        for phase, ms in _phase_timer.totals_milli().items():
            payload[f"phase_{phase}_ms"] = ms
        return payload

    def _process_task(self, task: pb.Task) -> int:
        if task.type == pb.TRAINING:
            return self._train_task(task)
        if task.type == pb.EVALUATION:
            return self._evaluate_task(task)
        if task.type == pb.PREDICTION:
            return self._predict_task(task)
        if task.type == pb.SAVE_MODEL:
            self._save_model(task)
            return 0
        logger.warning("Unknown task type %s", task.type)
        return 0

    def _save_model(self, task: pb.Task):
        """Checkpoint, and export if the task's config rider asks for it
        (cluster mode: the master injects the output dir at job end)."""
        self._owner.save(force=True)
        # snapshot: another worker thread may still be training (and
        # donating the live state's buffers) while the export reads it
        export_for_task(
            self._owner.snapshot(), self.spec, task,
            sample_features=self._owner.sample_features,
        )

    def _train_task(self, task: pb.Task) -> int:
        if self._profile_dir and not self._profiled:
            self._profiled = True
            import jax as _jax

            from elasticdl_tpu.common import profiler

            with profiler.trace(self._profile_dir):
                with profiler.annotate(f"task-{task.task_id}"):
                    records = self._train_task_inner(task)
                    if self.losses:
                        _jax.block_until_ready(self.losses[-1])
            return records
        return self._train_task_inner(task)

    def _train_task_inner(self, task: pb.Task) -> int:
        from elasticdl_tpu.worker.task_data_service import prefetch_batches

        records = 0
        steps = 0
        loss = None
        pending = []
        # Second buffering level (single-step dispatch only): batch k+1's
        # host->device transfer is issued while batch k executes
        # (ModelOwner.stage_batch; device_put is async on real backends).
        # The stacked path keeps host batches — np.stack wants numpy.
        device_stage = None
        if self.steps_per_execution == 1:
            def device_stage(item):
                staged_batch, staged_real = item
                return self._owner.stage_batch(staged_batch), staged_real
        # host read/parse overlaps the device step (double buffering)
        for batch, real in prefetch_batches(
            self._data_service.batches_for_task(
                task, self.minibatch_size, self._feed,
                feed_bulk=self._feed_bulk,
            ),
            device_stage=device_stage,
            phase_timer=_phase_timer,
        ):
            records += real
            if self.steps_per_execution > 1:
                # full groups dispatch as one scan program; the task's
                # tail (< steps_per_execution batches) falls through to
                # the single-step program below, so only the two K values
                # {1, steps_per_execution} are ever compiled
                if pending and not _same_batch_shapes(pending[-1], batch):
                    # dedup sticky caps can grow between batches; a
                    # mixed-shape group can't np.stack — drain the held
                    # batches through the single-step program first
                    for held in pending:
                        loss = self._owner.train_batch(held)
                        self.step_timer.tick()
                        _phase_timer.step_done()
                        steps += 1
                        self.losses.append(loss)
                    pending.clear()
                pending.append(batch)
                if len(pending) == self.steps_per_execution:
                    losses = self._owner.train_batch_stack(pending)
                    for _ in pending:
                        self.step_timer.tick()
                        _phase_timer.step_done()
                        steps += 1
                    pending.clear()
                    loss = losses[-1]
                    # per-step history, as documented: the scan returns
                    # all K losses (one device array; indexing is lazy)
                    self.losses.extend(losses)
                continue
            loss = self._owner.train_batch(batch)
            self.step_timer.tick()
            _phase_timer.step_done()
            steps += 1
            self.losses.append(loss)
        for batch in pending:
            loss = self._owner.train_batch(batch)
            self.step_timer.tick()
            _phase_timer.step_done()
            steps += 1
            self.losses.append(loss)
        if steps:
            _steps_counter.inc(steps)
            _steps_gauge.set(self.step_timer.steps_per_sec)
            # partial flush window: the task boundary must not strand
            # accumulated phase time (the trace exporter reads these)
            _phase_timer.flush()
        if loss is not None:
            # One scalar write per TASK, not per step: forcing the loss to
            # host every batch would serialize the device pipeline.
            self._summary.scalars(
                {
                    # serialized: a device->host fetch racing another
                    # thread's step execution corrupts the CPU backend
                    "train/loss": run_device_serialized(
                        lambda: float(np.asarray(loss))
                    ),
                    "train/steps_per_sec": self.step_timer.steps_per_sec,
                },
                step=self._owner.step,
            )
        return records

    def _evaluate_task(self, task: pb.Task) -> int:
        """Forward-only over the shard; metrics computed host-side on the
        un-padded slice and reported to the master for aggregation."""
        if not self._owner.has_trained_state():
            # A fresh worker (e.g. a replacement pod) with no trained state
            # and no checkpoint to restore must not report metrics from
            # randomly initialised params.  Re-queue for a worker that has
            # either.  (ADVICE r1: a configured-but-empty checkpoint dir
            # counts as *no* trained state.)
            raise TransientTaskError(
                "worker has no trained state for evaluation; re-queueing"
            )
        records = 0
        all_labels, all_preds = [], []
        eval_state, actual_version = None, None
        for batch, real in self._data_service.batches_for_task(
            task, self.minibatch_size, self._feed,
            feed_bulk=self._feed_bulk,
        ):
            if actual_version is None:
                # Eval-at-version (§3.5): score the checkpointed state at
                # the requested version when retrievable; otherwise label
                # metrics with the step actually evaluated.
                self._owner.ensure_state(batch)
                eval_state, actual_version = self._owner.state_for_eval(
                    task.model_version
                )
            preds = self._owner.predict_batch(batch, state=eval_state)
            all_labels.append(np.asarray(batch["labels"])[:real])
            all_preds.append(preds[:real])
            records += real
        if records:
            # Metrics computed once over the whole shard (not averaged per
            # batch) so rank-based metrics like AUC stay faithful.
            labels = np.concatenate(all_labels)
            preds = np.concatenate(all_preds)
            version = (
                actual_version
                if actual_version is not None and actual_version >= 0
                else self._owner.step
            )
            metrics = {
                name: float(fn(labels, preds))
                for name, fn in self.spec.eval_metrics.items()
            }
            report_evaluation_with_samples(
                self._client, self.worker_id, version,
                metrics, records, labels, preds, task_id=task.task_id,
            )
            self._summary.scalars(
                {f"eval/{k}": v for k, v in metrics.items()},
                step=version,
            )
        return records

    def _predict_task(self, task: pb.Task) -> int:
        records = 0
        # keyed by task_id and only committed on task completion: a
        # mid-task failure + re-queue must not leave partial rows that a
        # rerun would duplicate (the SPMD path keys the same way)
        self.predictions = getattr(self, "predictions", {})
        processor = self.spec.prediction_outputs_processor
        rows = []
        for batch, real in self._data_service.batches_for_task(
            task, self.minibatch_size, self._feed,
            feed_bulk=self._feed_bulk,
        ):
            preds = self._owner.predict_batch(batch)
            rows.append(preds[:real])
            records += real
        if rows:
            self.predictions[task.task_id] = np.concatenate(rows)
            if processor is not None:
                # reference C18 contract, buffered per task (ADVICE r3):
                # a mid-task failure + re-queue must not deliver partial
                # duplicate rows to the sink.  Delivery is at-least-once
                # at TASK granularity (a crash between this flush and the
                # completion report re-runs the whole task).
                for chunk in rows:
                    processor.process(chunk, self.worker_id)
        return records

    def _maybe_remesh(self):
        """Elastic cycle: if the membership epoch moved, rebuild the mesh
        and re-place (or restore) state before processing the next task."""
        if self._elastic is None:
            return
        try:
            spec = self._elastic.fetch_spec()
        except Exception as exc:
            # The spec fetch sits outside the per-task error handling; a
            # transient failure (master briefly unreachable, injected
            # rendezvous fault) must skip this remesh round, not kill the
            # worker — the next loop iteration fetches again.
            logger.warning("cluster spec fetch failed: %s; will retry", exc)
            return
        if not self._elastic.is_new_epoch(spec):
            return
        mesh = self._elastic.build_mesh(spec)
        if mesh is None:
            return
        self._owner.remesh(mesh)

    def _feed(self, records):
        return self.spec.feed(records, getattr(self._reader, "metadata", {}))

    @property
    def _feed_bulk(self):
        """Vectorized-parse closure for batches_for_task, or None when the
        zoo module has no feed_bulk (the streaming feed path then runs).
        With --wire_format (or legacy --compact_wire) and the matching
        zoo feed, batches parse straight into that device wire format."""
        if self.wire_format == "dedup":
            fn = self.spec.feed_bulk_dedup
        elif self.compact_wire:
            fn = self.spec.feed_bulk_compact
        else:
            fn = self.spec.feed_bulk
        if fn is None:
            return None
        metadata = getattr(self._reader, "metadata", {})
        return lambda buf, sizes: fn(buf, sizes, metadata)


def _task_export_config(task: pb.Task) -> dict:
    """Parse a SAVE_MODEL task's JSON config rider ({output, saved_model})."""
    if not task.extended_config:
        return {}
    import json

    try:
        return json.loads(task.extended_config)
    except ValueError:
        logger.warning(
            "Bad extended_config on task %d: %r",
            task.task_id, task.extended_config,
        )
        return {}


def export_for_task(state, spec, task: pb.Task,
                    sample_features=None) -> bool:
    """Export the model if the SAVE_MODEL task's rider names an output dir.

    Raises when an export was requested but there is no trained state —
    a silent skip would let the job report success with args.output never
    written; raising re-queues the task for a worker that has state.
    """
    config = _task_export_config(task)
    output = config.get("output", "")
    if not output:
        return False
    if state is None:
        raise RuntimeError(
            "SAVE_MODEL requested an export but this worker has no "
            "trained state; re-queueing"
        )
    from elasticdl_tpu.common.export import export_model

    export_model(
        state, spec, output,
        saved_model=bool(config.get("saved_model", False)),
        sample_features=sample_features,
    )
    logger.info("Exported model to %s", output)
    return True
