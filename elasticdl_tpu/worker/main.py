"""Worker process entry point.

Parity: reference python/worker/main.py (SURVEY.md C7).  Connects to the
master over gRPC, loads the model-zoo spec, builds the device mesh, runs
the task loop.
"""

from __future__ import annotations

import os

from elasticdl_tpu.common import args as args_lib
from elasticdl_tpu.common.constants import (
    GRPC_MAX_MESSAGE_LENGTH,
    WorkerEnv,
)
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.model_handler import get_model_spec
from elasticdl_tpu.data.reader import create_data_reader

logger = get_logger(__name__)


def build_master_client(addr: str, retry_policy=None):
    import grpc

    from elasticdl_tpu.common.resilience import (
        default_policy,
        wait_for_channel_ready,
    )
    from elasticdl_tpu.proto.service import MasterStub

    policy = retry_policy if retry_policy is not None else default_policy()
    channel = grpc.insecure_channel(
        addr,
        options=[
            ("grpc.max_send_message_length", GRPC_MAX_MESSAGE_LENGTH),
            ("grpc.max_receive_message_length", GRPC_MAX_MESSAGE_LENGTH),
        ],
    )
    # Bounded, jittered wait instead of a bare 60s block: a master that
    # never comes up turns into RetryBudgetExhausted -> exit code 45, a
    # charged relaunch, rather than an opaque hang-then-crash.
    wait_for_channel_ready(channel, policy)
    return MasterStub(channel, retry_policy=policy)


def start_keep_alive(client, worker_id: int, master_addr: str) -> str:
    """Self-report this worker's reachable address immediately, then keep
    reporting liveness on a daemon thread.  The address report closes the
    real-k8s gap where the watch delivers RUNNING before the pod IP is
    assigned (the coordinator address must never fall back to localhost on
    multi-host)."""
    import threading
    import time

    from elasticdl_tpu.common.constants import KEEP_ALIVE_INTERVAL_S
    from elasticdl_tpu.common.net_utils import get_reachable_address
    from elasticdl_tpu.proto import elasticdl_pb2 as pb

    address = get_reachable_address(master_addr)

    def beat():
        try:
            client.keep_alive(
                pb.KeepAliveRequest(
                    worker_id=worker_id,
                    timestamp_ms=int(time.time() * 1000),
                    address=address,
                )
            )
        except Exception:
            pass  # master briefly unreachable; liveness is best-effort

    beat()

    def loop():
        while True:
            time.sleep(KEEP_ALIVE_INTERVAL_S)
            beat()

    threading.Thread(target=loop, daemon=True).start()
    return address


def wait_for_membership(client, worker_id: int, poll_s: float = 0.5):
    """Block until this worker appears in a settled, group-confirmed
    cluster spec (see elasticdl_tpu.worker.spmd.wait_for_confirmed_epoch).
    """
    from elasticdl_tpu.worker.spmd import wait_for_confirmed_epoch

    return wait_for_confirmed_epoch(client, worker_id, poll_s=poll_s)


def main(argv=None):
    import sys

    from elasticdl_tpu.common import faults
    from elasticdl_tpu.common.resilience import (
        RETRY_EXHAUSTED_EXIT_CODE,
        RetryBudgetExhausted,
    )

    # Chaos runs propagate their seeded fault schedule to subprocess
    # workers via the environment; no-op otherwise.
    faults.configure_from_env()
    try:
        return _main(argv)
    except RetryBudgetExhausted as exc:
        # The master stayed unreachable past the whole retry budget
        # (at startup or mid-run).  Exit with the distinct charged code
        # so the pod manager relaunches us instead of us spinning on a
        # dead control plane.
        logger.error("Worker retry budget exhausted: %s", exc)
        sys.exit(RETRY_EXHAUSTED_EXIT_CODE)


def _main(argv=None):
    args = args_lib.parse_worker_args(argv)
    # honor the job's persistent compile cache (--compilation_cache_dir,
    # or a parent-provided env var) even though sitecustomize imported
    # jax before either was visible to it.  A relaunched worker then
    # loads the train-step executable instead of recompiling — the
    # biggest single chunk of elastic recovery time.
    from elasticdl_tpu.common.virtual_mesh import (
        apply_compilation_cache_config,
    )

    apply_compilation_cache_config(args.compilation_cache_dir)
    worker_id = int(
        os.environ.get(WorkerEnv.WORKER_ID, args.worker_id)
    )
    master_addr = os.environ.get(WorkerEnv.MASTER_ADDR, args.master_addr)
    # Cross-process tracing: --event_log wins; otherwise the master
    # exported ELASTICDL_EVENT_LOG into our environment (same wire as
    # the chaos schedule).
    from elasticdl_tpu.common import events

    if getattr(args, "event_log", ""):
        events.configure(args.event_log, role="worker",
                         worker_id=worker_id)
    else:
        events.configure_from_env(role="worker", worker_id=worker_id)
    # /metrics + /healthz + /varz.  Always an ephemeral port: worker argv
    # is the master's re-serialized argv, so a fixed port would collide
    # when master and workers share a host (tests, ProcessK8sClient).
    from elasticdl_tpu.common.telemetry import TelemetryServer

    telemetry = TelemetryServer(role="worker")
    try:
        telemetry.start()
        logger.info("Worker %d telemetry on port %d",
                    worker_id, telemetry.port)
    except Exception:
        logger.exception("telemetry server failed to start")
    from elasticdl_tpu.common.resilience import default_policy

    budget = getattr(args, "rpc_retry_budget_s", 0.0)
    rpc_policy = (
        default_policy(max_elapsed_s=budget) if budget else default_policy()
    )
    client = build_master_client(master_addr, retry_policy=rpc_policy)
    spec = get_model_spec(
        args.model_zoo,
        args.model_def,
        model_params=args.model_params,
        dataset_fn=args.dataset_fn,
        loss=args.loss,
        optimizer=args.optimizer,
        eval_metrics_fn=args.eval_metrics_fn,
        prediction_outputs_processor=getattr(
            args, "prediction_outputs_processor", ""
        ),
        arena_dtype=getattr(args, "arena_dtype", ""),
        store_cache_dtype=getattr(args, "store_cache_dtype", ""),
    )
    if spec.custom_data_reader is not None:
        reader = spec.custom_data_reader(data_origin=args.training_data)
    else:
        reader = create_data_reader(args.training_data)

    from elasticdl_tpu.worker.worker import Worker

    saver_factory = None
    if args.checkpoint_dir:
        # NOT constructed here: Orbax touches the XLA backend, and in
        # cluster mode jax.distributed.initialize must run first (the
        # SPMDWorker calls the factory inside setup()).
        def saver_factory():
            from elasticdl_tpu.common.save_utils import CheckpointSaver

            return CheckpointSaver(
                args.checkpoint_dir, keep_max=args.keep_checkpoint_max
            )

    tb_dir = (
        os.path.join(args.tensorboard_log_dir, f"worker-{worker_id}")
        if args.tensorboard_log_dir
        else ""
    )

    if args.distribution_strategy != "Local" and args.num_workers > 1:
        # Cluster SPMD: all worker processes form ONE global mesh and run
        # the same collective step — there is one model by construction
        # (worker/spmd.py).  Rank/topology comes from the master's
        # rendezvous; wait until this worker is a member of a settled
        # epoch.
        from elasticdl_tpu.proto import elasticdl_pb2 as pb
        from elasticdl_tpu.worker.spmd import SPMDWorker

        my_addr = start_keep_alive(client, worker_id, master_addr)
        cluster, me = wait_for_membership(client, worker_id)
        logger.info(
            "Worker %d joined epoch %d as rank %d/%d (addr=%s, "
            "coordinator=%s)",
            worker_id, cluster.rendezvous_id, me.rank, cluster.world_size,
            my_addr, cluster.coordinator_address,
        )
        worker = SPMDWorker(
            worker_id=worker_id,
            master_client=client,
            data_reader=reader,
            spec=spec,
            minibatch_size=args.minibatch_size,
            process_id=me.rank,
            num_processes=cluster.world_size,
            coordinator_address=cluster.coordinator_address,
            use_bf16=args.use_bf16,
            checkpoint_saver_factory=saver_factory,
            checkpoint_steps=args.checkpoint_steps,
            initial_epoch=cluster.rendezvous_id,
            output_dir=getattr(args, "output", ""),
            wedge_grace_s=args.wedge_grace_s,
            steps_per_execution=getattr(args, "steps_per_execution", 1),
            compact_wire=getattr(args, "compact_wire", False),
            wire_format=getattr(args, "wire_format", ""),
            tensorboard_dir=tb_dir,
            profile_dir=(
                os.path.join(args.profile_dir, f"worker-{worker_id}")
                if args.profile_dir
                else ""
            ),
            rpc_policy=rpc_policy,
        )
    else:
        worker = Worker(
            worker_id=worker_id,
            master_client=client,
            data_reader=reader,
            spec=spec,
            minibatch_size=args.minibatch_size,
            use_bf16=args.use_bf16,
            checkpoint_saver=saver_factory() if saver_factory else None,
            checkpoint_steps=args.checkpoint_steps,
            steps_per_execution=getattr(args, "steps_per_execution", 1),
            compact_wire=getattr(args, "compact_wire", False),
            wire_format=getattr(args, "wire_format", ""),
            tensorboard_dir=tb_dir,
            profile_dir=(
                os.path.join(args.profile_dir, f"worker-{worker_id}")
                if args.profile_dir
                else ""
            ),
        )
    drain_fn = (
        worker.save_checkpoint_and_flush
        if hasattr(worker, "save_checkpoint_and_flush")
        else worker.model_owner.save_and_flush
    )
    if saver_factory is not None:
        # Preemptible VMs: SIGTERM arrives with a grace window — flush one
        # final synchronous checkpoint so the next topology restores from
        # the last step, not the last periodic save (SURVEY.md §5).
        from elasticdl_tpu.common.preemption import install_preemption_hook

        install_preemption_hook(drain_fn)
    notice_source = getattr(args, "preemption_notice_file", "")
    if notice_source:
        # Maintenance-event awareness (SURVEY §7 C4 mapping): act on the
        # NOTICE — drain at a task boundary and checkpoint while the
        # grace window is still all ours — instead of racing the kill.
        from elasticdl_tpu.common.preemption import (
            MaintenanceNoticeWatcher,
            any_notice_checker,
            file_notice_checker,
            gce_metadata_checker,
        )

        checker = (
            any_notice_checker(
                gce_metadata_checker("preempted"),
                gce_metadata_checker("maintenance-event"),
            )
            if notice_source == "gce-metadata"
            else file_notice_checker(notice_source)
        )
        # The notice hook only SETS the drain flag; the main thread
        # checkpoints at its next task boundary (a save from the watcher
        # thread would race the training loop's state mutation).
        MaintenanceNoticeWatcher(checker, worker.drain_and_stop).start()

    ok = worker.run()
    logger.info("Worker %d exiting (clean=%s)", worker_id, ok)


if __name__ == "__main__":
    main()
