"""Model ownership and intra-process synchronization.

The reference kept every worker on ONE shared model: PS mode served all
workers from one parameter store (SURVEY.md C10, call stack §3.3);
AllReduce mode kept replicas in lockstep via Horovod (C15, §3.4).  The
TPU-native analogue inside one process is a single `ModelOwner`: one
Trainer + one TrainState shared by every worker thread, updates serialized
under a lock.  Semantically this is the reference's *async PS* — each
worker computes gradients against the params as of its own step start, and
applies them atomically — with staleness bounded by the number of threads
instead of by network latency.

Cross-process synchronization (cluster mode) is NOT this file's job: that
is SPMD over a global mesh (worker/spmd.py), where consistency holds by
construction because every process executes the same collective step.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)


class ModelOwner:
    """Owns one model replica: trainer + state + update lock + checkpoints.

    Workers never touch TrainState directly; everything flows through the
    owner so N workers sharing one owner train one model (the property the
    reference's whole PS/AllReduce machinery exists to provide).
    """

    def __init__(
        self,
        trainer,
        seed: int = 0,
        checkpoint_saver=None,
        checkpoint_steps: int = 0,
    ):
        from elasticdl_tpu.worker.trainer import run_device_serialized

        self.trainer = trainer
        self.lock = threading.RLock()
        self.state = None
        self.sample_features = None
        # serialized: owners are constructed on the pod-relaunch path
        # while sibling workers are mid-step, and the key creation is a
        # device op (see trainer._CPU_EXEC_LOCK)
        self._rng = run_device_serialized(
            lambda: jax.random.PRNGKey(seed)
        )
        self.checkpoint_saver = checkpoint_saver
        self.checkpoint_steps = checkpoint_steps

    # ---- state lifecycle ----------------------------------------------

    def ensure_state(self, batch) -> None:
        with self.lock:
            if self.sample_features is None:
                # one host row, kept for export signatures (SavedModel
                # needs the feature structure/shapes/dtypes)
                import numpy as np

                self.sample_features = jax.tree.map(
                    lambda a: np.asarray(a[:1]), batch["features"]
                )
            if self.state is not None:
                return
            self.state = self.trainer.init_state(
                self._rng, batch["features"]
            )
            if self.checkpoint_saver is not None:
                restored = self.checkpoint_saver.maybe_restore(self.state)
                if restored is not None:
                    self.state = restored
                    logger.info("Restored state from checkpoint")

    def has_trained_state(self) -> bool:
        """True if the owner holds (or can restore) non-random params."""
        from elasticdl_tpu.worker.trainer import run_device_serialized

        with self.lock:
            if self.state is not None and run_device_serialized(
                lambda: int(self.state.step)
            ) > 0:
                return True
            return (
                self.checkpoint_saver is not None
                and self.checkpoint_saver.latest_step() is not None
            )

    @property
    def step(self) -> int:
        from elasticdl_tpu.worker.trainer import run_device_serialized

        with self.lock:
            if self.state is None:
                return 0
            # serialized device->host fetch: a transfer racing another
            # thread's step execution corrupts the CPU backend
            return run_device_serialized(lambda: int(self.state.step))

    # ---- serialized model operations ----------------------------------

    def train_batch(self, batch):
        with self.lock:
            self.ensure_state(batch)
            self.state, loss = self.trainer.train_on_batch(
                self.state, batch
            )
            self._maybe_checkpoint()
            return loss

    def train_batch_stack(self, batches):
        """steps_per_execution path: len(batches) steps in one dispatch
        (Trainer.train_on_batch_stack); returns the per-step losses."""
        with self.lock:
            self.ensure_state(batches[0])
            self.state, losses = self.trainer.train_on_batch_stack(
                self.state, batches
            )
            self._maybe_checkpoint(stride=len(batches))
            return losses

    def stage_batch(self, batch):
        """Start batch's host->device transfer (Trainer.stage_batch) and
        return the placed batch for a later train_batch call — the
        double-buffering hook prefetch_batches' device_stage calls.
        ensure_state runs FIRST, on the host batch: its export-signature
        snapshot and init want host arrays, and init_state must precede
        any same-shaped device work anyway."""
        with self.lock:
            self.ensure_state(batch)
            return self.trainer.stage_batch(batch)

    def predict_batch(self, batch, state=None):
        """Forward pass; `state` overrides the owner's current state (eval
        at a restored version)."""
        with self.lock:
            self.ensure_state(batch)
            use = self.state if state is None else state
            return self.trainer.predict_on_batch(use, batch["features"])

    def save(self, force: bool = False) -> None:
        with self.lock:
            if self.checkpoint_saver is not None and self.state is not None:
                self.checkpoint_saver.save(self.state, force=force)

    def save_and_flush(self) -> None:
        """Synchronous final checkpoint (preemption hook)."""
        self.save(force=True)
        if self.checkpoint_saver is not None:
            self.checkpoint_saver.wait_until_finished()

    def _maybe_checkpoint(self, stride: int = 1) -> None:
        """Checkpoint when [step-stride, step] crossed a multiple of
        checkpoint_steps.  `stride` is the number of steps the last
        dispatch advanced (steps_per_execution): an exact-modulo check
        would skip every multiple the K-step jump lands past, stretching
        the cadence to lcm(K, checkpoint_steps)."""
        if (
            self.checkpoint_saver is not None
            and self.checkpoint_steps
            and self.state is not None
            and int(self.state.step) % self.checkpoint_steps < stride
        ):
            self.checkpoint_saver.save(self.state)

    def snapshot(self):
        """Donation-safe copy of the current state (see snapshot_state)."""
        with self.lock:
            return snapshot_state(self.state)

    def state_for_eval(self, requested_version: int):
        """Resolve the state an eval task should score (SURVEY.md §3.5:
        the reference evaluated the model at the task's version, pulled
        from the PS — here the checkpoint store is the version archive).

        Returns (state, actual_version): the checkpointed state at the
        requested version when it is retrievable, else the current state
        labeled with its TRUE step so the master never aggregates metrics
        under a version the model isn't at.
        """
        with self.lock:
            return state_at_version(
                self.state, self.checkpoint_saver, requested_version
            )

    # ---- elastic re-mesh ----------------------------------------------

    def remesh(self, mesh) -> None:
        """Point the trainer at a new mesh and re-place existing state."""
        with self.lock:
            self.trainer.set_mesh(mesh)
            if self.state is not None:
                self.state = self.trainer.replace_state(self.state)


def snapshot_state(state):
    """Donation-safe FORWARD-ONLY copy of a TrainState.

    The train step donates its input state (donate_argnums), so a caller
    that captures the LIVE state object and keeps using it across batches
    — an eval task scoring one consistent version while another worker
    thread keeps training — would read buffers the next train step has
    already donated (XLA: "Buffer has been deleted or donated", which on
    the multi-device CPU backend also wedges the whole device queue).
    Copying under the owner's lock orders the copy before any later
    donation.

    Only step/params/model_state are copied — everything a forward pass
    reads.  opt_state (2x param memory under Adam) keeps the live
    reference: eval/export never touch it, and copying it would roughly
    triple the snapshot's memory cost.  Do NOT train on a snapshot."""
    if state is None:
        return None
    import jax.numpy as jnp

    def copy_tree(tree):
        return jax.tree.map(
            lambda a: jnp.copy(a) if isinstance(a, jax.Array) else a, tree
        )

    return state.replace(
        step=copy_tree(state.step),
        params=copy_tree(state.params),
        model_state=copy_tree(state.model_state),
    )


def state_at_version(state, checkpoint_saver, requested_version: int):
    """Shared eval-at-version resolution (thread/SPMD workers).

    (state, actual_version) where actual_version is what the metrics must
    be labeled with.  The returned state is always safe to hold across
    batches: either a fresh restore or a donation-safe snapshot of the
    live state (see snapshot_state)."""
    current = -1 if state is None else int(state.step)
    if requested_version < 0 or requested_version == current:
        return snapshot_state(state), current
    if checkpoint_saver is not None and state is not None:
        restored = checkpoint_saver.restore_step(requested_version, state)
        if restored is not None:
            return restored, requested_version
    logger.info(
        "Eval at version %d not retrievable (current step %d, no "
        "checkpoint); evaluating current state",
        requested_version, current,
    )
    return snapshot_state(state), current
