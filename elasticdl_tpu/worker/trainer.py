"""The XLA-compiled training engine.

This replaces three reference components at once (SURVEY.md §7 design
mapping):

- the worker's eager `tf.GradientTape` step (C7),
- the parameter-server optimizer application, Python and Go/Eigen
  (C10/C16/C17) — Optax inside the jitted step; XLA *is* the native
  kernel,
- Horovod's dense-gradient AllReduce (C15) — gradient reduction over the
  mesh `data` axis is inserted by XLA from the NamedShardings.

One `jit`-compiled function owns forward + backward + optimizer update;
params/opt state live replicated (or sharded) on the mesh, the batch is
split along `data`.  bfloat16 compute keeps the MXU fed; params stay f32.
"""

from __future__ import annotations

import threading
import time
from functools import partial
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import NamedSharding, PartitionSpec as P

from elasticdl_tpu.common import programs
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.layers.arena import fold_quantized_updates
from elasticdl_tpu.parallel import mesh as mesh_lib

logger = get_logger(__name__)

# Process-wide device-execution serialization for the CPU backend.  The
# virtual multi-device CPU platform (xla_force_host_platform_device_count)
# can deadlock when two THREADS dispatch multi-device programs
# concurrently: each program's collectives rendezvous over the same
# device threads, and once interleaved neither completes — observed as a
# permanently wedged `jax.Array._value` that then blocks every later
# fetch in the process.  Serializing dispatch+completion removes the
# interleaving.  On TPU the hardware queue order is the serialization and
# this lock is never taken.
_CPU_EXEC_LOCK = threading.Lock()


def run_device_serialized(fn, *args):
    """Call fn(*args); on the CPU backend, hold the process-wide execution
    lock and block until the result is ready (see _CPU_EXEC_LOCK)."""
    if jax.default_backend() != "cpu":
        return fn(*args)
    with _CPU_EXEC_LOCK:
        return jax.block_until_ready(fn(*args))


def model_has_train_kwarg(model) -> bool:
    """Whether the model's __call__ takes the zoo contract's `train`
    kwarg (BatchNorm/dropout models).  Shared by the Trainer and the
    SavedModel export so train-time eval and serving stay in lockstep."""
    import inspect

    try:
        return "train" in inspect.signature(type(model).__call__).parameters
    except (TypeError, ValueError):
        return False


def _sown_aux_loss(intermediates) -> jnp.ndarray:
    """Sum every `moe_aux_loss` value sown anywhere in the module tree
    (already scaled by its coefficient at sow time).  Zero when nothing
    was sown — models without auxiliary objectives are unaffected."""
    total = jnp.zeros((), jnp.float32)
    for path, leaf in jax.tree_util.tree_leaves_with_path(intermediates):
        names = [getattr(k, "key", str(k)) for k in path]
        if "moe_aux_loss" in names:
            total = total + jnp.asarray(leaf, jnp.float32)
    return total


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any          # trainable variables ({"params": ...})
    opt_state: Any
    model_state: Any = struct.field(default_factory=dict)  # batch_stats etc.


class Trainer:
    """Builds and owns the jitted train/eval steps for one model.

    model_fn: flax Module (or any object with .init/.apply) — the zoo's
              `custom_model()`
    loss_fn:  (labels, predictions) -> scalar  — the zoo's `loss`
    optimizer: optax.GradientTransformation    — the zoo's `optimizer()`
    """

    # Step-phase attribution hook (common/profiler.PhaseTimer).  Class
    # default so trainers built by tests (or through __new__ scaffolding)
    # run untimed; the worker runtime assigns the process-wide timer.
    # Trainer-level because BOTH worker loops (threaded and SPMD) end up
    # here: h2d_stage covers stage_batch, compute covers the train
    # dispatch (including CPU-backend lock wait — attributing contention
    # to compute is deliberate: it IS time the step spent not overlapped).
    phase_timer = None

    # Tiered embedding store (elasticdl_tpu/store).  When set, batches
    # carry a `__store_plan__` admission plan the trainer must execute
    # against the state BEFORE the step that consumes the batch's slots.
    # Class default so __new__-built trainers (tests) stay flat.
    tiered_store = None

    def _timed(self, phase_name: str, fn, *args):
        timer = self.phase_timer
        if timer is None:
            return fn(*args)
        start = time.perf_counter()
        try:
            return fn(*args)
        finally:
            timer.add(phase_name, time.perf_counter() - start)

    def __init__(
        self,
        model,
        optimizer,
        loss_fn: Callable,
        mesh=None,
        use_bf16: bool = False,
        param_sharding_fn: Optional[Callable] = None,
    ):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh if mesh is not None else mesh_lib.create_mesh()
        self.use_bf16 = use_bf16
        self._param_sharding_fn = param_sharding_fn
        self._repl = mesh_lib.replicated(self.mesh)
        self._data = mesh_lib.data_sharding(self.mesh)
        # Models with train-time behavior (BatchNorm, dropout) take a
        # `train` kwarg per the zoo contract; plain models need not.
        self._has_train_kwarg = model_has_train_kwarg(model)
        self._build_steps()

    def set_mesh(self, mesh):
        """Elastic re-mesh: subsequent batches/state placements target the
        new mesh.  The jitted steps need no rebuild — they are polymorphic
        over input shardings."""
        self.mesh = mesh
        self._repl = mesh_lib.replicated(mesh)
        self._data = mesh_lib.data_sharding(mesh)

    def replace_state(self, state: "TrainState") -> "TrainState":
        """Re-place existing state onto the current mesh (single-process
        resharding; multi-host restores from checkpoint instead).  The
        device->host copy and re-placement are one serialized device
        operation: a remesh racing another thread's step execution
        corrupts the CPU backend (see _CPU_EXEC_LOCK)."""

        def _replace():
            # Safe asarray: the view is consumed by device_put inside the
            # same serialized device operation, so no donating step can
            # rewrite the buffer while it is live.
            host_state = jax.tree.map(  # graftlint: disable=GL-DONATE
                lambda x: np.asarray(x) if hasattr(x, "shape") else x, state
            )
            return jax.device_put(host_state, self.state_sharding(state))

        return run_device_serialized(_replace)

    # ---- state ---------------------------------------------------------

    def init_state(self, rng, sample_features) -> TrainState:
        return run_device_serialized(
            self._init_state_impl, rng, sample_features
        )

    def _init_state_impl(self, rng, sample_features) -> TrainState:
        mesh_lib.set_current_mesh(self.mesh)
        kwargs = {"train": False} if self._has_train_kwarg else {}
        variables = dict(
            self.model.init(rng, self._cast(sample_features), **kwargs)
        )
        # Split trainable ("params") from mutable model state (e.g.
        # batch_stats); the optimizer sees only the former.
        params = {"params": variables.pop("params")}
        model_state = variables
        state = TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=self.optimizer.init(params),
            model_state=model_state,
        )
        return jax.device_put(state, self.state_sharding(state))

    def init_state_global(self, rng, sample_features) -> TrainState:
        """Multi-process SPMD init: the whole init (model.init + optimizer
        init) runs as ONE jitted program with `out_shardings` over the
        global mesh, so every process participates in the same computation
        and the resulting state is identical across ranks by construction
        (no host-side broadcast needed — the reference's AllReduce mode had
        to broadcast variables from rank 0 instead, SURVEY.md §3.4)."""
        mesh_lib.set_current_mesh(self.mesh)
        kwargs = {"train": False} if self._has_train_kwarg else {}
        features = jax.tree.map(np.asarray, sample_features)

        def make():
            variables = dict(
                self.model.init(rng, self._cast(features), **kwargs)
            )
            params = {"params": variables.pop("params")}
            return TrainState(
                step=jnp.zeros((), jnp.int32),
                params=params,
                opt_state=self.optimizer.init(params),
                model_state=variables,
            )

        shapes = jax.eval_shape(make)
        shardings = self.state_sharding(shapes)
        return run_device_serialized(
            programs.registered_jit(
                "worker_init_state", make, out_shardings=shardings
            )
        )

    def state_sharding(self, state):
        """Sharding tree for the train state: replicated by default;
        `param_sharding_fn(path, value) -> PartitionSpec` overrides (used
        by sharded embedding tables / tensor parallelism)."""
        if self._param_sharding_fn is None:
            return jax.tree.map(lambda _: self._repl, state)

        def spec_for(path, leaf):
            spec = self._param_sharding_fn(path, leaf)
            return NamedSharding(self.mesh, spec if spec is not None else P())

        # model_state replicates EXCEPT the "quantized" collection: its
        # int8/scale planes mirror arena tables and must row-shard with
        # them (the path contains "embedding", so the same sharding fn
        # applies).
        model_state_sh = {
            key: (
                jax.tree_util.tree_map_with_path(spec_for, sub)
                if key == "quantized"
                else jax.tree.map(lambda _: self._repl, sub)
            )
            for key, sub in state.model_state.items()
        }

        params_sh = jax.tree_util.tree_map_with_path(spec_for, state.params)
        # Optax states embed per-param moment trees with the SAME pytree
        # structure as params (mu/nu in Adam, trace in momentum, ...);
        # shard those like the params and replicate everything else
        # (counts, scalars).  Structure matching — not shape matching —
        # so same-shaped params with different specs stay distinct.
        param_treedef = jax.tree.structure(state.params)

        def is_param_like(subtree):
            try:
                return jax.tree.structure(subtree) == param_treedef
            except Exception:
                return False

        def shard_subtree(subtree):
            if is_param_like(subtree):
                return params_sh
            return jax.tree.map(lambda _: self._repl, subtree)

        opt_sh = jax.tree.map(
            shard_subtree, state.opt_state, is_leaf=is_param_like
        )
        return TrainState(
            step=self._repl,
            params=params_sh,
            opt_state=opt_sh,
            model_state=model_state_sh,
        )

    def _cast(self, features):
        if not self.use_bf16:
            return features
        return jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
            else x,
            features,
        )

    # ---- steps ---------------------------------------------------------

    def _build_steps(self):
        def loss_of(params, model_state, features, labels):
            variables = {**params, **model_state}
            kwargs = {"train": True} if self._has_train_kwarg else {}
            # "intermediates" is always mutable in the TRAIN step so
            # layer-sown auxiliary objectives (MoE load balancing) reach
            # the loss; sown values are ephemeral and never enter the
            # persistent model_state.
            mutable = list(model_state.keys()) + ["intermediates"]
            preds, updates = self.model.apply(
                variables, self._cast(features), mutable=mutable, **kwargs
            )
            updates = dict(updates)
            intermediates = updates.pop("intermediates", {})
            new_model_state = updates if updates else model_state
            loss = jnp.asarray(
                self.loss_fn(labels, preds.astype(jnp.float32)), jnp.float32
            )
            loss = loss + _sown_aux_loss(intermediates)
            return loss, new_model_state

        def train_step(state: TrainState, batch):
            (loss, new_model_state), grads = jax.value_and_grad(
                loss_of, has_aux=True
            )(
                state.params, state.model_state,
                batch["features"], batch["labels"],
            )
            updates, opt_state = self.optimizer.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            # Quantized arenas: fold the carrier's delta back into the
            # int8 planes with stochastic rounding and zero the carrier.
            # Trace-time no-op when no "quantized" collection exists, so
            # the fp32 path stays bit-identical (layers/arena.py).
            params, new_model_state = fold_quantized_updates(
                params, new_model_state, state.step
            )
            return (
                TrainState(
                    step=state.step + 1,
                    params=params,
                    opt_state=opt_state,
                    model_state=new_model_state,
                ),
                loss,
            )

        def eval_step(state: TrainState, features):
            variables = {**state.params, **state.model_state}
            kwargs = {"train": False} if self._has_train_kwarg else {}
            preds = self.model.apply(
                variables, self._cast(features), **kwargs
            )
            return preds.astype(jnp.float32)

        def train_step_many(state: TrainState, stacked):
            # K serially-dependent train steps in ONE dispatched program
            # (lax.scan over a (K, B, ...) batch stack).  This is
            # `steps_per_execution`: per-dispatch overhead — significant
            # on remote/tunneled TPU runtimes (measured ~0.8s/call on the
            # axon tunnel vs ~0.2s device work) — is paid once per K
            # steps, and XLA overlaps the scan's iterations' transfers
            # and compute.
            return jax.lax.scan(train_step, state, stacked)

        # Shardings: batch split on `data`; XLA inserts the gradient
        # all-reduce from the sharding propagation (no explicit psum).
        self.train_step = programs.registered_jit(
            "worker_train_step", train_step, donate_argnums=(0,)
        )
        self.train_step_many = programs.registered_jit(
            "worker_train_step_many", train_step_many, donate_argnums=(0,)
        )
        self.eval_step = programs.registered_jit(
            "worker_eval_step", eval_step
        )

    # ---- host-side helpers --------------------------------------------

    def stage_batch(self, batch: Dict[str, np.ndarray]):
        """Start `batch`'s host->device transfer NOW; return the placed
        batch (an overlap handle) for a later train_on_batch call.

        Double buffering's second half: device_put is asynchronous on
        real backends, so staging batch k+1 while batch k executes hides
        the transfer behind compute.  train_on_batch re-shards the
        staged result, which is a no-op for an array already placed with
        the same sharding — staged and unstaged batches flow through the
        same path.  Must be called from the ONE thread that drives the
        device (prefetch_batches stages on the consumer thread): on the
        CPU backend the transfer rides inside the serialized region
        (_CPU_EXEC_LOCK), on TPU it's a plain async enqueue."""
        mesh_lib.set_current_mesh(self.mesh)
        # A store admission plan (or, in deferred multi-worker mode, the
        # raw sparse batch awaiting planning) is host bookkeeping, not
        # batch data — pop it around the shard (tree_map would treat it
        # as a leaf and try to device_put it), reattach on a copy after.
        carried = {
            k: batch[k]
            for k in ("__store_plan__", "__store_sparse__")
            if k in batch
        }
        if carried:
            batch = {k: v for k, v in batch.items() if k not in carried}
        staged = self._timed(
            "h2d_stage", run_device_serialized,
            mesh_lib.shard_batch, batch, self.mesh,
        )
        if carried:
            staged = dict(staged)
            staged.update(carried)
        return staged

    def train_on_batch(self, state, batch: Dict[str, np.ndarray]):
        mesh_lib.set_current_mesh(self.mesh)  # for mesh-aware model code

        # Tiered store: execute the batch's admission plan first — every
        # slot the step is about to gather must be cache-resident, and
        # evicted rows must be read out before their slots are reused.
        plan = batch.get("__store_plan__")
        if plan is not None:
            batch = {k: v for k, v in batch.items() if k != "__store_plan__"}
            if self.tiered_store is not None:
                state = self.tiered_store.apply_plan(state, plan)

        # Deferred multi-worker mode: the feed shipped the raw sparse
        # batch instead of a plan.  prepare+apply run back to back HERE,
        # inside the step-serialized region (ModelOwner's lock), so plans
        # are produced in exactly the order steps execute — the strict
        # batch-order invariant holds with any number of feed producers.
        pending = batch.get("__store_sparse__")
        if pending is not None:
            batch = {
                k: v for k, v in batch.items() if k != "__store_sparse__"
            }
            if self.tiered_store is not None:
                sparse, ranked = pending
                slots, plan = self.tiered_store.prepare(sparse, ranked=ranked)
                features = dict(batch["features"])
                features["slots"] = slots
                batch = dict(batch)
                batch["features"] = features
                state = self.tiered_store.apply_plan(state, plan)

        # The batch transfer rides inside the serialized region: a
        # device_put racing another thread's step execution corrupts the
        # virtual multi-device CPU backend (see _CPU_EXEC_LOCK).
        def _step():
            sharded = mesh_lib.shard_batch(batch, self.mesh)
            return self.train_step(state, sharded)

        state, loss = self._timed("compute", run_device_serialized, _step)
        return state, loss

    def train_on_batch_stack(self, state, batches):
        """One dispatch covering len(batches) train steps (jitted
        lax.scan).  Returns (state, losses) with losses shaped (K,).
        Batches must share shapes (the data service's static-shape
        contract guarantees it)."""
        from elasticdl_tpu.data.wire import is_packed_dedup

        mesh_lib.set_current_mesh(self.mesh)

        # Tiered store under steps_per_execution > 1 (ISSUE 18c): the K
        # steps run as ONE uninterruptible scan, so admissions are
        # planned once over the UNION of all K batches' rows and applied
        # before the block — every step sees its rows resident, folds
        # land once per block.  Eager per-batch plans are rejected: plan
        # k+1's evictions could reuse a slot batch k still reads, with
        # no apply point between the fused steps (client/api.py forces
        # deferred planning for this reason).
        if any("__store_plan__" in b for b in batches):
            raise ValueError(
                "eager per-batch store plans cannot cover a fused "
                "multi-step block — use TieredStore.enable_deferred_"
                "prepare() so the raw sparse batches arrive here and "
                "one union plan covers the whole block"
            )
        if any("__store_sparse__" in b for b in batches):
            pendings = [b.get("__store_sparse__") for b in batches]
            batches = [
                {k: v for k, v in b.items() if k != "__store_sparse__"}
                for b in batches
            ]
            if self.tiered_store is not None:
                if any(p is None for p in pendings):
                    raise ValueError(
                        "mixed store-prepared and raw batches in one "
                        "fused block"
                    )
                slots_list, plan = self.tiered_store.prepare_block(
                    [sparse for sparse, _ranked in pendings]
                )
                for b, slots in zip(batches, slots_list):
                    features = dict(b["features"])
                    features["slots"] = slots
                    b["features"] = features
                state = self.tiered_store.apply_plan(state, plan)

        stacked = self._timed(
            "pack",
            lambda: jax.tree.map(lambda *xs: np.stack(xs), *batches),
        )
        sharding = mesh_lib.stacked_data_sharding(self.mesh)
        repl = mesh_lib.replicated(self.mesh)

        def put(x):
            if is_packed_dedup(x):
                # only inverse8 is batch-major under the (K, ...) stack;
                # the side planes replicate (see mesh.shard_batch)
                return {
                    k: jax.device_put(
                        v, sharding if k == "inverse8" else repl
                    )
                    for k, v in x.items()
                }
            return jax.device_put(x, sharding)

        def _step():
            placed = jax.tree.map(put, stacked, is_leaf=is_packed_dedup)
            return self.train_step_many(state, placed)

        return self._timed("compute", run_device_serialized, _step)

    def train_on_global_batch_stack(self, state, global_stacked):
        """K-step scan on an already-assembled global (K, B, ...) stack
        (mesh.make_global_batch_stack_from_local) — the multi-process
        steps_per_execution hot path.  Returns (state, losses (K,))."""
        mesh_lib.set_current_mesh(self.mesh)
        return self._timed(
            "compute", run_device_serialized,
            self.train_step_many, state, global_stacked,
        )

    def train_on_global_batch(self, state, global_batch):
        """Train step on a batch already assembled into global arrays
        (mesh.make_global_batch) — the multi-process SPMD hot path."""
        mesh_lib.set_current_mesh(self.mesh)
        return self._timed(
            "compute", run_device_serialized,
            self.train_step, state, global_batch,
        )

    def predict_on_global_batch(self, state, global_features):
        """Forward pass on global arrays; returns the still-global (data-
        sharded) predictions — callers allgather if they need host values."""
        mesh_lib.set_current_mesh(self.mesh)
        return run_device_serialized(self.eval_step, state, global_features)

    def predict_on_batch(self, state, features):
        from elasticdl_tpu.data.wire import is_packed_dedup

        mesh_lib.set_current_mesh(self.mesh)
        repl = mesh_lib.replicated(self.mesh)

        def put(x):
            if is_packed_dedup(x):
                # same placement rule as mesh.shard_batch: only inverse8
                # is batch-major; the side planes replicate
                return {
                    k: jax.device_put(
                        v, self._data if k == "inverse8" else repl
                    )
                    for k, v in x.items()
                }
            return jax.device_put(x, self._data)

        def _step():
            placed = jax.tree.map(put, features, is_leaf=is_packed_dedup)
            return np.asarray(self.eval_step(state, placed))

        return run_device_serialized(_step)

    # ---- elastic prewarm ----------------------------------------------

    def prewarm_for_device_counts(
        self, sample_batch, device_counts, rng=None, block: bool = False,
    ):
        """Populate the persistent compile cache with this model's
        train-step executables for EXPECTED post-failure mesh sizes
        (SURVEY §7 hard part 1's named mitigation): a remesh after a
        preemption then restores with a disk-cache read (measured ~5x
        faster than the cold compile) instead of a fresh XLA compile.

        Runs host-side only — states are abstract ShapeDtypeStructs; no
        device memory is touched.  Data-parallel-default meshes only
        (the elastic unit shrinks along `data`); counts not dividing the
        fixed axes are skipped.  Compiles in a daemon thread unless
        `block` (tests).  Requires identical XLA flags in the restarted
        process for the cache key to match — true for pod relaunches,
        which re-serialize the same argv/env.

        ELASTICDL_FORCE_PREWARM=1 overrides the starved-host core-count
        guard (used by the warm-recovery drill, whose 1-core CI box
        would otherwise never exercise the prewarm path it asserts).
        """
        import os
        import threading

        force = os.environ.get("ELASTICDL_FORCE_PREWARM") == "1"
        if not force and not block and (os.cpu_count() or 1) < 4:
            # A background XLA compile on a starved host (1-2 cores —
            # CI boxes) competes with the training loop for the SAME
            # cores and can stall it past the wedge-watchdog grace
            # (observed in the cluster drills: a 25s prewarm compile got
            # the rank shot as wedged).  Real TPU hosts have 100+ vCPUs;
            # skip only where the background work would do net harm.
            logger.info(
                "prewarm skipped: %s cores is too few to compile in the "
                "background without starving the training loop",
                os.cpu_count(),
            )
            return None
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        features = jax.tree.map(np.asarray, sample_batch["features"])

        def work():
            for count in device_counts:
                try:
                    self._prewarm_one(count, features, sample_batch, rng)
                except Exception as exc:  # advisory path, never fatal
                    logger.info(
                        "prewarm for %d devices skipped: %s", count, exc
                    )

        if block:
            work()
            return None
        thread = threading.Thread(target=work, daemon=True)
        thread.start()
        return thread

    def _prewarm_one(self, count, features, sample_batch, rng):
        import time as _time

        t0 = _time.perf_counter()
        devices = jax.devices()
        if not 0 < count <= len(devices):
            return
        mesh = mesh_lib.create_mesh(devices[:count])
        warm = Trainer(
            model=self.model, optimizer=self.optimizer,
            loss_fn=self.loss_fn, mesh=mesh, use_bf16=self.use_bf16,
            param_sharding_fn=self._param_sharding_fn,
        )
        prev_mesh = mesh_lib.get_current_mesh()
        kwargs = {"train": False} if self._has_train_kwarg else {}

        def make():
            variables = dict(
                self.model.init(rng, warm._cast(features), **kwargs)
            )
            params = {"params": variables.pop("params")}
            return TrainState(
                step=jnp.zeros((), jnp.int32),
                params=params,
                opt_state=self.optimizer.init(params),
                model_state=variables,
            )

        # everything tracing under the prewarm mesh sits inside the
        # try/finally: a failure anywhere (eval_shape, sharding, lower)
        # must not leak the small mesh into the caller thread's TLS
        # (block=True runs on the caller's thread)
        mesh_lib.set_thread_mesh(mesh)
        try:
            shapes = jax.eval_shape(make)
            shardings = warm.state_sharding(shapes)
            abstract_state = jax.tree.map(
                lambda s, sh: jax.ShapeDtypeStruct(
                    s.shape, s.dtype, sharding=sh
                ),
                shapes, shardings,
            )
            abstract_batch = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(
                    np.asarray(a).shape, np.asarray(a).dtype,
                    sharding=warm._data,
                ),
                sample_batch,
            )
            warm.train_step.aot_compile(abstract_state, abstract_batch)
        finally:
            mesh_lib.set_thread_mesh(prev_mesh)
        logger.info(
            "prewarmed train step for %d-device mesh in %.1fs (persistent"
            " cache populated)", count, _time.perf_counter() - t0,
        )

    def timed_steps_per_sec_fused(self, state, batch, iters: int = 40):
        """Device-honest step rate: ONE jitted program runs `iters`
        serially-dependent train steps via lax.fori_loop and returns two
        scalars — the step counter AND an anchor folded from the final
        params — synced with a value fetch.

        Why not time per-call dispatch (a Python loop over train_step
        with block_until_ready)?  Measured pitfalls on remote/tunneled
        devices: (a) async dispatch makes block_until_ready under-report
        badly — the loop can time Python dispatch, not device work
        (observed >100% "MFU"); (b) returning the full TrainState from
        the timed program makes the runtime stage hundreds of MB per
        call (observed 30x slowdown).

        The params ANCHOR is load-bearing: returning only the step
        counter lets XLA's while-loop simplifier dead-code-eliminate the
        entire training chain (step+1 does not depend on params), and
        the 'measured' loop then costs one device round trip regardless
        of iters — verified on this machine (8 vs 32 iters: identical
        ~95ms totals; with the anchor: 22.9ms per real step).  A scalar
        folded from the final params forces every iteration's
        forward+backward+update to execute."""
        batch = mesh_lib.shard_batch(batch, self.mesh)
        cache = getattr(self, "_fused_timing_cache", None)
        if cache is None:
            cache = self._fused_timing_cache = {}
        fused = cache.get(iters)
        if fused is None:
            # one jitted closure per iters value: a fresh jax.jit each
            # call would recompile identical shapes on every repeat
            def multi(s, b):
                def body(_, s2):
                    s3, _loss = self.train_step(s2, b)
                    return s3

                out = jax.lax.fori_loop(0, iters, body, s)
                # every param leaf: anchoring a subset would let the
                # partitioner prune the unused leaves' gradient/update
                # branches (Adam state chains stay live through params)
                anchor = sum(
                    leaf.ravel()[0].astype(jnp.float32)
                    for leaf in jax.tree.leaves(out.params)
                )
                # quantized arenas: the int8 planes live in model_state
                # and the fold chain feeds ONLY them (the carrier is
                # zeroed) — without anchoring them XLA would DCE the
                # whole requantize and overstate int8 speed
                anchor = anchor + sum(
                    leaf.ravel()[0].astype(jnp.float32)
                    for leaf in jax.tree.leaves(
                        out.model_state.get("quantized", {})
                    )
                )
                return out.step, anchor

            fused = cache[iters] = programs.registered_jit(
                "worker_timed_fused", multi
            )
        # warm once per (iters, shapes): compile + first-exec costs; later
        # repeats (bench medians) skip it — re-warming every repeat would
        # double the device work under a wall-clock-budgeted driver
        warmed = getattr(self, "_fused_timing_warmed", None)
        if warmed is None:
            warmed = self._fused_timing_warmed = set()
        key = (iters, tuple(
            (tuple(x.shape), str(x.dtype))
            for x in jax.tree.leaves(batch)
        ))
        if key not in warmed:
            jax.device_get(fused(state, batch))
            warmed.add(key)
        start = time.perf_counter()
        jax.device_get(fused(state, batch))
        return iters / (time.perf_counter() - start)
