"""Seeded, replayable open-loop traffic generator for the serve tier.

The autoscaling loop (master/policy.py ServingPolicyEngine) is only
testable if the load that drives it is reproducible: a flaky load
source makes every scaling decision a flaky assertion.  This generator
is therefore **open-loop** (the offered schedule never depends on how
the fleet answered — a shed or a failure does not slow the next tick,
exactly the regime where admission control and autoscaling matter) and
**fully derived from the seed**:

- The per-tick request count is Poisson with rate
  `base_qps * factor(tick) * tick_interval_s`, sampled by Knuth's
  product method from `random.Random` so the draw is bit-identical
  across platforms (no numpy RNG in the schedule path).
- `factor(tick)` comes from the profile, a closed TRAFFIC_PROFILES
  vocabulary: `poisson` (flat), `spike` (a step to `spike_factor`x for
  `spike_ticks` ticks at `spike_at_tick` — the bench.py --traffic
  scenario), `diurnal` (a sinusoid), `ramp` (linear climb to
  `spike_factor`x over `ramp_ticks`).
- Request shapes draw from the closed REQUEST_SHAPES batch-row catalog
  and spread round-robin over `clients` logical client loops.  The
  loops run interleaved on the calling thread: concurrency here would
  only add nondeterminism, and the router already exercises its lock
  paths under the chaos tests.
- Each tick's draws come from a tick-keyed RNG, so an injected
  `traffic.tick` fault (the generator skipping a tick, modelling a
  stalled load source) cannot shift the schedule of later ticks: the
  replay stays byte-identical whether or not chaos fired.

The generator never imports the router; it calls an injected
`request_fn(client_id, rows, payload_seed) -> "ok"|"shed"|"failed"`.
`router_request_fn` adapts a FleetRouter (+ an encode function from the
model zoo) into that shape for bench.py and the online pipeline.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from math import exp, pi, sin
from typing import Callable, List, Optional

from elasticdl_tpu.common import faults
from elasticdl_tpu.common import metrics as metrics_lib
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)

#: Closed profile vocabulary — `--traffic_profile` must name one of
#: these, and docs/SERVING.md documents each shape.
TRAFFIC_PROFILES = frozenset({"poisson", "spike", "diurnal", "ramp"})

#: Closed batch-row catalog: every generated request carries one of
#: these row counts, so the serving batcher's fill ratio is driven by
#: arrival rate, never by unbounded shape variety.
REQUEST_SHAPES = (1, 2, 4, 8)

_OUTCOMES = frozenset({"ok", "shed", "failed"})


@dataclass
class TrafficConfig:
    """Knobs for one generator run (docs/SERVING.md maps each to its
    --traffic_* flag where one exists)."""

    profile: str = "poisson"
    base_qps: float = 50.0
    clients: int = 4
    seed: int = 0
    tick_interval_s: float = 1.0
    spike_at_tick: int = 10          # spike: first elevated tick
    spike_ticks: int = 5             # spike: elevated tick count
    spike_factor: float = 5.0        # spike/ramp: peak multiplier
    ramp_ticks: int = 20             # ramp: ticks to reach the peak
    diurnal_period_ticks: int = 24   # diurnal: sinusoid period
    amplitude: float = 0.5           # diurnal: swing around 1.0

    def __post_init__(self):
        assert self.profile in TRAFFIC_PROFILES, self.profile
        assert self.base_qps >= 0.0
        assert self.clients >= 1
        assert self.tick_interval_s > 0.0


def _poisson(rng: random.Random, lam: float) -> int:
    """Knuth's product method: exact Poisson from uniform draws only,
    so the schedule replays bit-identically on any platform.  Rates in
    this codebase are tens-per-tick; the O(lam) cost is irrelevant."""
    if lam <= 0.0:
        return 0
    limit = exp(-lam)
    k = 0
    product = rng.random()
    while product > limit:
        k += 1
        product *= rng.random()
    return k


def router_request_fn(router, encode_fn,
                      ok_codes=None, shed_codes=None) -> Callable:
    """Adapt a FleetRouter into the generator's request_fn shape.

    `encode_fn(rows, payload_seed)` builds the model-specific feature
    payload (seeded, so a replay offers byte-identical tensors); the
    response code classifies the outcome against the serving proto's
    shed vocabulary.  Transport exceptions — including a whole-fleet
    sweep failure — classify as "failed"."""
    from elasticdl_tpu.proto import serving_pb2 as spb
    from elasticdl_tpu.proto.service import SHED_CODES
    from elasticdl_tpu.serving.server import make_predict_request

    ok_codes = ok_codes if ok_codes is not None else (spb.SERVING_OK,)
    shed_codes = shed_codes if shed_codes is not None else SHED_CODES

    def request_fn(client_id: int, rows: int, payload_seed: int) -> str:
        del client_id  # identical clients; the id only orders the log
        try:
            response = router.predict(
                make_predict_request(encode_fn(rows, payload_seed))
            )
        except faults.DroppedRequest:
            return "failed"
        except Exception:
            return "failed"
        if response.code in ok_codes:
            return "ok"
        if response.code in shed_codes:
            return "shed"
        return "failed"

    return request_fn


class TrafficGenerator:
    """Drives `request_fn` with the seeded open-loop schedule.

    Tests and bench.py call `tick()` by hand (injectable clock-free
    design: nothing here reads a wall clock); each tick fires the
    `traffic.tick` fault point before offering anything, so chaos can
    stall the load source for a tick without perturbing the schedule
    of the ticks around it."""

    def __init__(self, request_fn: Callable[[int, int, int], str],
                 config: TrafficConfig):
        self._request_fn = request_fn
        self.config = config
        self._tick = 0
        self._offered = 0
        self._ok = 0
        self._shed = 0
        self._failed = 0
        self._tick_faults = 0
        self._last_offered = 0
        #: per-tick offered counts in tick order — the replayable
        #: schedule the determinism tests byte-compare.
        self.schedule: List[int] = []
        #: per-tick outcome records (clock-free).
        self.log: List[dict] = []

        self.metrics_registry = metrics_lib.MetricsRegistry()
        self._offered_total = self.metrics_registry.counter(
            "traffic_requests_offered_total",
            "requests the open-loop schedule offered the fleet",
        )
        self._ok_total = self.metrics_registry.counter(
            "traffic_requests_ok_total",
            "offered requests the fleet answered SERVING_OK",
        )
        self._shed_total = self.metrics_registry.counter(
            "traffic_requests_shed_total",
            "offered requests the whole fleet shed",
        )
        self._failed_total = self.metrics_registry.counter(
            "traffic_requests_failed_total",
            "offered requests that failed outright (transport error "
            "or non-OK, non-shed response)",
        )
        self._ticks_total = self.metrics_registry.counter(
            "traffic_ticks_total",
            "generator ticks executed (faulted ticks included)",
        )
        self._tick_faults_total = self.metrics_registry.counter(
            "traffic_tick_faults_total",
            "ticks the traffic.tick fault point stalled (schedule "
            "unchanged; the tick offered nothing)",
        )
        self.metrics_registry.gauge_fn(
            "traffic_offered_per_sec",
            lambda: self._last_offered / self.config.tick_interval_s,
            "offered rate over the last tick",
        )
        self.metrics_registry.gauge_fn(
            "traffic_shed_ratio",
            lambda: self._shed / self._offered if self._offered else 0.0,
            "lifetime fraction of offered requests the fleet shed",
        )

    # ---- the schedule --------------------------------------------------

    def _factor(self, tick: int) -> float:
        cfg = self.config
        if cfg.profile == "spike":
            inside = (cfg.spike_at_tick <= tick
                      < cfg.spike_at_tick + cfg.spike_ticks)
            return cfg.spike_factor if inside else 1.0
        if cfg.profile == "diurnal":
            phase = 2.0 * pi * tick / max(1, cfg.diurnal_period_ticks)
            return max(0.0, 1.0 + cfg.amplitude * sin(phase))
        if cfg.profile == "ramp":
            frac = min(1.0, tick / max(1, cfg.ramp_ticks))
            return 1.0 + (cfg.spike_factor - 1.0) * frac
        return 1.0  # poisson: flat

    def _tick_rng(self, tick: int) -> random.Random:
        # Tick-keyed, not one consumed stream: a faulted (skipped) tick
        # must not shift the draws of every later tick, or chaos runs
        # and clean runs would see different schedules for the same
        # seed.
        return random.Random((self.config.seed << 20) ^ (tick + 1))

    def plan(self, tick: int) -> List[tuple]:
        """The (client_id, rows, payload_seed) entries tick `tick`
        offers — pure function of (seed, config, tick)."""
        cfg = self.config
        rng = self._tick_rng(tick)
        lam = cfg.base_qps * self._factor(tick) * cfg.tick_interval_s
        count = _poisson(rng, lam)
        entries = []
        for i in range(count):
            rows = REQUEST_SHAPES[rng.randrange(len(REQUEST_SHAPES))]
            payload_seed = rng.randrange(1 << 31)
            entries.append((i % cfg.clients, rows, payload_seed))
        return entries

    # ---- the loop body -------------------------------------------------

    def tick(self) -> dict:
        """Offer one tick's schedule; returns the clock-free tick
        record (also appended to `self.log`)."""
        tick = self._tick
        self._tick += 1
        self._ticks_total.inc()
        entries = self.plan(tick)
        self.schedule.append(len(entries))
        try:
            faults.fire(faults.POINT_TRAFFIC_TICK)
        except faults.InjectedFault:
            # The load source stalled for a tick.  Offer nothing; the
            # schedule entry is already recorded, so the replay stays
            # byte-identical with or without the chaos schedule.
            self._tick_faults += 1
            self._tick_faults_total.inc()
            self._last_offered = 0
            record = {"tick": tick, "offered": 0, "ok": 0, "shed": 0,
                      "failed": 0, "faulted": True}
            self.log.append(record)
            return record
        ok = shed = failed = 0
        for client_id, rows, payload_seed in entries:
            outcome = self._request_fn(client_id, rows, payload_seed)
            assert outcome in _OUTCOMES, outcome
            if outcome == "ok":
                ok += 1
            elif outcome == "shed":
                shed += 1
            else:
                failed += 1
        offered = len(entries)
        self._offered += offered
        self._ok += ok
        self._shed += shed
        self._failed += failed
        self._last_offered = offered
        self._offered_total.inc(offered)
        self._ok_total.inc(ok)
        self._shed_total.inc(shed)
        self._failed_total.inc(failed)
        record = {"tick": tick, "offered": offered, "ok": ok,
                  "shed": shed, "failed": failed, "faulted": False}
        self.log.append(record)
        return record

    def run(self, ticks: int) -> List[dict]:
        return [self.tick() for _ in range(ticks)]

    # ---- bookkeeping ---------------------------------------------------

    def shed_ratio(self) -> float:
        return self._shed / self._offered if self._offered else 0.0

    def offered_qps(self) -> float:
        """Mean offered rate over the run so far."""
        if self._tick == 0:
            return 0.0
        return self._offered / (self._tick * self.config.tick_interval_s)

    def snapshot(self) -> dict:
        """Clock-free; byte-comparable across same-seed runs."""
        return {
            "profile": self.config.profile,
            "seed": self.config.seed,
            "ticks": self._tick,
            "offered": self._offered,
            "ok": self._ok,
            "shed": self._shed,
            "failed": self._failed,
            "tick_faults": self._tick_faults,
            "offered_qps": round(self.offered_qps(), 3),
            "shed_ratio": round(self.shed_ratio(), 4),
            "schedule": list(self.schedule),
        }
