"""Replayable open-loop traffic for the serving control loop.

`generator.py` turns a seed plus a profile name from the closed
TRAFFIC_PROFILES vocabulary into a byte-identical request schedule and
drives the fleet router with it — the load side of the autoscaling
story in docs/SERVING.md "Autoscaling & backpressure".
"""

from elasticdl_tpu.traffic.generator import (  # noqa: F401
    REQUEST_SHAPES,
    TRAFFIC_PROFILES,
    TrafficConfig,
    TrafficGenerator,
    router_request_fn,
)
