"""Sharded tiered store: row-range partitioning + shard handoff.

The single-worker `TieredStore` (tiered.py) binds one producer to one
consumer; the elastic claim of the paper needs the opposite — workers
joining and dying freely while the embedding state they were
responsible for survives.  This module adds that layer:

* `ShardMap` — the row space `wire.field_disjoint_ids` induces is
  partitioned into `num_shards` shards (`shard = row % num_shards`,
  stable under lazy vocabulary growth: a row's shard never changes as
  the vocab grows).  Shards are assigned to workers round-robin and the
  map rebalances deterministically on worker death/join, so same-seed
  chaos runs replay byte-identically.

* `ShardedTieredStore` — ONE master-resident `HostTier` (the bulk tier
  survives any worker's death) plus a per-shard `HotRowCache` slice.
  Admission planning partitions the dedup wire's batch-global frequency
  ranking per shard — order is preserved within each shard, so the
  global admission order is exactly the concatenation the single-cache
  plan would have produced shard-locally.  Global cache slots are
  `shard_index * per_shard_capacity + local_slot`.

* Shard handoff — on worker death or policy eviction the master
  reassigns the dead worker's shards to the least-loaded alive
  successors.  Each move fires the `store.shard_handoff` fault point
  (docs/ROBUSTNESS.md): an injected fault defers that move (retried on
  the next handoff call), it never loses it.  The successor starts with
  an empty cache slice (residency is rebuilt by admission traffic); its
  host-tier slice can be rebuilt from the checkpoint sidecar plus the
  deterministic backfill seed when the host copy is lost
  (`rebuild_shard`).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from elasticdl_tpu.common import events, faults
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.metrics import MetricsRegistry
from elasticdl_tpu.data.wire import frequency_rank
from elasticdl_tpu.store.cache import HotRowCache
from elasticdl_tpu.store.host_tier import HostTier

logger = get_logger(__name__)


class ShardMap:
    """shard -> worker assignment with deterministic rebalancing.

    All decisions are pure functions of the current assignment and the
    sorted worker ids — no clocks, no randomness — so a chaos run's
    handoff sequence is byte-stable across same-seed replays.
    """

    def __init__(self, num_shards: int, workers):
        if num_shards < 1:
            raise ValueError("need at least one shard")
        self.num_shards = int(num_shards)
        # Liveness is an EXPLICIT register, not derived from the owner
        # map: a shard whose evacuation was deferred by an injected
        # fault still names its dead owner, and that corpse must never
        # be picked as a handoff target.
        self._workers: List[int] = sorted({int(w) for w in workers})
        if not self._workers:
            raise ValueError("need at least one worker")
        self._owner: Dict[int, int] = {
            s: self._workers[s % len(self._workers)]
            for s in range(self.num_shards)
        }

    # ---- queries --------------------------------------------------------

    def owner(self, shard: int) -> int:
        return self._owner[int(shard)]

    def workers(self) -> List[int]:
        return list(self._workers)

    def worker_shards(self, worker_id: int) -> List[int]:
        return sorted(
            s for s, w in self._owner.items() if w == int(worker_id)
        )

    def shard_of_rows(self, rows: np.ndarray) -> np.ndarray:
        return np.asarray(rows, np.int64) % self.num_shards

    def as_dict(self) -> Dict[int, int]:
        return dict(self._owner)

    # ---- rebalancing ----------------------------------------------------

    def least_loaded(self) -> int:
        """Least-loaded REGISTERED worker (ties toward the smallest id)
        — the handoff target, chosen at apply time so a move deferred by
        a fault re-targets against the liveness at retry, not at plan."""
        loads = {w: 0 for w in self._workers}
        for w in self._owner.values():
            if w in loads:
                loads[w] += 1
        return min(self._workers, key=lambda w: (loads[w], w))

    def remove_worker(self, worker_id: int) -> List[int]:
        """Deregister a dead/evicted worker; returns the shards needing
        evacuation (owner unchanged until each move applies)."""
        worker_id = int(worker_id)
        if worker_id not in self._workers:
            return []
        if len(self._workers) == 1:
            raise ValueError("cannot remove the last worker")
        self._workers.remove(worker_id)
        return self.worker_shards(worker_id)

    def add_worker(self, worker_id: int) -> List[int]:
        """Register a joiner; returns its fair share of shards to
        migrate, taken from the most-loaded donors (ties toward the
        largest worker id, so low-id workers keep their shards)."""
        worker_id = int(worker_id)
        if worker_id in self._workers:
            return []
        self._workers.append(worker_id)
        self._workers.sort()
        target = self.num_shards // len(self._workers)
        shards: List[int] = []
        donors = [w for w in self._workers if w != worker_id]
        loads = {w: len(self.worker_shards(w)) for w in donors}
        for _ in range(target):
            donor = max(donors, key=lambda w: (loads[w], w))
            if loads[donor] <= 1:
                break
            candidates = [
                s for s in self.worker_shards(donor) if s not in shards
            ]
            if not candidates:
                break
            loads[donor] -= 1
            shards.append(max(candidates))
        return shards

    def apply_move(self, shard: int, new_owner: int) -> None:
        self._owner[int(shard)] = int(new_owner)


@dataclass
class ShardedPlan:
    """One batch's merged per-shard admission schedule."""

    slots: np.ndarray                  # (B, F) int32 GLOBAL cache slots
    rows: np.ndarray                   # (B, F) int64 store rows
    admit_rows: np.ndarray             # (K,) int64
    evict_rows: np.ndarray             # (E,) int64
    hits: int
    misses: int
    growth: int = 0
    by_shard: Dict[int, int] = field(default_factory=dict)  # lookups/shard


class ShardedTieredStore:
    """Multi-worker tiered store: one shared host tier, per-shard cache
    slices, deterministic shard handoff.

    Unlike `TieredStore` this class is safe to drive from multiple
    logical workers: every operation takes the store lock, and plans
    stay per-shard so no cross-worker ordering is required beyond the
    lock's serialization.
    """

    def __init__(
        self,
        planes: Dict[str, int],
        num_fields: int,
        cache_rows: int,
        num_shards: int,
        workers,
        host_dtype: str = "fp32",
        seed: int = 0x5EED,
        registry: Optional[MetricsRegistry] = None,
    ):
        self.planes = dict(planes)
        self.num_fields = int(num_fields)
        self.num_shards = int(num_shards)
        self.per_shard_rows = max(1, int(cache_rows) // self.num_shards)
        self.cache_rows = self.per_shard_rows * self.num_shards
        self.host = HostTier(planes, num_fields, host_dtype, seed)
        self.map = ShardMap(num_shards, workers)
        self._caches: Dict[int, HotRowCache] = {
            s: HotRowCache(self.per_shard_rows)
            for s in range(self.num_shards)
        }
        self._lock = threading.Lock()
        self._pending_moves: List[Tuple[int, int]] = []   # (shard, old)
        self.registry = registry if registry is not None else MetricsRegistry()
        self._hits = self.registry.counter(
            "store_cache_hits_total",
            "Embedding lookups served by the device hot-row cache",
        )
        self._misses = self.registry.counter(
            "store_cache_misses_total",
            "Embedding lookups that needed a host-tier admission",
        )
        self._growth = self.registry.counter(
            "store_growth_rows_total",
            "Vocabulary rows lazily grown on first lookup",
        )
        self._handoffs = self.registry.counter(
            "store_shard_handoffs_total",
            "shard row-ranges reassigned to a successor worker",
        )
        self._handoff_faults = self.registry.counter(
            "store_shard_handoff_faults_total",
            "handoff moves deferred by an injected store.shard_handoff "
            "fault",
        )
        self.registry.gauge_fn(
            "store_shard_pending_handoffs_count",
            lambda: float(len(self._pending_moves)),
            "deferred shard moves awaiting retry",
        )

    # ---- admission planning --------------------------------------------

    def prepare(self, sparse: np.ndarray) -> ShardedPlan:
        """Plan one batch: grow vocab, then partition the batch-global
        frequency ranking per shard and plan each shard's cache slice.
        The global frequency order is preserved inside every shard (a
        boolean mask keeps relative order), so shard-local admission
        matches what the single global cache would have admitted for
        those rows."""
        sparse = np.asarray(sparse, np.int64)
        with self._lock:
            rows, n_new = self.host.assign(sparse)
            flat = np.asarray(rows, np.int64).reshape(-1)
            uniq, counts = frequency_rank(flat)
            shard_of_flat = self.map.shard_of_rows(flat)
            shard_of_uniq = self.map.shard_of_rows(uniq)
            global_slots = np.empty(flat.size, np.int64)
            admit_rows: List[np.ndarray] = []
            evict_rows: List[np.ndarray] = []
            hits = misses = 0
            by_shard: Dict[int, int] = {}
            for shard in np.unique(shard_of_uniq):
                shard = int(shard)
                lookup_mask = shard_of_flat == shard
                rank_mask = shard_of_uniq == shard
                plan = self._caches[shard].plan(
                    flat[lookup_mask],
                    ranked=(uniq[rank_mask], counts[rank_mask]),
                )
                offset = shard * self.per_shard_rows
                global_slots[lookup_mask] = (
                    plan.slots.reshape(-1).astype(np.int64) + offset
                )
                admit_rows.append(plan.admit_rows)
                evict_rows.append(plan.evict_rows)
                hits += plan.hits
                misses += plan.misses
                by_shard[shard] = int(lookup_mask.sum())
        self._hits.inc(hits)
        self._misses.inc(misses)
        if n_new:
            self._growth.inc(n_new)
            events.emit(events.STORE_GROWN, rows=n_new,
                        vocab_rows=self.host.size)
        return ShardedPlan(
            slots=global_slots.reshape(rows.shape).astype(np.int32),
            rows=rows,
            admit_rows=(
                np.concatenate(admit_rows) if admit_rows
                else np.empty(0, np.int64)
            ),
            evict_rows=(
                np.concatenate(evict_rows) if evict_rows
                else np.empty(0, np.int64)
            ),
            hits=hits,
            misses=misses,
            growth=n_new,
            by_shard=by_shard,
        )

    # ---- statistics plane (the online pipeline's consumer) --------------

    def fold_stats(self, rows: np.ndarray, clicked: np.ndarray,
                   plane: str = "ctr") -> None:
        """Accumulate [impressions, clicks] per store row into a host
        plane — the write-back that makes the host tier live state a
        handoff must not lose (the chaos test pins its byte stability)."""
        rows = np.asarray(rows, np.int64).reshape(-1)
        clicked = np.asarray(clicked, np.float32).reshape(-1)
        if rows.size == 0:
            return
        uniq, inverse = np.unique(rows, return_inverse=True)
        imps = np.bincount(inverse, minlength=uniq.size).astype(np.float32)
        clk = np.bincount(
            inverse, weights=clicked, minlength=uniq.size
        ).astype(np.float32)
        with self._lock:
            cur = self.host.gather(uniq, planes=(plane,))[plane]
            cur[:, 0] += imps
            if cur.shape[1] > 1:
                cur[:, 1] += clk
            self.host.set_rows(uniq, {plane: cur})

    # ---- shard handoff --------------------------------------------------

    def handoff(self, dead_worker: Optional[int] = None,
                sidecar=None) -> List[Tuple[int, int, int]]:
        """Reassign `dead_worker`'s shards (plus any moves a previous
        injected fault deferred).  Every move fires `store.shard_handoff`
        first: a raised fault defers THAT move — retried on the next
        call — and the rest proceed, so chaos never wedges the whole
        evacuation.  Returns the completed (shard, old, new) moves.

        The successor's cache slice starts empty (admission traffic
        rebuilds residency); when `sidecar` is given the shard's host
        rows are also rebuilt from it (`rebuild_shard`) — the lost-host
        recovery path."""
        with self._lock:
            moves = list(self._pending_moves)
            self._pending_moves = []
            if dead_worker is not None:
                moves.extend(
                    (s, int(dead_worker))
                    for s in self.map.remove_worker(dead_worker)
                )
            completed = self._apply_moves_locked(moves, sidecar)
        self._emit_moves(completed)
        return completed

    def join(self, new_worker: int,
             sidecar=None) -> List[Tuple[int, int, int]]:
        """Rebalance toward a joining worker (plus any deferred moves):
        same per-move fault/deferral semantics as `handoff`."""
        with self._lock:
            moves = list(self._pending_moves)
            self._pending_moves = []
            moves.extend(
                (s, self.map.owner(s))
                for s in self.map.add_worker(new_worker)
            )
            completed = self._apply_moves_locked(moves, sidecar)
        self._emit_moves(completed)
        return completed

    def _apply_moves_locked(self, moves, sidecar):
        """`moves` is (shard, old_owner) — the TARGET is chosen at apply
        time (`ShardMap.least_loaded`), so a deferred move retried after
        further deaths/joins lands on a worker that is actually alive."""
        completed: List[Tuple[int, int, int]] = []
        for shard, old in moves:
            try:
                faults.fire(faults.POINT_STORE_SHARD_HANDOFF)
            except faults.InjectedFault as exc:
                self._handoff_faults.inc()
                self._pending_moves.append((shard, old))
                logger.warning(
                    "shard %d handoff from %d deferred (%s)",
                    shard, old, exc,
                )
                continue
            new = self.map.least_loaded()
            # the moved shard's residency belonged to the old
            # worker's device table — the successor starts cold
            self._caches[shard].reset()
            if sidecar is not None:
                self._rebuild_shard_locked(shard, sidecar)
            self.map.apply_move(shard, new)
            completed.append((shard, old, new))
        return completed

    def _emit_moves(self, completed) -> None:
        for shard, old, new in completed:
            self._handoffs.inc()
            events.emit(
                events.STORE_SHARD_HANDOFF,
                shard=shard, from_worker=old, to_worker=new,
            )

    def pending_handoffs(self) -> int:
        with self._lock:
            return len(self._pending_moves)

    def shard_rows(self, shard: int) -> np.ndarray:
        """Assigned store rows belonging to `shard`."""
        n = self.host.size
        all_rows = np.arange(n, dtype=np.int64)
        return all_rows[all_rows % self.num_shards == int(shard)]

    def rebuild_shard(self, shard: int, sidecar) -> int:
        """Rebuild one shard's host-tier slice: sidecar values for rows
        the checkpoint covers, the deterministic backfill seed for rows
        grown since (host_tier.row_init_values keys on the row index, so
        the re-init equals the original init).  Returns rows rebuilt."""
        with self._lock:
            return self._rebuild_shard_locked(shard, sidecar)

    def _rebuild_shard_locked(self, shard: int, sidecar) -> int:
        rows = self.shard_rows(shard)
        if rows.size == 0:
            return 0
        covered_n = int(sidecar.meta.get("vocab_rows", 0))
        covered = rows[rows < covered_n]
        fresh = rows[rows >= covered_n]
        if covered.size:
            values = {
                name: sidecar.latest_row_values(name)[covered]
                for name in self.planes
            }
            self.host.set_rows(covered, values)
        if fresh.size:
            self.host.reinit_rows(fresh)
        return int(rows.size)

    # ---- checkpoint integration -----------------------------------------

    def cache_state(self) -> Dict[str, np.ndarray]:
        """Per-shard residency arrays for the sharded sidecar."""
        out: Dict[str, np.ndarray] = {}
        with self._lock:
            for shard, cache in self._caches.items():
                row_of, score, _ = cache.state_arrays()
                out[f"shard{shard}__row_of"] = row_of
                out[f"shard{shard}__score"] = score
        return out

    def load_cache_state(self, arrays: Dict[str, np.ndarray]) -> None:
        with self._lock:
            for shard, cache in self._caches.items():
                row_of = arrays.get(f"shard{shard}__row_of")
                if row_of is None:
                    continue
                cache.load_state_arrays(
                    row_of, arrays.get(f"shard{shard}__score")
                )

    # ---- introspection --------------------------------------------------

    def stats(self) -> dict:
        hits = self._hits.value()
        misses = self._misses.value()
        total = hits + misses
        with self._lock:
            occupancy = sum(c.occupancy for c in self._caches.values())
            owners = self.map.as_dict()
            pending = len(self._pending_moves)
        return {
            "hit_rate": (hits / total) if total else 0.0,
            "hits": int(hits),
            "misses": int(misses),
            "growth_rows": int(self._growth.value()),
            "vocab_rows": self.host.size,
            "cache_occupancy_rows": occupancy,
            "cache_rows": self.cache_rows,
            "num_shards": self.num_shards,
            "per_shard_rows": self.per_shard_rows,
            "shard_owners": {str(s): w for s, w in sorted(owners.items())},
            "handoffs": int(self._handoffs.value()),
            "handoff_faults": int(self._handoff_faults.value()),
            "pending_handoffs": pending,
            "host_bytes": self.host.nbytes,
        }
