"""TieredServingEngine: cold-row lookup on Predict + tiered hot swap.

Wraps a plain `ServingEngine` whose model is the TIERED zoo variant
(reads `slots` + per-plane cold overlays).  Clients keep sending raw
`{dense, sparse}` features; this wrapper translates ids through the
sidecar's vocabulary + cache map:

  resident row    -> its cache slot (the trained device value)
  known cold row  -> slot -1 + the host-tier value in the overlay
  unknown id      -> slot -1 + zeros (a never-trained id serves the
                     model's bias path, not garbage)

Serving NEVER grows the vocabulary or mutates the cache — Predict is
read-only by contract (a growth on the serve path would diverge
replicas from the trainer's deterministic id->row map).

Hot swap: the reloader calls `swap(variables, step, ...)` exactly as it
does on a plain engine (`step`/`state_template` delegate); the wrapper
additionally loads the step's sidecar so tier metadata (vocab, cache
map, host planes) swaps atomically WITH the device variables.  An RLock
spans translate+predict and swap, so a request always sees one
consistent (metadata, variables) generation — in-flight requests finish
on the generation they read, zero dropped.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

from elasticdl_tpu.common import events
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.store import checkpoint as store_ckpt
from elasticdl_tpu.store.host_tier import LazyVocabulary

logger = get_logger(__name__)


class TieredServingEngine:
    """`engine` is a ServingEngine over the tiered model, compiled
    against the translated feature spec ({dense, slots, <overlays>}).
    `overlay_features` maps each store plane to the feature name its
    cold values travel under (deepfm_tiered: fm_embedding -> cold_fm,
    fm_linear -> cold_linear)."""

    def __init__(self, engine, checkpoint_dir: str, step: int,
                 overlay_features: Dict[str, str],
                 slots_feature: str = "slots",
                 sparse_feature: str = "sparse"):
        self._engine = engine
        self._dir = checkpoint_dir
        self._overlay_features = dict(overlay_features)
        self._slots_feature = slots_feature
        self._sparse_feature = sparse_feature
        self._lock = threading.RLock()
        self._adopt_sidecar(int(step))

    # ---- tier metadata -------------------------------------------------

    def _adopt_sidecar(self, step: int) -> None:
        if not store_ckpt.has_sidecar(self._dir, step):
            raise RuntimeError(
                f"checkpoint step {step} has no tiered sidecar under "
                f"{self._dir}; cannot serve a tiered model without its "
                "vocabulary/cache metadata"
            )
        sidecar = store_ckpt.load_sidecar(self._dir, step)
        meta = sidecar.meta
        # Plane-dtype consistency: an int8-cache sidecar only pairs with
        # a model compiled with quantized cache planes (and vice versa).
        # Catch the mismatch HERE, atomically with the swap, instead of
        # serving garbage through a silent reinterpretation.
        template = getattr(self._engine, "state_template", None)
        model_state = getattr(template, "model_state", None)
        wants_int8 = bool(
            isinstance(model_state, dict) and model_state.get("quantized")
        )
        if template is not None and (
                (sidecar.cache_dtype == "int8") != wants_int8):
            raise RuntimeError(
                f"tiered sidecar at step {step} holds "
                f"{sidecar.cache_dtype!r} cache values but the serving "
                f"model was compiled with cache_dtype="
                f"{'int8' if wants_int8 else 'float32'!r}; rebuild the "
                "serving model with the matching cache_dtype or migrate "
                "the checkpoint (arena_convert)"
            )
        vocab = LazyVocabulary.from_arrays(
            int(meta["num_fields"]), *sidecar.vocab_arrays()
        )
        n = vocab.size
        # store row -> cache slot (-1 when not resident)
        slot_of_row = np.full(max(n, 1), -1, np.int64)
        resident = (sidecar.row_of >= 0) & (sidecar.row_of < n)
        slot_of_row[sidecar.row_of[resident]] = np.nonzero(resident)[0]
        host_planes = {
            name: sidecar.host_plane(name) for name in meta["planes"]
        }
        with self._lock:
            self._vocab = vocab
            self._slot_of_row = slot_of_row
            self._host_planes = host_planes
            self._planes = {
                name: int(dim) for name, dim in meta["planes"].items()
            }

    # ---- engine delegation (reloader compatibility) --------------------

    @property
    def step(self) -> int:
        return self._engine.step

    @property
    def state_template(self):
        return self._engine.state_template

    @property
    def produced_unix_s(self) -> Optional[float]:
        return self._engine.produced_unix_s

    @property
    def swap_count(self) -> int:
        return self._engine.swap_count

    @property
    def compile_count(self) -> int:
        return self._engine.compile_count

    def swap(self, variables, step: int,
             produced_unix_s: Optional[float] = None) -> None:
        """Adopt the step's tier metadata, then swap the device
        variables — one atomic generation change under the lock.  Raises
        (leaving the CURRENT generation serving) when the sidecar is
        missing: the reloader counts that as a rejected step."""
        with self._lock:
            self._adopt_sidecar(int(step))
            self._engine.swap(variables, step,
                              produced_unix_s=produced_unix_s)
            vocab_rows = int(self._vocab.size)
        events.emit(events.STORE_TIER_SWAPPED, step=int(step),
                    vocab_rows=vocab_rows)

    # ---- predict -------------------------------------------------------

    def translate(self, sparse: np.ndarray) -> Tuple[np.ndarray, Dict]:
        """(slots, overlay features) for a raw (B, F) id batch.  Callers
        holding no lock get a consistent snapshot because the method
        grabs the generation lock itself."""
        with self._lock:
            rows = self._vocab.lookup(np.asarray(sparse, np.int64))
            slots = np.full(rows.shape, -1, np.int32)
            known = rows >= 0
            slots[known] = self._slot_of_row[rows[known]]
            cold = known & (slots < 0)
            overlays = {}
            for plane, feat in self._overlay_features.items():
                dim = self._planes[plane]
                overlay = np.zeros(rows.shape + (dim,), np.float32)
                if cold.any():
                    overlay[cold] = self._host_planes[plane][rows[cold]]
                overlays[feat] = overlay
            return slots, overlays

    def predict(self, features: Dict[str, np.ndarray], rows: int,
                phase_out: Optional[Dict[str, float]] = None):
        """Raw `{dense, sparse}` features in; (predictions, step) out.
        Held under the generation lock end-to-end so the slots/overlays
        and the device variables always belong to the same checkpoint."""
        with self._lock:
            translated = {
                k: v for k, v in features.items()
                if k != self._sparse_feature
            }
            slots, overlays = self.translate(
                features[self._sparse_feature]
            )
            translated[self._slots_feature] = slots
            translated.update(overlays)
            return self._engine.predict(
                translated, rows, phase_out=phase_out
            )
