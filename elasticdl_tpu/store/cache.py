"""Device hot-row cache bookkeeping + per-batch admission plans.

Pure numpy/host bookkeeping — the cache's VALUES live in the model's
`TieredArena` param on device; this module only decides which store row
occupies which cache slot.

Admission is mandatory: every row a training batch touches must be
cache-resident before the step runs (gradients flow only through the
device table).  Per batch the cache:

  1. frequency-ranks the batch's unique rows (`wire.frequency_rank` —
     the dedup wire format's signal, reused as the admission policy);
  2. counts hits (resident BEFORE this batch's admissions) vs misses;
  3. fills empty slots first, then evicts the lowest-score resident
     rows NOT in the current batch (score = decayed lookup frequency;
     ties break on lowest slot index, so planning is deterministic);
  4. returns a `CachePlan` the TieredStore executes at apply time.

Raises if a single batch references more unique rows than the cache
holds — that configuration cannot satisfy the every-touched-row-resident
invariant and must fail loudly, not thrash.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

from elasticdl_tpu.data.wire import frequency_rank

# Device cache storage modes (mirrors layers/arena.py ARENA_DTYPES —
# not imported: this module must stay jax-free numpy).
CACHE_DTYPES = ("float32", "int8")


def cache_value_bytes_per_row(dim: int, cache_dtype: str) -> int:
    """Bytes one cache row of one plane occupies on the fused GATHER
    path: fp32 streams 4*dim; int8 streams dim code bytes + one fp32
    scale.  (The fp32 carrier + Adam moments exist in BOTH modes and the
    forward never reads the carrier's bytes — XLA folds the exact-zero
    add away — so they cancel out of the comparison; docs/PERF.md §4.)"""
    if cache_dtype == "int8":
        return int(dim) * 1 + 4
    return int(dim) * 4


def device_cache_bytes(planes: Dict[str, int], cache_rows: int,
                       cache_dtype: str) -> int:
    """Analytic bytes of the gather-path cache storage across planes."""
    return sum(
        int(cache_rows) * cache_value_bytes_per_row(dim, cache_dtype)
        for dim in planes.values()
    )


def device_cache_bytes_per_step(planes: Dict[str, int], lookups: int,
                                cache_dtype: str) -> int:
    """Analytic gather-path bytes one train step streams from the cache:
    `lookups` row reads per plane (B*F for the dense slot batch, or the
    dedup'd unique count on the packed wire)."""
    return sum(
        int(lookups) * cache_value_bytes_per_row(dim, cache_dtype)
        for dim in planes.values()
    )


def partition_plan(plan: "CachePlan", num_shards: int,
                   cache_rows: int) -> list:
    """Split one admission plan into per-device sub-plans along the
    mesh-sharded slot arena.

    `embedding_param_sharding` row-shards the (cache_rows, dim) cache
    table over the mesh `model` axis in contiguous blocks of
    cache_rows/num_shards rows, so the device owning a slot is simply
    `slot // block`.  Each sub-plan keeps the parent plan's admission
    order within its device (order-preserving mask selection) and the
    union of the sub-plans is exactly the parent plan — the equivalence
    the sharded-seam test pins.  The scatter itself still executes as
    ONE fused program (XLA partitions it from the table sharding); the
    sub-plans are the per-chip accounting the bench and metrics report.
    """
    num_shards = int(num_shards)
    if num_shards < 1 or cache_rows % num_shards:
        raise ValueError(
            f"cache_rows={cache_rows} must divide evenly over "
            f"{num_shards} mesh shards (row-sharded table blocks)"
        )
    block = cache_rows // num_shards
    subs = []
    admit_dev = np.asarray(plan.admit_slots, np.int64) // block
    evict_dev = np.asarray(plan.evict_slots, np.int64) // block
    for d in range(num_shards):
        am = admit_dev == d
        em = evict_dev == d
        subs.append({
            "device": d,
            "slot_lo": d * block,
            "slot_hi": (d + 1) * block,
            "admit_slots": plan.admit_slots[am].copy(),
            "admit_rows": plan.admit_rows[am].copy(),
            "evict_slots": plan.evict_slots[em].copy(),
            "evict_rows": plan.evict_rows[em].copy(),
        })
    return subs


@dataclass
class CachePlan:
    """One batch's admission/eviction schedule.

    `slots` is what the model consumes; the admit/evict arrays are what
    `TieredStore.apply_plan` executes against device + host tiers.
    `deferred` marks admits whose host value is still in-flight on the
    fold queue (evicted recently, write-back pending) — those are
    gathered synchronously at apply time, after a fold-queue flush.
    """

    slots: np.ndarray                 # (B, F) int32 cache slots
    admit_slots: np.ndarray           # (K,) int32
    admit_rows: np.ndarray            # (K,) int64 store rows
    evict_slots: np.ndarray           # (E,) int32
    evict_rows: np.ndarray            # (E,) int64 store rows
    hits: int
    misses: int
    growth: int = 0                   # vocab rows grown by this batch
    deferred: Optional[np.ndarray] = None   # (K,) bool
    prefetch_rows: Optional[np.ndarray] = None  # admit_rows[~deferred]
    admit_values: Dict[str, np.ndarray] = field(default_factory=dict)
    ready: threading.Event = field(default_factory=threading.Event)
    # Mesh-sharded seam: per-device sub-plans over the row-sharded slot
    # arena (partition_plan); None on an unsharded (1-device) store.
    sub_plans: Optional[list] = None
    # Fused multi-step: number of batches this plan's admissions cover
    # (1 for per-batch plans, K for a steps_per_execution block).
    block_batches: int = 1


class HotRowCache:
    """Slot bookkeeping for the device-resident hot-row cache.

    NOT thread-safe on its own — always driven under TieredStore's lock
    (plans must be produced sequentially anyway: slot assignment is
    stateful).
    """

    def __init__(self, capacity: int, decay: float = 0.999,
                 dtype: str = "float32"):
        if capacity < 1:
            raise ValueError("cache needs at least one row")
        if dtype not in CACHE_DTYPES:
            raise ValueError(
                f"cache dtype must be one of {CACHE_DTYPES}, got {dtype!r}"
            )
        self.capacity = int(capacity)
        # Storage dtype of the device VALUES this bookkeeping fronts —
        # carried through state_arrays() so a sidecar written by an int8
        # cache can never be silently re-interpreted as fp32 on restore.
        self.dtype = dtype
        self._decay = float(decay)
        self._slot_of: Dict[int, int] = {}      # store row -> slot
        self.row_of = np.full(self.capacity, -1, np.int64)
        self._score = np.zeros(self.capacity, np.float64)

    @property
    def occupancy(self) -> int:
        return len(self._slot_of)

    def slot_of(self, row: int) -> int:
        """Resident slot for a store row, or -1 (the serving path)."""
        return self._slot_of.get(int(row), -1)

    def plan(self, rows: np.ndarray, ranked=None) -> CachePlan:
        """`ranked` is an optional precomputed `(uniq, counts)` admission
        signal for exactly these rows — DedupPacker.last_ranking, merged
        batch-globally by the wire pack — so the cache doesn't re-derive
        the frequency view the packer already built.  Order and
        tie-breaks must match `frequency_rank(rows.reshape(-1))`
        (admission order is eviction-victim-visible); the wire pack
        guarantees that, and the parity test pins it."""
        rows = np.asarray(rows, np.int64)
        flat = rows.reshape(-1)
        if ranked is None:
            uniq, counts = frequency_rank(flat)
        else:
            uniq = np.asarray(ranked[0], np.int64)
            counts = np.asarray(ranked[1], np.int64)
            if int(counts.sum()) != flat.size:
                raise ValueError(
                    f"precomputed ranking covers {int(counts.sum())} "
                    f"lookups but the batch has {flat.size}"
                )
        if uniq.size > self.capacity:
            raise ValueError(
                f"batch touches {uniq.size} unique rows but the cache "
                f"holds {self.capacity}; shrink the batch or grow the "
                f"cache — thrashing within one step is not supported "
                "(with steps_per_execution > 1 the admission block spans "
                "the UNION of all K fused batches' rows)"
            )
        resident = np.fromiter(
            (int(r) in self._slot_of for r in uniq), bool, uniq.size
        )
        hits = int(counts[resident].sum())
        misses = int(counts[~resident].sum())
        admit_rows = uniq[~resident]          # descending frequency

        # Victim selection: empty slots first, then lowest-score resident
        # rows outside the current batch (those are guaranteed to exist:
        # free + non-batch-resident >= capacity - batch_uniques >= admits).
        free = np.nonzero(self.row_of < 0)[0]
        n_free = min(free.size, admit_rows.size)
        admit_slots = free[:n_free].astype(np.int64)
        need = admit_rows.size - n_free
        if need > 0:
            cand = np.nonzero(
                (self.row_of >= 0) & ~np.isin(self.row_of, uniq)
            )[0]
            order = cand[np.lexsort((cand, self._score[cand]))]
            evict_slots = order[:need]
        else:
            evict_slots = np.empty(0, np.int64)
        evict_rows = self.row_of[evict_slots].copy()

        # Commit the bookkeeping NOW (plans are produced ahead of
        # execution; the next plan must see this one's assignments).
        for s, r in zip(evict_slots, evict_rows):
            del self._slot_of[int(r)]
        admit_slots = np.concatenate([admit_slots, evict_slots])
        for s, r in zip(admit_slots, admit_rows):
            self._slot_of[int(r)] = int(s)
            self.row_of[s] = r
            self._score[s] = 0.0

        # Frequency scores: decay everything, bump this batch's rows.
        self._score *= self._decay
        uniq_slots = np.fromiter(
            (self._slot_of[int(r)] for r in uniq), np.int64, uniq.size
        )
        self._score[uniq_slots] += counts

        # Row -> slot translation for the full batch.
        order = np.argsort(uniq, kind="stable")
        uniq_sorted, slot_sorted = uniq[order], uniq_slots[order]
        slots = slot_sorted[np.searchsorted(uniq_sorted, flat)]
        return CachePlan(
            slots=slots.reshape(rows.shape).astype(np.int32),
            admit_slots=admit_slots.astype(np.int32),
            admit_rows=admit_rows.copy(),
            evict_slots=evict_slots.astype(np.int32),
            evict_rows=evict_rows,
            hits=hits,
            misses=misses,
        )

    # ---- invalidation (shard handoff) ----------------------------------

    def reset(self) -> None:
        """Drop all residency and scores — a handed-off shard's
        successor starts cold and lets admission traffic rebuild."""
        self._slot_of.clear()
        self.row_of.fill(-1)
        self._score.fill(0.0)

    def invalidate_rows(self, rows: np.ndarray) -> int:
        """Evict specific store rows from the bookkeeping (no device
        traffic — pair with store.device.zero_cache_slots when the
        slots' on-device values must also be cleared).  Returns the
        number of rows that were resident."""
        n = 0
        for row in np.asarray(rows, np.int64).reshape(-1):
            slot = self._slot_of.pop(int(row), None)
            if slot is not None:
                self.row_of[slot] = -1
                self._score[slot] = 0.0
                n += 1
        return n

    # ---- serialization -------------------------------------------------

    def state_arrays(self):
        """(row_of, score, dtype) — residency map plus the PLANE DTYPE
        of the device values this map fronts.  The dtype travels with
        the sidecar so an int8 cache's values can never restore into an
        fp32 cache (or vice versa) without an explicit conversion."""
        return self.row_of.copy(), self._score.copy(), self.dtype

    def load_state_arrays(self, row_of: np.ndarray,
                          score: Optional[np.ndarray] = None,
                          dtype: Optional[str] = None,
                          convert: bool = False) -> None:
        """Adopt a saved residency map.  `dtype` is the saved cache's
        plane dtype (state_arrays()[2] / the sidecar's `cache_dtype`
        meta); a mismatch with this cache's dtype raises unless
        `convert=True` — the caller asserting the device VALUES were
        converted too (CheckpointSaver's arena_convert restore path)."""
        if dtype is not None and dtype != self.dtype and not convert:
            raise ValueError(
                f"cache plane dtype mismatch: sidecar holds {dtype!r} "
                f"values but this cache stores {self.dtype!r} — restore "
                "through CheckpointSaver (arena_convert migrates the "
                "device values) or pass convert=True after converting "
                "them yourself"
            )
        row_of = np.asarray(row_of, np.int64)
        if row_of.shape != (self.capacity,):
            raise ValueError(
                f"cache map shape {row_of.shape} != ({self.capacity},)"
            )
        self.row_of = row_of.copy()
        self._slot_of = {
            int(r): int(s) for s, r in enumerate(row_of) if r >= 0
        }
        self._score = (
            np.asarray(score, np.float64).copy()
            if score is not None else np.zeros(self.capacity, np.float64)
        )
