"""The tiered store's ONE device seam (GL-BOUNDARY allowlisted).

Every device interaction the store needs — scatter admitted rows into
the cache param, zero their optimizer moments, read rows back for
eviction write-back or checkpointing — funnels through this module so
the rest of `store/` stays host-plane numpy (and graftlint can keep
flagging device APIs anywhere else under `store/`).

All entry points route through `run_device_serialized`: on the tier-1
box the mesh is 8 virtual devices on one CPU core, and two threads
dispatching concurrently wedge the backend (see trainer._CPU_EXEC_LOCK).

Index vectors are BUCKET-PADDED: the admit count K varies per batch,
and jax compiles per shape — an unpadded scatter would recompile every
time a new K shows up (measured 40x+ step-time inflation on the CPU
box).  Padding K up to a power-of-four bucket caps the distinct shapes
at ~log4(cache_rows).  The pad entries repeat index 0 with its REAL
value, so duplicate writes are idempotent and the result is exactly
the unpadded scatter's.

On top of the padding, the per-plane gathers/scatters are FUSED into
one jitted program per call site (cache keyed on the static plane
layout + cache dtype; jax's own jit cache handles the bucket shapes).
The eager version of apply_admissions cost ~6 separate dispatches per
step — fusing them cut apply time ~5x on the tier-1 box.

`cache_dtype="int8"` (ISSUE 18): the cache VALUES live as q8 codes +
per-row fp32 scales in model_state["quantized"] (PR 9's plane layout,
`layers/arena.py TieredArena`), with the trainable param a zero fp32
carrier.  Reads dequantize inside the fused gather (+ the carrier, so a
mid-step read stays exact); admissions quantize the host-gathered fp32
values inside the fused scatter and zero the carrier rows alongside the
moments.  The quantize/dequantize numerics are `layers/arena.py`'s
functions; this module is on GL-QUANT's named store allowlist because
it must address the raw planes to scatter/gather them.

Reads return OWNING numpy copies (`np.array(..., copy=True)`): the
train step donates its state (`donate_argnums=(0,)`), so a zero-copy
view of a device buffer would be rewritten under us by the next step.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from elasticdl_tpu.common import programs
from elasticdl_tpu.layers.arena import dequantize_rows, quantize_rows
from elasticdl_tpu.worker.trainer import run_device_serialized


def _get_in(tree, path: Tuple[str, ...]):
    node = tree
    for key in path:
        node = node[key]
    return node


def _set_in(tree, path: Tuple[str, ...], value):
    """Functional nested-dict set: copies only the dicts along `path`."""
    if not path:
        return value
    out = dict(tree)
    out[path[0]] = _set_in(tree[path[0]], path[1:], value)
    return out


def _quant_path(path: Tuple[str, ...]) -> Tuple[str, ...]:
    """Plane path inside model_state["quantized"] for a cache param path
    ("params", <module>, "embedding") — the collections mirror each
    other by construction (flax puts the `self.variable("quantized",
    "embedding", ...)` planes at the param's module path)."""
    return path[1:]


def _pad_bucket(n: int) -> int:
    """Smallest power-of-FOUR >= n (floor 64): caps the distinct gather/
    scatter shapes XLA ever sees from this module at ~log4(cache_rows),
    so compile churn burns out within a few warm-up steps.  The extra
    padded rows are idempotent duplicate writes — wasted bandwidth only,
    and at most 4x of it."""
    size = 64
    while size < n:
        size <<= 2
    return size


def _pad_indices(idx: np.ndarray) -> np.ndarray:
    """Pad an index vector to its bucket by repeating index 0."""
    padded = np.full(_pad_bucket(idx.size), idx[0], idx.dtype)
    padded[: idx.size] = idx
    return padded


def _layout(param_paths: Dict[str, Tuple[str, ...]]
            ) -> Tuple[Tuple[str, Tuple[str, ...]], ...]:
    """Hashable, order-stable (name, path) tuple — the static key the
    fused-program caches below hang off."""
    return tuple(sorted(param_paths.items()))


@functools.lru_cache(maxsize=None)
def _gather_program(layout, cache_dtype: str):
    paths = tuple(path for _, path in layout)

    if cache_dtype == "int8":

        def gather(params, quant, idx):
            # dequant(codes, scales) + carrier: exact even mid-step (the
            # carrier is zero BETWEEN steps — fold_quantized_updates —
            # so this is normally just the dequantized planes).
            out = []
            for path in paths:
                planes = _get_in(quant, _quant_path(path))
                carrier = _get_in(params, path)
                out.append(
                    dequantize_rows(
                        planes["q8"][idx], planes["scale"][idx]
                    ) + carrier[idx]
                )
            return tuple(out)

        return programs.registered_jit("store_gather", gather)

    def gather(params, quant, idx):
        del quant
        return tuple(_get_in(params, path)[idx] for path in paths)

    return programs.registered_jit("store_gather", gather)


def _quant_collection(state, cache_dtype: str):
    if cache_dtype != "int8":
        return {}
    quant = state.model_state.get("quantized")
    if not quant:
        raise ValueError(
            'cache_dtype="int8" but the model state has no "quantized" '
            "collection — build the zoo model with cache_dtype='int8' "
            "(TieredArena) so the planes exist"
        )
    return quant


def read_rows(state, param_paths: Dict[str, Tuple[str, ...]],
              slots: np.ndarray,
              cache_dtype: str = "float32") -> Dict[str, np.ndarray]:
    """Owning fp32 copies of cache rows `slots`, per plane — the
    eviction write-back read.  int8 caches dequantize inside the fused
    gather; the returned values are always fp32."""
    n = int(np.asarray(slots).size)
    idx = _pad_indices(np.asarray(slots, np.int32))
    layout = _layout(param_paths)
    gather = _gather_program(layout, cache_dtype)
    quant = _quant_collection(state, cache_dtype)

    def _read():
        rows = gather(state.params, quant, idx)
        return {
            name: np.array(jax.device_get(t), np.float32, copy=True)[:n]
            for (name, _), t in zip(layout, rows)
        }

    return run_device_serialized(_read)


def read_full_tables(state, param_paths: Dict[str, Tuple[str, ...]],
                     cache_dtype: str = "float32"
                     ) -> Dict[str, np.ndarray]:
    """Owning fp32 copies of the whole cache table per plane (sidecar
    checkpointing, migration — cache tables are small by construction).
    int8 caches return the dequantized view (+ carrier, exact)."""

    def _read():
        out = {}
        if cache_dtype == "int8":
            quant = _quant_collection(state, cache_dtype)
            for name, path in param_paths.items():
                planes = _get_in(quant, _quant_path(path))
                table = dequantize_rows(
                    planes["q8"], planes["scale"]
                ) + _get_in(state.params, path)
                out[name] = np.array(
                    jax.device_get(table), np.float32, copy=True
                )
            return out
        for name, path in param_paths.items():
            table = _get_in(state.params, path)
            out[name] = np.array(
                jax.device_get(table), np.float32, copy=True
            )
        return out

    return run_device_serialized(_read)


def read_full_planes(state, param_paths: Dict[str, Tuple[str, ...]]
                     ) -> Dict[str, Dict[str, np.ndarray]]:
    """Owning RAW plane copies {name: {"q8", "scale"}} of an int8 cache
    — the sidecar stores these verbatim so an int8->int8 restore is
    bit-exact (no dequant/requant round trip)."""
    quant = _quant_collection(state, "int8")

    def _read():
        out = {}
        for name, path in param_paths.items():
            planes = _get_in(quant, _quant_path(path))
            out[name] = {
                "q8": np.array(
                    jax.device_get(planes["q8"]), np.int8, copy=True
                ),
                "scale": np.array(
                    jax.device_get(planes["scale"]), np.float32, copy=True
                ),
            }
        return out

    return run_device_serialized(_read)


def zero_cache_slots(state, param_paths: Dict[str, Tuple[str, ...]],
                     slots: np.ndarray, cache_dtype: str = "float32"):
    """Zero cache rows `slots` in every plane (and their optimizer
    moments) — the device half of shard-handoff invalidation: a moved
    shard's old slots must not keep serving stale values on the worker
    that lost the shard.  Reuses the fused admission program with
    all-zero row values (an int8 cache quantizes zeros to code 0 /
    scale 1.0 — the exact all-zero-row representation)."""
    slots = np.asarray(slots, np.int32).reshape(-1)
    if slots.size == 0:
        return state
    values = {
        name: np.zeros(
            (slots.size, int(_get_in(state.params, path).shape[1])),
            np.float32,
        )
        for name, path in param_paths.items()
    }
    return apply_admissions(state, param_paths, slots, values,
                            cache_dtype=cache_dtype)


def apply_admissions(state, param_paths: Dict[str, Tuple[str, ...]],
                     slots: np.ndarray,
                     values: Dict[str, np.ndarray],
                     cache_dtype: str = "float32"):
    """Scatter host-gathered row values into every plane's cache storage
    and zero those rows' optimizer moments.

    Moment zeroing makes an admitted row behave exactly like a
    never-touched flat-arena row: in Adam, an untouched row's mu/nu stay
    zero, so a row that leaves and re-enters the cache must not carry
    moments from its previous residency.

    int8 caches quantize the fp32 values INSIDE the fused program
    (layers/arena.py `quantize_rows` — deterministic round-to-nearest,
    the same numerics admissions from an int8 HOST tier already went
    through) and additionally zero the admitted rows of the fp32
    carrier: a re-admitted slot must not inherit a stale carrier delta.
    """
    n = int(np.asarray(slots).size)
    idx = _pad_indices(np.asarray(slots, np.int32))
    layout = _layout(param_paths)

    def _pad_values(vals: np.ndarray) -> np.ndarray:
        # pad rows repeat row 0: every duplicate write carries the same
        # value, so the padded scatter equals the unpadded one
        padded = np.repeat(vals[:1], idx.size, axis=0)
        padded[:n] = vals
        return padded

    vals = tuple(
        _pad_values(np.asarray(values[name], np.float32))
        for name, _ in layout
    )
    admit = _admit_program(layout, cache_dtype)
    quant = _quant_collection(state, cache_dtype)

    def _apply():
        params, new_quant, opt_state = admit(
            state.params, quant, state.opt_state, idx, vals
        )
        if cache_dtype == "int8":
            model_state = dict(state.model_state)
            model_state["quantized"] = new_quant
            return state.replace(
                params=params, opt_state=opt_state,
                model_state=model_state,
            )
        return state.replace(params=params, opt_state=opt_state)

    return run_device_serialized(_apply)


@functools.lru_cache(maxsize=None)
def _admit_program(layout, cache_dtype: str):
    paths = tuple(path for _, path in layout)

    def admit(params, quant, opt_state, idx, vals):
        for path, v in zip(paths, vals):
            if cache_dtype == "int8":
                planes = _get_in(quant, _quant_path(path))
                codes, scales = quantize_rows(v)
                planes = {
                    "q8": planes["q8"].at[idx].set(codes),
                    "scale": planes["scale"].at[idx].set(scales),
                }
                quant = _set_in(quant, _quant_path(path), planes)
                # the carrier rows reset with the value: an admission IS
                # the row's new fp32 state, any queued delta is stale
                carrier = _get_in(params, path)
                params = _set_in(
                    params, path,
                    carrier.at[idx].set(jnp.zeros((), carrier.dtype)),
                )
            else:
                table = _get_in(params, path)
                params = _set_in(
                    params, path, table.at[idx].set(v.astype(table.dtype))
                )

        # Optax moment trees share the params' pytree structure
        # (trainer.state_sharding uses the same trick); zero the admitted
        # rows in every such subtree.  All of this tree walking happens
        # at trace time — the compiled program is just fused scatters.
        param_treedef = jax.tree.structure(params)

        def is_param_like(subtree):
            try:
                return jax.tree.structure(subtree) == param_treedef
            except Exception:
                return False

        def zero_rows(subtree):
            if not is_param_like(subtree):
                return subtree
            for path in paths:
                leaf = _get_in(subtree, path)
                subtree = _set_in(
                    subtree, path,
                    leaf.at[idx].set(jnp.zeros((), leaf.dtype)),
                )
            return subtree

        opt_state = jax.tree.map(
            zero_rows, opt_state, is_leaf=is_param_like
        )
        return params, quant, opt_state

    return programs.registered_jit("store_admit", admit)
