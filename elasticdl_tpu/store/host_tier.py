"""Host-RAM bulk tier: full-vocabulary embedding planes + lazy growth.

Everything here is numpy — this module is deliberately host-plane code
(graftlint GL-BOUNDARY sanctions host-side row math in `store/`; device
work lives only in `store/device.py`).

Two pieces:

* `LazyVocabulary` — per-field id→row maps that GROW on first lookup
  instead of hashing into a fixed capacity.  Row assignment is
  deterministic in the id stream: fields are scanned left-to-right and
  new ids within a field get rows in first-occurrence order, so the
  same batch sequence always produces the same map (checkpoint restores
  and eviction write-backs depend on this).

* `HostTier` — the storage planes, one per arena the model owns (DeepFM:
  `fm_embedding` dim 16 + `fm_linear` dim 1), all sharing ONE row
  numbering.  Rows are fp32, or int8 codes + per-row scales via the
  arena's host quantization mirrors when `host_dtype="int8"` (4x denser
  — the PR 9 memory-wall trick applied to the bulk tier).

Thread-safety: one lock around every operation.  Growth reallocates the
backing arrays, so a gather racing a grow would read freed memory; the
single lock also keeps `set_rows` (fold worker) and `assign` (prefetch
producer) mutually exclusive.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

import numpy as np

from elasticdl_tpu.layers.arena import dequantize_rows_host, quantize_rows_host

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer (uint64 in, uint64 out).
    Wraparound is the algorithm, not an accident."""
    with np.errstate(over="ignore"):
        z = (x + _GOLDEN).astype(np.uint64)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))


def row_init_values(seed: int, plane_index: int, rows: np.ndarray,
                    dim: int, scale: float = 0.05) -> np.ndarray:
    """Deterministic per-row init: uniform [-scale*sqrt(3), +scale*sqrt(3))
    (same std as the arena's normal(0.05) initializer), keyed by
    (seed, plane, row, column) so a row's init never depends on WHEN it
    was grown — only on which row it is."""
    rows = np.asarray(rows, np.uint64).reshape(-1)
    with np.errstate(over="ignore"):
        salt = _splitmix64(
            np.uint64(seed & 0xFFFFFFFFFFFFFFFF)
            + np.uint64(plane_index + 1) * _GOLDEN
        )
        idx = (rows[:, None] * np.uint64(dim)
               + np.arange(dim, dtype=np.uint64))
    z = _splitmix64(idx ^ salt)
    u = (z >> np.uint64(11)).astype(np.float64) / float(1 << 53)
    amp = scale * np.sqrt(3.0)
    return ((2.0 * u - 1.0) * amp).astype(np.float32)


class LazyVocabulary:
    """Per-field id→row maps with deterministic first-occurrence growth.

    NOT thread-safe on its own — always driven under HostTier's lock.
    """

    def __init__(self, num_fields: int):
        self.num_fields = int(num_fields)
        self._maps = [dict() for _ in range(self.num_fields)]
        self._next_row = 0

    @property
    def size(self) -> int:
        return self._next_row

    def assign(self, sparse: np.ndarray):
        """Map a (B, F) id batch to store rows, growing on first lookup.

        Returns (rows (B, F) int64, new_fields (N,) int64,
        new_ids (N,) int64, new_rows (N,) int64) — the N newly assigned
        entries in assignment order, for the caller to initialise.
        """
        sparse = np.asarray(sparse, np.int64)
        if sparse.ndim != 2 or sparse.shape[1] != self.num_fields:
            raise ValueError(
                f"expected (B, {self.num_fields}) ids, got {sparse.shape}"
            )
        rows = np.empty_like(sparse)
        new_fields, new_ids, new_rows = [], [], []
        for f in range(self.num_fields):
            col = sparse[:, f]
            uniq, first = np.unique(col, return_index=True)
            m = self._maps[f]
            uniq_rows = np.empty(uniq.size, np.int64)
            # New ids claim rows in first-occurrence order within the
            # field — the determinism contract.
            for i in np.argsort(first, kind="stable"):
                v = int(uniq[i])
                r = m.get(v)
                if r is None:
                    r = self._next_row
                    self._next_row += 1
                    m[v] = r
                    new_fields.append(f)
                    new_ids.append(v)
                    new_rows.append(r)
                uniq_rows[i] = r
            rows[:, f] = uniq_rows[np.searchsorted(uniq, col)]
        return (
            rows,
            np.asarray(new_fields, np.int64),
            np.asarray(new_ids, np.int64),
            np.asarray(new_rows, np.int64),
        )

    def lookup(self, sparse: np.ndarray) -> np.ndarray:
        """Growth-free lookup (the serving path): unknown ids map to -1."""
        sparse = np.asarray(sparse, np.int64)
        rows = np.empty_like(sparse)
        for f in range(min(self.num_fields, sparse.shape[1])):
            m = self._maps[f]
            col = sparse[:, f]
            uniq, inverse = np.unique(col, return_inverse=True)
            uniq_rows = np.fromiter(
                (m.get(int(v), -1) for v in uniq), np.int64, uniq.size
            )
            rows[:, f] = uniq_rows[inverse]
        return rows

    def state_arrays(self):
        """(fields, ids, rows) int64 arrays — the serializable form."""
        n = self._next_row
        fields = np.empty(n, np.int64)
        ids = np.empty(n, np.int64)
        rows = np.empty(n, np.int64)
        i = 0
        for f, m in enumerate(self._maps):
            for v, r in m.items():
                fields[i], ids[i], rows[i] = f, v, r
                i += 1
        order = np.argsort(rows[:i], kind="stable")
        return fields[:i][order], ids[:i][order], rows[:i][order]

    @classmethod
    def from_arrays(cls, num_fields: int, fields, ids, rows):
        vocab = cls(num_fields)
        fields = np.asarray(fields, np.int64)
        ids = np.asarray(ids, np.int64)
        rows = np.asarray(rows, np.int64)
        for f, v, r in zip(fields, ids, rows):
            vocab._maps[int(f)][int(v)] = int(r)
        vocab._next_row = int(rows.max()) + 1 if rows.size else 0
        return vocab


class HostTier:
    """The host-RAM bulk tier: every plane's full vocabulary.

    `backfill` (optional) is consulted for newly grown rows before the
    deterministic init — `fn(plane_name, fields, ids) -> (N, dim) fp32
    or None`.  flat→tiered checkpoint migration uses it to lazily pull
    rows out of a restored flat table instead of re-initialising them.
    """

    def __init__(self, planes: Dict[str, int], num_fields: int,
                 host_dtype: str = "fp32", seed: int = 0x5EED,
                 init_scale: float = 0.05, initial_rows: int = 1024):
        if host_dtype not in ("fp32", "int8"):
            raise ValueError(f"host_dtype must be fp32|int8, got {host_dtype}")
        self.planes = dict(planes)
        self.host_dtype = host_dtype
        self.seed = int(seed)
        self.init_scale = float(init_scale)
        self.vocab = LazyVocabulary(num_fields)
        self._lock = threading.Lock()
        self._cap = 0
        self._initial_rows = max(1, int(initial_rows))
        self._fp32: Dict[str, np.ndarray] = {}
        self._codes: Dict[str, np.ndarray] = {}
        self._scales: Dict[str, np.ndarray] = {}
        self._backfill: Optional[Callable] = None
        self._plane_index = {
            name: i for i, name in enumerate(sorted(self.planes))
        }

    # ---- capacity ------------------------------------------------------

    def _ensure_capacity(self, rows_needed: int) -> None:
        if rows_needed <= self._cap:
            return
        new_cap = max(self._initial_rows, self._cap)
        while new_cap < rows_needed:
            new_cap = new_cap + max(new_cap // 2, self._initial_rows)
        for name, dim in self.planes.items():
            if self.host_dtype == "fp32":
                arr = np.zeros((new_cap, dim), np.float32)
                if self._cap:
                    arr[: self._cap] = self._fp32[name]
                self._fp32[name] = arr
            else:
                codes = np.zeros((new_cap, dim), np.int8)
                scales = np.ones((new_cap, 1), np.float32)
                if self._cap:
                    codes[: self._cap] = self._codes[name]
                    scales[: self._cap] = self._scales[name]
                self._codes[name] = codes
                self._scales[name] = scales
        self._cap = new_cap

    # ---- growth / lookup ----------------------------------------------

    def set_backfill(self, fn: Optional[Callable]) -> None:
        with self._lock:
            self._backfill = fn

    def assign(self, sparse: np.ndarray):
        """Map ids to rows, growing + initialising new rows.

        Returns (rows (B, F) int64, n_new int).
        """
        with self._lock:
            rows, new_fields, new_ids, new_rows = self.vocab.assign(sparse)
            if new_rows.size:
                self._ensure_capacity(self.vocab.size)
                for name, dim in self.planes.items():
                    values = None
                    if self._backfill is not None:
                        values = self._backfill(name, new_fields, new_ids)
                    if values is None:
                        values = row_init_values(
                            self.seed, self._plane_index[name],
                            new_rows, dim, self.init_scale,
                        )
                    self._write_rows(name, new_rows, values)
            return rows, int(new_rows.size)

    def lookup(self, sparse: np.ndarray) -> np.ndarray:
        with self._lock:
            return self.vocab.lookup(sparse)

    @property
    def size(self) -> int:
        with self._lock:
            return self.vocab.size

    @property
    def nbytes(self) -> int:
        # plane arrays are allocated lazily on first growth — a store
        # that has assigned no rows yet holds no storage at all
        with self._lock:
            total = 0
            for name in self.planes:
                if self.host_dtype == "fp32":
                    if name in self._fp32:
                        total += self._fp32[name][: self.vocab.size].nbytes
                elif name in self._codes:
                    total += self._codes[name][: self.vocab.size].nbytes
                    total += self._scales[name][: self.vocab.size].nbytes
            return total

    # ---- row values ----------------------------------------------------

    def _write_rows(self, name: str, rows: np.ndarray,
                    values: np.ndarray) -> None:
        values = np.asarray(values, np.float32).reshape(
            -1, self.planes[name]
        )
        if self.host_dtype == "fp32":
            self._fp32[name][rows] = values
        else:
            codes, scales = quantize_rows_host(values)
            self._codes[name][rows] = codes
            self._scales[name][rows] = scales

    def gather(self, rows: np.ndarray,
               planes=None) -> Dict[str, np.ndarray]:
        """fp32 values for `rows`, per plane.  Rows must be assigned."""
        rows = np.asarray(rows, np.int64).reshape(-1)
        with self._lock:
            if rows.size and int(rows.max()) >= self.vocab.size:
                raise IndexError("gather of unassigned store row")
            out = {}
            for name in planes if planes is not None else self.planes:
                if self.host_dtype == "fp32":
                    out[name] = self._fp32[name][rows].copy()
                else:
                    out[name] = dequantize_rows_host(
                        self._codes[name][rows], self._scales[name][rows]
                    )
            return out

    def set_rows(self, rows: np.ndarray,
                 values: Dict[str, np.ndarray]) -> None:
        """Absolute write-back (the eviction fold path)."""
        rows = np.asarray(rows, np.int64).reshape(-1)
        with self._lock:
            if rows.size and int(rows.max()) >= self.vocab.size:
                raise IndexError("set_rows of unassigned store row")
            for name, vals in values.items():
                self._write_rows(name, rows, vals)

    def reinit_rows(self, rows: np.ndarray) -> None:
        """Rewrite `rows` with their deterministic seed init — the
        shard-handoff recovery path for rows grown after the last
        sidecar: because `row_init_values` keys on (seed, plane, row)
        alone, the re-init is byte-identical to the value the row first
        grew with."""
        rows = np.asarray(rows, np.int64).reshape(-1)
        if rows.size == 0:
            return
        with self._lock:
            if int(rows.max()) >= self.vocab.size:
                raise IndexError("reinit_rows of unassigned store row")
            for name, dim in self.planes.items():
                values = row_init_values(
                    self.seed, self._plane_index[name], rows, dim,
                    self.init_scale,
                )
                self._write_rows(name, rows, values)

    # ---- serialization -------------------------------------------------

    def state_dict(self) -> Dict[str, np.ndarray]:
        with self._lock:
            n = self.vocab.size
            fields, ids, rows = self.vocab.state_arrays()
            out = {
                "vocab_fields": fields,
                "vocab_ids": ids,
                "vocab_rows": rows,
            }
            for name in self.planes:
                if self.host_dtype == "fp32":
                    out[f"plane_{name}_fp32"] = self._fp32[name][:n].copy()
                else:
                    out[f"plane_{name}_codes"] = self._codes[name][:n].copy()
                    out[f"plane_{name}_scales"] = (
                        self._scales[name][:n].copy()
                    )
            return out

    def load_state_dict(self, state: Dict[str, np.ndarray]) -> None:
        with self._lock:
            self.vocab = LazyVocabulary.from_arrays(
                self.vocab.num_fields,
                state["vocab_fields"], state["vocab_ids"],
                state["vocab_rows"],
            )
            n = self.vocab.size
            self._cap = 0
            self._fp32, self._codes, self._scales = {}, {}, {}
            self._ensure_capacity(max(n, 1))
            for name in self.planes:
                if self.host_dtype == "fp32":
                    self._fp32[name][:n] = state[f"plane_{name}_fp32"]
                else:
                    self._codes[name][:n] = state[f"plane_{name}_codes"]
                    self._scales[name][:n] = state[f"plane_{name}_scales"]
