"""Tiered embedding store: host-RAM bulk tier + device hot-row cache.

The flat `EmbeddingArena` must fit the whole vocabulary in HBM; this
package keeps the full (lazily grown) vocabulary in host RAM — fp32 or
int8+scales, reusing the arena's quantized-plane numerics — and pins
only a hot-row cache on device.  The cache table is the model's ONLY
trainable embedding storage: every row a batch touches is admitted
before the step runs, so the jitted train step stays structurally
identical to the flat arena's and bitwise-identical on an all-hot
working set.  Cold rows are gathered from the host tier on the prefetch
thread (overlapped with compute) and written back host-side on
eviction.

Module layout:
  host_tier.py   host-RAM planes + lazy vocabulary (numpy only)
  cache.py       hot-row cache bookkeeping + per-batch admission plans
  device.py      the ONE sanctioned device seam (GL-BOUNDARY allowlist)
  tiered.py      TieredStore orchestrator + background threads
  checkpoint.py  sidecar save/load + tiered<->flat migration
  serving.py     TieredServingEngine (cold-row lookup on Predict)
"""

from elasticdl_tpu.store.cache import CachePlan, HotRowCache
from elasticdl_tpu.store.host_tier import HostTier, LazyVocabulary
from elasticdl_tpu.store.tiered import TieredStore

__all__ = [
    "CachePlan",
    "HotRowCache",
    "HostTier",
    "LazyVocabulary",
    "TieredStore",
]
