"""Tiered checkpoint sidecar + tiered<->flat migration.

Orbax owns the TrainState (which, for a tiered model, contains the
device cache tables); everything else the store needs to resume — host
planes, the lazy vocabulary, the cache residency map — rides in a
SIDECAR under `<checkpoint_dir>/.tiered/<step>/`, written synchronously
by `CheckpointSaver.save()` and pruned with the same rotation as the
step dirs.  The sidecar is self-contained: it also carries a copy of
the cache VALUES at save time, so serving and migration can reconstruct
every vocabulary row's latest value without interpreting the orbax tree.

Migration ("arena_convert-style", both directions):

* tiered -> flat: `flat_tables_from_sidecar` materialises full
  (capacity, dim) flat-arena tables by hashing every vocabulary id with
  the flat model's hash and scattering its latest value (cache value if
  resident, else host-tier value).  Hash collisions resolve to the
  EARLIEST-assigned store row — deterministic, and matching the flat
  arena's first-writer-wins intuition.  Unmapped flat rows keep the
  template's init.

* flat -> tiered: `fill_matching` copies every same-path, same-shape
  leaf (the dense layers) from a raw restored tree into a tiered
  template; the cache starts empty and the host tier lazily backfills
  rows from the flat tables via `flat_backfill` instead of
  re-initialising them.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Callable, Dict

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.layers.arena import dequantize_rows_host

logger = get_logger(__name__)

SIDECAR_ROOT = ".tiered"


def sidecar_dir(checkpoint_dir: str, step: int) -> str:
    return os.path.join(
        os.path.abspath(checkpoint_dir), SIDECAR_ROOT, str(int(step))
    )


def has_sidecar(checkpoint_dir: str, step: int) -> bool:
    return os.path.isfile(
        os.path.join(sidecar_dir(checkpoint_dir, step), "meta.json")
    )


def save_sidecar(checkpoint_dir: str, step: int, store, state) -> str:
    """Write the store's host/bookkeeping state for `step`.  Runs
    synchronously inside CheckpointSaver.save() — the cache-value read
    must happen before the next (donating) train step rewrites the
    state's buffers."""
    from elasticdl_tpu.store import device as store_device

    d = sidecar_dir(checkpoint_dir, step)
    os.makedirs(d, exist_ok=True)
    arrays: Dict[str, np.ndarray] = {}
    for key, value in store.host.state_dict().items():
        arrays[f"host__{key}"] = value
    row_of, score, cache_dtype = store.cache.state_arrays()
    arrays["cache__row_of"] = row_of
    arrays["cache__score"] = score
    if cache_dtype == "int8":
        # Raw q8/scale planes, NOT a dequantized fp32 view: an
        # int8 -> int8 restore must be bit-exact, no requant round trip.
        for name, planes in store_device.read_full_planes(
                state, store.param_paths).items():
            arrays[f"values__{name}__q8"] = planes["q8"]
            arrays[f"values__{name}__scale"] = planes["scale"]
    else:
        for name, table in store_device.read_full_tables(
                state, store.param_paths).items():
            arrays[f"values__{name}"] = table

    npz_path = os.path.join(d, "store.npz")
    tmp = npz_path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, npz_path)

    meta = {
        "step": int(step),
        "cache_rows": int(store.cache_rows),
        "num_fields": int(store.num_fields),
        "host_dtype": store.host.host_dtype,
        "planes": {name: int(dim) for name, dim in store.planes.items()},
        "vocab_rows": int(store.host.size),
        "cache_dtype": cache_dtype,
    }
    meta_path = os.path.join(d, "meta.json")
    tmp = meta_path + ".tmp"
    # meta.json lands LAST via os.replace: its presence marks a complete
    # sidecar (has_sidecar keys off it), so readers never see a torn one.
    with open(tmp, "w") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, meta_path)
    return d


@dataclass
class TieredSidecar:
    meta: dict
    host_state: Dict[str, np.ndarray]
    row_of: np.ndarray                 # (cache_rows,) store row per slot
    score: np.ndarray
    cache_values: Dict[str, np.ndarray]   # plane -> (cache_rows, dim) fp32
    # int8 sidecars additionally carry the raw planes (bit-exact
    # int8 -> int8 restore); cache_values is then the dequantized view.
    cache_planes: Dict[str, Dict[str, np.ndarray]] = field(
        default_factory=dict
    )

    @property
    def cache_dtype(self) -> str:
        """Plane dtype the cache VALUES were saved as.  Pre-ISSUE-18
        sidecars carry no marker and were always fp32."""
        return self.meta.get("cache_dtype", "float32")

    def host_plane(self, name: str) -> np.ndarray:
        """Full (vocab_rows, dim) fp32 view of a host plane."""
        if self.meta["host_dtype"] == "fp32":
            return np.asarray(self.host_state[f"plane_{name}_fp32"],
                              np.float32)
        return dequantize_rows_host(
            self.host_state[f"plane_{name}_codes"],
            self.host_state[f"plane_{name}_scales"],
        )

    def vocab_arrays(self):
        return (
            np.asarray(self.host_state["vocab_fields"], np.int64),
            np.asarray(self.host_state["vocab_ids"], np.int64),
            np.asarray(self.host_state["vocab_rows"], np.int64),
        )

    def latest_row_values(self, name: str) -> np.ndarray:
        """(vocab_rows, dim) fp32: host-tier values, overridden by the
        cache value for every resident row — each row's freshest state
        at save time."""
        values = self.host_plane(name).copy()
        resident = self.row_of >= 0
        slots = np.nonzero(resident)[0]
        rows = self.row_of[slots]
        in_range = rows < values.shape[0]
        values[rows[in_range]] = self.cache_values[name][slots[in_range]]
        return values


def load_sidecar(checkpoint_dir: str, step: int) -> TieredSidecar:
    d = sidecar_dir(checkpoint_dir, step)
    with open(os.path.join(d, "meta.json")) as f:
        meta = json.load(f)
    host_state: Dict[str, np.ndarray] = {}
    row_of = score = None
    cache_values: Dict[str, np.ndarray] = {}
    cache_planes: Dict[str, Dict[str, np.ndarray]] = {}
    with np.load(os.path.join(d, "store.npz")) as npz:
        for key in npz.files:
            if key.startswith("host__"):
                host_state[key[len("host__"):]] = npz[key]
            elif key == "cache__row_of":
                row_of = npz[key]
            elif key == "cache__score":
                score = npz[key]
            elif key.startswith("values__"):
                name = key[len("values__"):]
                for plane_key in ("q8", "scale"):
                    suffix = f"__{plane_key}"
                    if name.endswith(suffix):
                        base = name[: -len(suffix)]
                        cache_planes.setdefault(base, {})[plane_key] = (
                            npz[key]
                        )
                        break
                else:
                    cache_values[name] = npz[key]
    # int8 layout: materialise the fp32 view consumers (serving,
    # migration) read through; the raw planes stay alongside.
    for name, planes in cache_planes.items():
        cache_values[name] = dequantize_rows_host(
            planes["q8"], planes["scale"]
        )
    return TieredSidecar(meta, host_state, row_of, score, cache_values,
                         cache_planes)


SHARDED_ROOT = ".sharded"


def sharded_sidecar_dir(checkpoint_dir: str, step: int) -> str:
    return os.path.join(
        os.path.abspath(checkpoint_dir), SHARDED_ROOT, str(int(step))
    )


def save_sharded_sidecar(checkpoint_dir: str, step: int, store) -> str:
    """Sidecar for a `ShardedTieredStore`: the shared host tier, every
    shard's cache residency slice, and the shard->worker map.  Same
    torn-write discipline as `save_sidecar` (meta.json lands last)."""
    d = sharded_sidecar_dir(checkpoint_dir, step)
    os.makedirs(d, exist_ok=True)
    arrays: Dict[str, np.ndarray] = {}
    for key, value in store.host.state_dict().items():
        arrays[f"host__{key}"] = value
    for key, value in store.cache_state().items():
        arrays[f"cache__{key}"] = value

    npz_path = os.path.join(d, "store.npz")
    tmp = npz_path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, npz_path)

    meta = {
        "step": int(step),
        "num_shards": int(store.num_shards),
        "per_shard_rows": int(store.per_shard_rows),
        "num_fields": int(store.num_fields),
        "host_dtype": store.host.host_dtype,
        "planes": {name: int(dim) for name, dim in store.planes.items()},
        "vocab_rows": int(store.host.size),
        "shard_owners": {
            str(s): int(w) for s, w in store.map.as_dict().items()
        },
    }
    meta_path = os.path.join(d, "meta.json")
    tmp = meta_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, meta_path)
    return d


def has_sharded_sidecar(checkpoint_dir: str, step: int) -> bool:
    return os.path.isfile(
        os.path.join(sharded_sidecar_dir(checkpoint_dir, step), "meta.json")
    )


@dataclass
class ShardedSidecar:
    """Loaded sharded sidecar.  `host_state` feeds
    `HostTier.load_state_dict`; `cache_arrays` feeds
    `ShardedTieredStore.load_cache_state`; `latest_row_values` is the
    interface `ShardedTieredStore.rebuild_shard` consumes."""

    meta: dict
    host_state: Dict[str, np.ndarray]
    cache_arrays: Dict[str, np.ndarray]

    def host_plane(self, name: str) -> np.ndarray:
        if self.meta["host_dtype"] == "fp32":
            return np.asarray(self.host_state[f"plane_{name}_fp32"],
                              np.float32)
        return dequantize_rows_host(
            self.host_state[f"plane_{name}_codes"],
            self.host_state[f"plane_{name}_scales"],
        )

    def latest_row_values(self, name: str) -> np.ndarray:
        """(vocab_rows, dim) fp32.  The sharded store's live values are
        host-resident (per-shard caches hold only admission bookkeeping,
        not a device value copy), so the host plane IS the freshest
        state at save time."""
        return self.host_plane(name).copy()


def load_sharded_sidecar(checkpoint_dir: str, step: int) -> ShardedSidecar:
    d = sharded_sidecar_dir(checkpoint_dir, step)
    with open(os.path.join(d, "meta.json")) as f:
        meta = json.load(f)
    host_state: Dict[str, np.ndarray] = {}
    cache_arrays: Dict[str, np.ndarray] = {}
    with np.load(os.path.join(d, "store.npz")) as npz:
        for key in npz.files:
            if key.startswith("host__"):
                host_state[key[len("host__"):]] = npz[key]
            elif key.startswith("cache__"):
                cache_arrays[key[len("cache__"):]] = npz[key]
    return ShardedSidecar(meta, host_state, cache_arrays)


def prune_sidecars(checkpoint_dir: str, keep_steps) -> None:
    """Drop sidecars of rotated-away steps (same policy as manifests).
    Covers both the single-store and sharded sidecar roots."""
    keep = {str(int(s)) for s in keep_steps}
    import shutil

    for root_name in (SIDECAR_ROOT, SHARDED_ROOT):
        root = os.path.join(os.path.abspath(checkpoint_dir), root_name)
        if not os.path.isdir(root):
            continue
        for name in os.listdir(root):
            if name.isdigit() and name not in keep:
                shutil.rmtree(os.path.join(root, name), ignore_errors=True)


# ---- migration: tiered -> flat ----------------------------------------


def flat_tables_from_sidecar(
    sidecar: TieredSidecar,
    templates: Dict[str, np.ndarray],
    hash_fn: Callable[[np.ndarray, np.ndarray], np.ndarray],
) -> Dict[str, np.ndarray]:
    """Materialise flat-arena tables from a tiered sidecar.

    `templates`: per plane, a freshly initialised (capacity, dim) table
    — unmapped rows keep this init.  `hash_fn(fields, ids) -> flat rows`
    is the flat model's id hashing (e.g. deepfm's hash_field_rows_host
    over field-offset ids).
    """
    fields, ids, rows = sidecar.vocab_arrays()
    flat_rows = np.asarray(hash_fn(fields, ids), np.int64)
    # Descending store-row scatter: duplicates resolve last-write-wins,
    # so the EARLIEST-assigned vocabulary row claims a collided flat row.
    order = np.argsort(-rows, kind="stable")
    out = {}
    for name, template in templates.items():
        table = np.array(template, np.float32, copy=True)
        values = sidecar.latest_row_values(name)[rows]
        table[flat_rows[order]] = values[order]
        out[name] = table
    return out


def flat_backfill(
    flat_tables: Dict[str, np.ndarray],
    hash_fn: Callable[[np.ndarray, np.ndarray], np.ndarray],
):
    """HostTier backfill source pulling grown rows out of restored flat
    tables — the lazy half of flat -> tiered migration."""

    def backfill(plane: str, fields: np.ndarray,
                 ids: np.ndarray) -> np.ndarray:
        table = flat_tables.get(plane)
        if table is None:
            return None
        flat_rows = np.asarray(
            hash_fn(np.asarray(fields, np.int64),
                    np.asarray(ids, np.int64)),
            np.int64,
        )
        return np.asarray(table, np.float32)[flat_rows]

    return backfill


# ---- migration: path-matched tree fill --------------------------------


def _walk(tree, prefix=()):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _walk(v, prefix + (str(k),))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _walk(v, prefix + (str(i),))
    else:
        yield prefix, tree


def fill_matching(template, raw):
    """Copy every leaf of `raw` whose normalized path AND shape match
    into a copy of `template` (dict keys and sequence indices both
    normalize to strings, so an orbax raw tree — which renders tuples as
    lists and int-keyed dicts as str-keyed — still lines up).  Leaves
    with no match keep the template's value: that is exactly what lets a
    flat arena table (capacity, dim) coexist with a tiered cache table
    (cache_rows, dim) under the same name across a migration."""
    raw_map = {path: leaf for path, leaf in _walk(raw)}

    def rebuild(node, prefix):
        if isinstance(node, dict):
            return {
                k: rebuild(v, prefix + (str(k),)) for k, v in node.items()
            }
        if isinstance(node, (list, tuple)):
            return type(node)(
                rebuild(v, prefix + (str(i),)) for i, v in enumerate(node)
            )
        leaf = raw_map.get(prefix)
        if (
            leaf is not None
            and hasattr(leaf, "shape") and hasattr(node, "shape")
            and tuple(leaf.shape) == tuple(node.shape)
        ):
            out = np.asarray(leaf)
            if hasattr(node, "dtype") and out.dtype != node.dtype:
                out = out.astype(node.dtype)
            return out
        return node

    return rebuild(template, ())
