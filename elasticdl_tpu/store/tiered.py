"""TieredStore: the orchestrator tying host tier, hot-row cache, and
the device seam together.

Data flow per training batch (single producer, single consumer):

  prefetch producer thread (wrap_feed/wrap_feed_bulk):
      prepare(sparse) -> (slots, CachePlan)
        - lazy vocab growth (host tier assign)
        - cache admission plan (frequency-ranked, deterministic)
        - enqueue async host-gather of admit-row values

  cold-miss prefetcher thread:
      gathers admit values from the host tier -> plan.ready

  consumer thread (trainer.train_on_batch, just before the step):
      apply_plan(state, plan) -> state'
        - read evicted rows from device, enqueue host fold
        - wait for prefetched admit values (deferred rows: flush the
          fold queue, then gather synchronously)
        - scatter admits into the cache param + zero their moments

  host-fold worker thread:
      set_rows(evicted values) into the host tier

Ordering invariant: prepare() runs strictly in batch order on the ONE
producer thread, and apply_plan()/train run strictly in batch order on
the consumer — so plan k+1's bookkeeping always reflects plan k's
admissions, and eviction write-backs always carry the latest trained
value.  Two free-running producer threads would break this, so
multi-worker Local training uses DEFERRED planning instead
(`enable_deferred_prepare`): feeds attach the raw sparse batch and the
trainer runs prepare+apply back to back at train time, under the
ModelOwner lock that already serializes every step — strict order is
restored at the cost of the async cold-gather overlap (docs/PERF.md
§4).  Sharding the row space itself across workers is
store/sharding.py's job.

The stale-value hazard — a row evicted by plan k and re-admitted by
plan k+j while its fold is still queued — is handled by the
`_pending_writeback` set: such admits are marked `deferred`, and
apply_plan flushes the fold queue before gathering them.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from elasticdl_tpu.common import events
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.metrics import MetricsRegistry
from elasticdl_tpu.store import device as store_device
from elasticdl_tpu.store.cache import (
    CACHE_DTYPES,
    CachePlan,
    HotRowCache,
    device_cache_bytes,
    partition_plan,
)
from elasticdl_tpu.store.host_tier import HostTier

logger = get_logger(__name__)


class TieredStore:
    """One store instance manages every embedding plane of one model
    (DeepFM: fm_embedding + fm_linear), sharing one vocabulary and one
    cache slot numbering across planes."""

    def __init__(self, planes: Dict[str, int], num_fields: int,
                 cache_rows: int, host_dtype: str = "fp32",
                 seed: int = 0x5EED,
                 param_paths: Optional[Dict[str, Tuple[str, ...]]] = None,
                 registry: Optional[MetricsRegistry] = None,
                 phase_timer=None, cache_dtype: str = "float32"):
        if cache_dtype not in CACHE_DTYPES:
            raise ValueError(
                f"cache_dtype must be one of {CACHE_DTYPES}, "
                f"got {cache_dtype!r}"
            )
        self.planes = dict(planes)
        self.num_fields = int(num_fields)
        self.cache_rows = int(cache_rows)
        self.cache_dtype = cache_dtype
        # Mesh-sharded seam (ISSUE 18b): >1 means the cache slot arena is
        # row-sharded over the model axis and every plan carries per-chip
        # sub-plans (accounting + tests; execution stays ONE fused
        # program — XLA partitions it from the table sharding).
        self.mesh_shards = 1
        self.host = HostTier(planes, num_fields, host_dtype, seed)
        self.cache = HotRowCache(cache_rows, dtype=cache_dtype)
        self.param_paths = dict(param_paths) if param_paths else {
            name: ("params", name, "embedding") for name in planes
        }
        self.phase_timer = phase_timer
        self.registry = registry if registry is not None else MetricsRegistry()

        self._lock = threading.Lock()
        # Deferred mode (multi-worker Local path): attach() ships the raw
        # sparse batch instead of planning eagerly; the trainer prepares
        # AND applies at train time under the one step-serializing lock.
        self.deferred_prepare = False
        self._pending_writeback = set()     # store rows with fold in flight
        self._gather_q: "queue.Queue" = queue.Queue()
        self._fold_q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._threads = []
        self._started = False
        # Liveness counters the Local-path regression test asserts on.
        self.prefetch_ticks = 0
        self.fold_ticks = 0
        # Cold-gather seconds split by where they ran: the prefetcher
        # thread (overlapped with compute) vs the consumer at apply time
        # (on the critical path).  The bench reports the overlap share.
        self.gather_async_s = 0.0
        self.gather_sync_s = 0.0

        self._hits = self.registry.counter(
            "store_cache_hits_total",
            "Embedding lookups served by the device hot-row cache",
        )
        self._misses = self.registry.counter(
            "store_cache_misses_total",
            "Embedding lookups that needed a host-tier admission",
        )
        self._growth = self.registry.counter(
            "store_growth_rows_total",
            "Vocabulary rows lazily grown on first lookup",
        )
        self._gather_hist = self.registry.histogram(
            "store_cold_gather_seconds",
            "Host-tier gather latency for cold-row admissions",
        )
        self.registry.gauge_fn(
            "store_cache_occupancy_rows",
            lambda: float(self.cache.occupancy),
            "Resident rows in the device hot-row cache",
        )
        self.registry.gauge_fn(
            "store_cache_hit_ratio",
            self._hit_ratio,
            "Lifetime cache hit fraction of embedding lookups",
        )
        self._block_plans = self.registry.counter(
            "store_block_plans_total",
            "Multi-batch admission plans spanning a fused step block",
        )
        self.registry.gauge_fn(
            "store_device_cache_bytes",
            lambda: float(self.device_cache_bytes()),
            "Resident byte footprint of the device hot-row cache values",
        )
        self.registry.gauge_fn(
            "store_mesh_shards_count",
            lambda: float(self.mesh_shards),
            "Model-axis shards the cache slot arena is partitioned over",
        )

    def device_cache_bytes(self) -> int:
        """Analytic VALUE bytes of the device cache at full capacity —
        q8 codes + per-row scales for int8, 4 bytes/element for fp32.
        The fp32 carrier and optimizer moments are identical in both
        modes and excluded (store/cache.py cache_value_bytes_per_row)."""
        return device_cache_bytes(
            self.planes, self.cache_rows, self.cache_dtype
        )

    def set_mesh_shards(self, n: int) -> None:
        """Declare the model-axis mesh size the cache params are sharded
        over.  cache_rows must split evenly so every chip owns an equal
        contiguous slot block (same contiguous row-blocking jax uses for
        a P(\"model\", None) table)."""
        n = int(n)
        if n < 1 or self.cache_rows % n:
            raise ValueError(
                f"cache_rows={self.cache_rows} must divide evenly over "
                f"{n} mesh shards"
            )
        self.mesh_shards = n

    def _hit_ratio(self) -> float:
        hits = self._hits.value()
        total = hits + self._misses.value()
        return (hits / total) if total else 0.0

    # ---- background threads -------------------------------------------

    def start(self) -> None:
        """Start the cold-miss prefetcher and host-fold worker.  The
        Local path must call this too (it never goes through
        Master.start) — client/api.py owns that call."""
        if self._started:
            return
        self._started = True
        self._stop.clear()
        for name, fn in (("store-prefetch", self._gather_loop),
                         ("store-fold", self._fold_loop)):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        if not self._started:
            return
        self._fold_q.join()      # drain pending write-backs first
        self._gather_q.put(None)
        self._fold_q.put(None)
        for t in self._threads:
            t.join(timeout=30.0)
        self._threads = []
        self._started = False

    def _gather_loop(self) -> None:
        while True:
            plan = self._gather_q.get()
            if plan is None:
                return
            try:
                t0 = time.perf_counter()
                plan.admit_values = self.host.gather(plan.prefetch_rows)
                dt = time.perf_counter() - t0
                self._gather_hist.record(dt)
                if self.phase_timer is not None:
                    self.phase_timer.add("cold_gather", dt)
                self.gather_async_s += dt
                self.prefetch_ticks += 1
            except Exception:
                logger.exception("cold-row prefetch failed")
            finally:
                plan.ready.set()

    def _fold_loop(self) -> None:
        while True:
            item = self._fold_q.get()
            if item is None:
                self._fold_q.task_done()
                return
            rows, values = item
            try:
                self.host.set_rows(rows, values)
                with self._lock:
                    for r in rows:
                        self._pending_writeback.discard(int(r))
                self.fold_ticks += 1
            except Exception:
                logger.exception("host fold failed")
            finally:
                self._fold_q.task_done()

    # ---- producer side -------------------------------------------------

    def prepare(self, sparse: np.ndarray, ranked=None):
        """Producer-side planning: grow vocab, plan cache admissions,
        kick off the async host gather.  Returns (slots, plan).  MUST be
        called in batch order from a single thread.

        `ranked` is an optional `(uniq_ids, counts)` frequency ranking of
        THIS batch's FIELD-ENCODED ids (DedupPacker.last_ranking over
        `wire.field_disjoint_ids(sparse)` — the vocab keys (field, id),
        so raw ids colliding across fields must not merge).  Encoded
        value <-> (field, id) <-> store row is then a bijection on the
        batch, so the counts carry over to rows unchanged — only the
        unique VALUES need translating, one first-occurrence lookup
        instead of a full re-rank."""
        from elasticdl_tpu.data.wire import field_disjoint_ids

        with self._lock:
            rows, n_new = self.host.assign(sparse)
            if ranked is not None:
                uniq_ids = np.asarray(ranked[0], np.int64)
                flat_ids = field_disjoint_ids(sparse).reshape(-1)
                flat_rows = np.asarray(rows, np.int64).reshape(-1)
                sort_idx = np.argsort(flat_ids, kind="stable")
                sorted_ids = flat_ids[sort_idx]
                pos = np.searchsorted(sorted_ids, uniq_ids)
                if pos.size and (
                    int(pos.max(initial=0)) >= sorted_ids.size
                    or np.any(sorted_ids[np.minimum(
                        pos, sorted_ids.size - 1)] != uniq_ids)
                ):
                    raise ValueError(
                        "ranking does not match this batch's encoded "
                        "ids — rank wire.field_disjoint_ids(sparse), "
                        "not the raw per-field ids"
                    )
                rows_u = flat_rows[sort_idx[pos]]
                counts_u = np.asarray(ranked[1], np.int64)
                # Tie-break in ROW space: the wire ranking breaks count
                # ties toward the smaller encoded id, but admission order
                # must match `frequency_rank(rows)` (ties -> smaller row;
                # vocab rows are claimed in first-occurrence order, so
                # the two orders genuinely differ).  One lexsort over the
                # k uniques — still no re-count of the full batch.
                order = np.lexsort((rows_u, -counts_u))
                ranked = (rows_u[order], counts_u[order])
            plan = self.cache.plan(rows, ranked=ranked)
            self._finish_plan_locked(plan, n_new)
        self._publish_plan(plan, n_new)
        return plan.slots, plan

    def prepare_block(self, sparse_list):
        """Plan ONE admission block covering the UNION of K batches'
        rows (steps_per_execution > 1, ISSUE 18c): the K fused steps
        run as one uninterruptible lax.scan, so per-batch plans are
        impossible (plan k+1 could evict rows batch k still needs,
        with no apply point between them).  Union planning makes every
        row of every batch resident for the whole block; evictions are
        rows OUTSIDE the union, so reading them before the block is
        exact.  Frequency ranking is recomputed over the union (a
        per-batch wire ranking doesn't aggregate across batches).

        Returns (slots_list, plan): K slot arrays, one plan whose
        admit/evict apply once before the block.  Same single-thread
        batch-order contract as prepare()."""
        if not sparse_list:
            raise ValueError("prepare_block needs at least one batch")
        with self._lock:
            rows_list = []
            n_new = 0
            for sparse in sparse_list:
                rows, grown = self.host.assign(sparse)
                rows_list.append(np.asarray(rows))
                n_new += grown
            union = np.concatenate([r.reshape(-1) for r in rows_list])
            plan = self.cache.plan(union)
            plan.block_batches = len(rows_list)
            self._finish_plan_locked(plan, n_new)
        self._publish_plan(plan, n_new)
        self._block_plans.inc()
        flat_slots = np.asarray(plan.slots).reshape(-1)
        slots_list = []
        offset = 0
        for rows in rows_list:
            size = rows.size
            slots_list.append(
                flat_slots[offset:offset + size].reshape(rows.shape)
            )
            offset += size
        return slots_list, plan

    def _finish_plan_locked(self, plan: CachePlan, n_new: int) -> None:
        plan.growth = n_new
        for r in plan.evict_rows:
            self._pending_writeback.add(int(r))
        plan.deferred = np.fromiter(
            (int(r) in self._pending_writeback
             for r in plan.admit_rows),
            bool, plan.admit_rows.size,
        )
        plan.prefetch_rows = plan.admit_rows[~plan.deferred]
        if self.mesh_shards > 1:
            plan.sub_plans = partition_plan(
                plan, self.mesh_shards, self.cache_rows
            )

    def _publish_plan(self, plan: CachePlan, n_new: int) -> None:
        self._hits.inc(plan.hits)
        self._misses.inc(plan.misses)
        if n_new:
            self._growth.inc(n_new)
            events.emit(events.STORE_GROWN, rows=n_new,
                        vocab_rows=self.host.size)
        if (plan.prefetch_rows.size and self._started
                and not self.deferred_prepare):
            self._gather_q.put(plan)
        else:
            # Nothing to prefetch (or threads not running: tests drive
            # apply_plan synchronously) — gather happens at apply time.
            # Deferred mode lands here on purpose: apply_plan runs
            # immediately after prepare, so bouncing the gather to the
            # prefetcher thread buys no overlap and would miscount the
            # wait as async; the sync gather is the honest attribution.
            plan.ready.set()

    # ---- consumer side -------------------------------------------------

    def apply_plan(self, state, plan: CachePlan):
        """Consumer-side execution, strictly before the train step that
        consumes `plan.slots`.  Returns the updated state."""
        if plan.evict_rows.size:
            evicted = store_device.read_rows(
                state, self.param_paths, plan.evict_slots,
                cache_dtype=self.cache_dtype,
            )
            self._fold_q.put((plan.evict_rows.copy(), evicted))
            if not self._started:
                self._drain_fold_queue_inline()
        if plan.admit_rows.size:
            plan.ready.wait()
            values = plan.admit_values
            missing = (
                plan.deferred
                if values
                else np.ones(plan.admit_rows.size, bool)
            )
            if missing.any():
                # Deferred rows: their latest value is on the fold queue
                # — flush it, then gather synchronously (attributed to
                # cold_gather on the consumer, i.e. NOT overlapped).
                t0 = time.perf_counter()
                self._fold_q.join()
                cold = self.host.gather(plan.admit_rows[missing])
                dt = time.perf_counter() - t0
                self._gather_hist.record(dt)
                if self.phase_timer is not None:
                    self.phase_timer.add("cold_gather", dt)
                self.gather_sync_s += dt
                full = {}
                for name, dim in self.planes.items():
                    arr = np.empty(
                        (plan.admit_rows.size, dim), np.float32
                    )
                    if values:
                        arr[~missing] = values[name]
                    arr[missing] = cold[name]
                    full[name] = arr
                values = full
            state = store_device.apply_admissions(
                state, self.param_paths, plan.admit_slots, values,
                cache_dtype=self.cache_dtype,
            )
        return state

    def _drain_fold_queue_inline(self) -> None:
        """Synchronous fold for thread-less (unit-test) operation."""
        while True:
            try:
                item = self._fold_q.get_nowait()
            except queue.Empty:
                return
            if item is None:
                self._fold_q.task_done()
                continue
            rows, values = item
            try:
                self.host.set_rows(rows, values)
                with self._lock:
                    for r in rows:
                        self._pending_writeback.discard(int(r))
                self.fold_ticks += 1
            finally:
                self._fold_q.task_done()

    # ---- feed integration ---------------------------------------------

    def enable_deferred_prepare(self) -> None:
        """Multi-worker Local mode: move planning from the (no longer
        unique) feed producer to the trainer's step-serialized critical
        section.  prepare+apply then run back to back in the SAME order
        the steps run, which restores the strict-batch-order invariant
        with any number of producer threads — trading away the async
        cold-gather overlap (every gather becomes a sync gather)."""
        self.deferred_prepare = True

    def attach(self, batch: dict) -> dict:
        """Rewrite one feed batch: raw `sparse` ids become cache `slots`,
        and the plan rides along under `__store_plan__` (popped by the
        trainer before any tree_map sees the batch).  A feed that packed
        this batch through DedupPacker can leave the packer's ranking
        under `__dedup_ranking__` (popped here, never shipped) and the
        admission plan reuses it.  In deferred mode the raw sparse batch
        (+ ranking) rides under `__store_sparse__` instead and the
        trainer plans at train time."""
        features = dict(batch["features"])
        sparse = features.pop("sparse")
        out = dict(batch)
        ranked = out.pop("__dedup_ranking__", None)
        if self.deferred_prepare:
            sparse = np.asarray(sparse)
            # Placeholder keeps the feature structure complete for
            # model.init / export signatures; the trainer overwrites it
            # with the real planned slots inside the step-serialized
            # region (train_on_batch's __store_sparse__ branch).
            features["slots"] = np.zeros(sparse.shape, np.int32)
            out["features"] = features
            out["__store_sparse__"] = (sparse, ranked)
            return out
        slots, plan = self.prepare(sparse, ranked=ranked)
        features["slots"] = slots
        out["features"] = features
        out["__store_plan__"] = plan
        return out

    def wrap_feed(self, feed):
        """Wrap a feed/feed_bulk callable so every batch it produces is
        store-prepared.  Runs on the prefetch producer thread — the ONE
        sequential prepare() site."""
        if feed is None:
            return None

        def wrapped(*args, **kwargs):
            return self.attach(feed(*args, **kwargs))

        return wrapped

    # ---- checkpoint integration ---------------------------------------

    def load_sidecar_state(self, host_state: Dict[str, np.ndarray],
                           row_of: np.ndarray,
                           score: Optional[np.ndarray] = None,
                           cache_dtype: Optional[str] = None,
                           convert: bool = False) -> None:
        """Adopt a restored sidecar: host planes + vocab + cache map.
        Cache VALUES live in the restored TrainState (orbax), so only
        bookkeeping changes here.  `cache_dtype` is the sidecar's
        recorded plane dtype (None for pre-ISSUE-18 sidecars = fp32);
        a mismatch against this store's dtype raises unless `convert`
        acknowledges the values were migrated (CheckpointSaver's
        arena_convert path)."""
        with self._lock:
            self.host.load_state_dict(host_state)
            self.cache.load_state_arrays(
                row_of, score, dtype=cache_dtype, convert=convert
            )
            self._pending_writeback.clear()

    # ---- introspection -------------------------------------------------

    def stats(self) -> dict:
        hits = self._hits.value()
        misses = self._misses.value()
        total = hits + misses
        return {
            "hit_rate": (hits / total) if total else 0.0,
            "hits": int(hits),
            "misses": int(misses),
            "growth_rows": int(self._growth.value()),
            "vocab_rows": self.host.size,
            "cache_occupancy_rows": self.cache.occupancy,
            "cache_rows": self.cache_rows,
            "cache_dtype": self.cache_dtype,
            "device_cache_bytes": self.device_cache_bytes(),
            "mesh_shards": self.mesh_shards,
            "block_plans": int(self._block_plans.value()),
            "host_bytes": self.host.nbytes,
            "prefetch_ticks": self.prefetch_ticks,
            "fold_ticks": self.fold_ticks,
            "cold_gather_async_s": self.gather_async_s,
            "cold_gather_sync_s": self.gather_sync_s,
            "cold_gather_overlap_share": (
                self.gather_async_s
                / max(self.gather_async_s + self.gather_sync_s, 1e-12)
            ),
        }
