"""Worker-side elastic mesh lifecycle.

The TPU-native replacement for the reference's Horovod-elastic worker logic
(SURVEY.md C15: retry on HorovodInternalError -> re-rendezvous -> rebuild
ring -> re-broadcast).  Here the cycle is (SURVEY.md §7):

  1. poll the master's rendezvous epoch between tasks (cheap RPC);
  2. on a bump: re-initialise the distributed runtime for the new
     (world_size, rank, coordinator) — `jax.distributed` on real
     multi-host TPU; a device-subset mesh in single-process tests;
  3. rebuild the mesh, re-place (or checkpoint-restore) the train state;
  4. continue pulling tasks — the task queue already re-leased anything
     the lost workers held, so no step-exact replay is needed.

The jitted train step is polymorphic over input shardings, so a re-mesh
does not invalidate the compiled-function cache key logic — XLA compiles
once per (shapes, shardings) combination and reuses entries when a prior
topology returns.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.proto import elasticdl_pb2 as pb

logger = get_logger(__name__)


class ElasticMeshManager:
    """Tracks the membership epoch and rebuilds the mesh on change.

    devices_for_world(world_size) -> device list lets tests map "one worker
    == one CPU device"; real deployments use all local devices (each worker
    process owns one host's chips and jax.distributed provides the global
    view).
    """

    def __init__(
        self,
        master_client,
        worker_id: int,
        devices_for_world: Optional[Callable] = None,
        use_jax_distributed: bool = False,
    ):
        self._client = master_client
        self._worker_id = worker_id
        self._devices_for_world = devices_for_world
        self._use_jax_distributed = use_jax_distributed
        self._known_id = -1
        self.world_size = 0
        self.rank = -1
        self.remesh_count = 0

    def fetch_spec(self) -> pb.ClusterSpec:
        return self._client.get_cluster_spec(
            pb.GetClusterSpecRequest(
                worker_id=self._worker_id,
                known_rendezvous_id=self._known_id,
            )
        )

    def is_new_epoch(self, spec: pb.ClusterSpec) -> bool:
        return spec.rendezvous_id != self._known_id

    def needs_remesh(self) -> bool:
        return self.is_new_epoch(self.fetch_spec())

    def build_mesh(self, spec: Optional[pb.ClusterSpec] = None):
        """Re-rendezvous and return the new mesh (None if this worker is
        no longer a member)."""
        spec = spec or self.fetch_spec()
        self._known_id = spec.rendezvous_id
        self.world_size = spec.world_size
        self.rank = next(
            (w.rank for w in spec.workers if w.worker_id == self._worker_id),
            -1,
        )
        if self.rank < 0 or self.world_size == 0:
            logger.warning(
                "Worker %d not in rendezvous %d",
                self._worker_id, spec.rendezvous_id,
            )
            return None
        if self._use_jax_distributed:
            # Real multi-host path: re-init the coordination service for
            # the new topology.  (jax.distributed.shutdown is a no-op if
            # never initialised.)
            jax.distributed.shutdown()
            jax.distributed.initialize(
                coordinator_address=spec.coordinator_address,
                num_processes=self.world_size,
                process_id=self.rank,
            )
            devices = jax.devices()
        elif self._devices_for_world is not None:
            devices = self._devices_for_world(self.world_size)
        else:
            devices = jax.devices()
        mesh = mesh_lib.create_mesh(devices, data=len(devices))
        self.remesh_count += 1
        logger.info(
            "Worker %d re-meshed: epoch=%d world=%d rank=%d devices=%d",
            self._worker_id, self._known_id, self.world_size, self.rank,
            len(devices),
        )
        return mesh
