"""Device-mesh management: the TPU-native replacement for the reference's
process-level cluster topology (PS shards + Horovod ring — SURVEY.md C15/C16).

All parallelism in elasticdl-tpu is expressed as a `jax.sharding.Mesh` with
up to five logical axes:

  data     — data parallelism (the reference's only strategy)
  model    — sharded embedding tables / tensor parallelism
  seq      — sequence/context parallelism (ring attention)
  expert   — expert parallelism (MoE)
  pipe     — pipeline parallelism (GPipe microbatch schedule, ops/pipeline)

Elasticity = rebuilding the mesh when membership changes: the rendezvous
server bumps an epoch, every process re-initialises jax.distributed with the
new topology, `create_mesh` lays the surviving devices out again, and the
train step recompiles for the new shapes (state restored from Orbax).  The
task queue makes this cheap — no step-exact replay, just re-leased shards.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"
PIPE_AXIS = "pipe"

# Trace-time mesh context: model code (e.g. ring attention inside a Flax
# module) needs the mesh for shard_map, but zoo `custom_model()` factories
# are mesh-agnostic.  The Trainer sets this before tracing/executing steps.
# THREAD-local: a background prewarm compile (Trainer.prewarm_*) traces
# under a different mesh concurrently with the training thread.
import contextlib as _contextlib
import threading as _threading

_MESH_TLS = _threading.local()
_DEFAULT_MESH: "Optional[Mesh]" = None


def set_current_mesh(mesh: "Mesh") -> None:
    global _DEFAULT_MESH
    _MESH_TLS.mesh = mesh
    # also serves as the cross-thread default: helper threads that never
    # set a mesh (data loaders calling feed etc.) see the training mesh
    _DEFAULT_MESH = mesh


# Export mode: serving export (jax2tf -> TF SavedModel) cannot stage
# shard_map or Pallas custom calls.  Inside this context, mesh-manual ops
# (ring attention, GPipe schedule, flash kernel) switch to their
# numerically-identical single-device lax formulations — the param tree is
# unchanged by design, so a checkpoint trained on any mesh exports.
_EXPORT_MODE = _threading.local()


@_contextlib.contextmanager
def export_mode():
    prev = getattr(_EXPORT_MODE, "on", False)
    _EXPORT_MODE.on = True
    try:
        yield
    finally:
        _EXPORT_MODE.on = prev


def in_export_mode() -> bool:
    return getattr(_EXPORT_MODE, "on", False)


def set_thread_mesh(mesh: "Mesh") -> None:
    """Thread-local ONLY (no cross-thread default update): for background
    work — prewarm compiles — that must not leak its mesh to others."""
    _MESH_TLS.mesh = mesh


def get_current_mesh() -> "Mesh":
    mesh = getattr(_MESH_TLS, "mesh", None)
    if mesh is not None:
        return mesh
    if _DEFAULT_MESH is not None:
        return _DEFAULT_MESH
    return create_mesh()


def create_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    data: int = -1,
    model: int = 1,
    seq: int = 1,
    expert: int = 1,
    pipe: int = 1,
) -> Mesh:
    """Build a mesh over `devices` (default: all).  `data=-1` absorbs the
    remaining devices after the explicit axes are carved out."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    fixed = model * seq * expert * pipe
    if data == -1:
        if n % fixed:
            raise ValueError(
                f"{n} devices not divisible by model*seq*expert*pipe={fixed}"
            )
        data = n // fixed
    if data * fixed != n:
        raise ValueError(
            f"mesh {data}x{model}x{seq}x{expert}x{pipe} != {n} devices"
        )
    # pipe is the OUTERMOST axis: neighbor stages land on ICI-adjacent
    # device groups, and the data/model/seq axes stay contiguous within a
    # stage (the same layout logic that keeps gradient reductions on ICI)
    arr = np.array(devices).reshape(pipe, data, model, seq, expert)
    return Mesh(
        arr, (PIPE_AXIS, DATA_AXIS, MODEL_AXIS, SEQ_AXIS, EXPERT_AXIS)
    )


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch sharding: leading axis split over `data` (replicated over the
    other mesh axes)."""
    return NamedSharding(mesh, P(DATA_AXIS))


def stacked_data_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a (K, B, ...) stack of K batches (steps_per_execution
    dispatch): the scan axis stays whole, the batch axis splits over
    `data`."""
    return NamedSharding(mesh, P(None, DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch: Dict[str, np.ndarray], mesh: Mesh):
    """Place a host batch onto the mesh split along the data axis.

    The dedup'd id plane (data/wire.py) is the one structured leaf: only
    its `inverse8` plane is batch-major; the unique/starts/exc_val side
    planes are whole-batch tables every shard reads, so they replicate
    (splitting them over `data` would be wrong — and (F,) `starts` does
    not even divide the axis)."""
    from elasticdl_tpu.data.wire import is_packed_dedup

    sharding = data_sharding(mesh)
    repl = replicated(mesh)

    def put(x):
        if is_packed_dedup(x):
            return {
                k: jax.device_put(v, sharding if k == "inverse8" else repl)
                for k, v in x.items()
            }
        return jax.device_put(x, sharding)

    return jax.tree.map(put, batch, is_leaf=is_packed_dedup)


def make_global_batch(batch: Dict[str, np.ndarray], mesh: Mesh):
    """Assemble a host batch into global `jax.Array`s split along `data`.

    Multi-process SPMD path: every process passes the SAME full global
    batch (each rank reads the whole shard); `make_array_from_callback`
    transfers only the locally-addressable shards, so no host holds or
    ships more than its slice to devices.  Works identically in
    single-process mode, where it degenerates to a plain sharded put.
    """
    sharding = data_sharding(mesh)

    def to_global(x):
        x = np.asarray(x)
        return jax.make_array_from_callback(
            x.shape, sharding, lambda idx: x[idx]
        )

    return jax.tree.map(to_global, batch)


def local_batch_range(mesh: Mesh, global_batch_size: int):
    """Rows [start, stop) of a data-sharded global batch that THIS
    process's addressable devices hold, or None when they are not one
    contiguous row range (exotic device layouts — callers then fall back
    to full-batch reads).  This is what lets each rank read only its
    1/world_size slice of a task's records (SURVEY §3.3: per-worker
    disjoint reads) instead of every rank reading the whole shard."""
    sharding = data_sharding(mesh)
    index_map = sharding.addressable_devices_indices_map(
        (global_batch_size,)
    )
    spans = set()
    for idx in index_map.values():
        sl = idx[0]
        start = 0 if sl.start is None else sl.start
        stop = global_batch_size if sl.stop is None else sl.stop
        spans.add((start, stop))
    starts = sorted(spans)
    lo, hi = starts[0][0], starts[0][1]
    for start, stop in starts[1:]:
        if start > hi:
            return None  # hole between this process's row spans
        hi = max(hi, stop)
    return lo, hi


def make_global_batch_from_local(
    batch: Dict[str, np.ndarray], mesh: Mesh, global_batch_size: int,
    local_start: int,
):
    """Assemble global `jax.Array`s from ONLY this process's local rows
    (`local_batch_range` slice starting at `local_start` in global
    coordinates).  The callback is invoked for addressable shards only,
    so no host materializes — or reads — rows outside its slice."""
    sharding = data_sharding(mesh)

    def to_global(x):
        x = np.asarray(x)
        shape = (global_batch_size,) + x.shape[1:]

        def fetch(idx):
            sl = idx[0]
            start = (0 if sl.start is None else sl.start) - local_start
            stop = (
                global_batch_size if sl.stop is None else sl.stop
            ) - local_start
            if start < 0 or stop > len(x):
                raise IndexError(
                    "requested global rows outside this rank's local "
                    "slice (local_batch_range mismatch)"
                )
            return x[start:stop]

        return jax.make_array_from_callback(shape, sharding, fetch)

    return jax.tree.map(to_global, batch)


def make_global_batch_stack_from_local(
    local_batches, mesh: Mesh, global_batch_size: int, local_start: int,
):
    """Assemble K local batches into global (K, B, ...) `jax.Array`s
    sharded P(None, data) — the steps_per_execution stack for the
    multi-process SPMD path.  Like make_global_batch_from_local, each
    host provides only its own rows of every batch in the stack."""
    stacked = jax.tree.map(lambda *xs: np.stack(xs), *local_batches)
    sharding = stacked_data_sharding(mesh)

    def to_global(x):
        shape = (x.shape[0], global_batch_size) + x.shape[2:]

        def fetch(idx):
            bsl = idx[1]
            start = (0 if bsl.start is None else bsl.start) - local_start
            stop = (
                global_batch_size if bsl.stop is None else bsl.stop
            ) - local_start
            if start < 0 or stop > x.shape[1]:
                raise IndexError(
                    "requested global rows outside this rank's local "
                    "slice (local_batch_range mismatch)"
                )
            return x[idx[0], start:stop]

        return jax.make_array_from_callback(shape, sharding, fetch)

    return jax.tree.map(to_global, stacked)


def pad_to_multiple(batch: Dict[str, np.ndarray], multiple: int):
    """Pad batch leading dim up to a multiple (wrapping existing rows) so
    shapes stay static under jit; returns (padded_batch, real_count)."""
    sizes = {x.shape[0] for x in jax.tree.leaves(batch)}
    assert len(sizes) == 1, "ragged batch"
    n = sizes.pop()
    if n % multiple == 0:
        return batch, n
    target = ((n + multiple - 1) // multiple) * multiple
    reps = (target + n - 1) // n

    def pad(x):
        return np.concatenate([x] * reps, axis=0)[:target]

    return jax.tree.map(pad, batch), n
