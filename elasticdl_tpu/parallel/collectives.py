"""Cross-host collective helpers above what XLA emits automatically.

Parity: reference python/collective_ops/ + Horovod wrapper (SURVEY.md
C15).  On TPU, device-level collectives are XLA's job: inside `jit` they
are emitted from shardings (the gradient all-reduce, the embedding
id-routing), and algorithmic `shard_map` code (ring attention, the GPipe
schedule) uses the `jax.lax` primitives directly.  What remains for a
framework module is the cross-HOST layer: process-level gathers for
host-side code.  There is deliberately no hand-rolled ring — XLA owns
scheduling and fusion — and no wrapper aliases around `jax.lax`
(earlier rounds carried broadcast/pmean helpers with no production
callers; they were deleted rather than kept as vocabulary).
"""

from __future__ import annotations

import jax
import numpy as np


def host_snapshot(tree):
    """Deep, OWNING host copy of a pytree of (possibly sharded) arrays.

    `np.asarray(arr)` on the CPU backend can be a zero-copy VIEW of the
    device buffer; a later donating step (`jit(..., donate_argnums=...)`)
    hands that buffer back to XLA for reuse and silently rewrites the
    "snapshot" in place.  Anything that captures state for later
    comparison or serialization while training continues (checkpoint
    reference copies, model export) must copy unconditionally."""
    return jax.tree.map(
        lambda x: np.array(x, copy=True) if hasattr(x, "shape") else x,
        tree,
    )


def host_allgather(x) -> np.ndarray:
    """Gather a (possibly data-sharded) array fully onto EVERY host as a
    numpy value.  Used where device results must reach host-side code that
    needs all rows — metric fns (AUC over the whole eval shard) and
    prediction output (worker/spmd.py).  No-op in single-process mode."""
    if jax.process_count() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(x, tiled=True)
