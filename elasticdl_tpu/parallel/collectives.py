"""Collective operations above what XLA emits automatically.

Parity: reference python/collective_ops/ + Horovod wrapper (SURVEY.md C15).
On TPU, device-level collectives are XLA's job: inside `jit` they are
emitted from shardings, and inside `shard_map` code uses the `jax.lax`
primitives directly.  What remains for a framework module is the
cross-HOST layer (process-level gathers for host-side metrics/output) and
the named patterns the reference's Horovod wrapper provided (gradient
allreduce, broadcast-on-init).  There is deliberately no hand-rolled ring
— XLA owns scheduling and fusion.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.parallel.mesh import DATA_AXIS


def host_allgather(x) -> np.ndarray:
    """Gather a (possibly data-sharded) array fully onto EVERY host as a
    numpy value.  Used where device results must reach host-side code that
    needs all rows — metric fns (AUC over the whole eval shard) and
    prediction output (worker/spmd.py).  No-op in single-process mode."""
    if jax.process_count() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(x, tiled=True)


def allreduce_mean_gradients(grads, axis_name: str = DATA_AXIS):
    """Explicit DP gradient averaging for shard_map-style training loops.
    (The jit/NamedSharding path does not need this — the partitioner
    inserts the reduction.)"""
    return jax.tree.map(lambda g: jax.lax.pmean(g, axis_name), grads)


def broadcast_from(value, root: int = 0, axis_name: str = DATA_AXIS):
    """Broadcast `value` from shard `root` to all shards of `axis_name`
    (the Horovod broadcast-variables-on-init equivalent, used after an
    elastic re-init when a replacement worker must adopt rank 0's state)."""
    idx = jax.lax.axis_index(axis_name)
    masked = jax.tree.map(
        lambda v: jnp.where(idx == root, v, jnp.zeros_like(v)), value
    )
    return jax.tree.map(lambda v: jax.lax.psum(v, axis_name), masked)
