"""Collective-ops surface.

Parity: reference python/collective_ops/ + Horovod wrapper (SURVEY.md C15).
On TPU these are XLA collectives over ICI/DCN; inside `jit` they are
emitted automatically from shardings, and inside `shard_map` they are the
explicit `jax.lax` primitives re-exported here.  This module exists so
framework code has ONE place naming the communication vocabulary; there is
deliberately no hand-rolled ring — XLA owns scheduling and fusion.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from elasticdl_tpu.parallel.mesh import DATA_AXIS

# explicit collectives for shard_map code
psum = jax.lax.psum
pmean = jax.lax.pmean
pmax = jax.lax.pmax
pmin = jax.lax.pmin
all_gather = jax.lax.all_gather
ppermute = jax.lax.ppermute
all_to_all = jax.lax.all_to_all
axis_index = jax.lax.axis_index


def allreduce_mean_gradients(grads, axis_name: str = DATA_AXIS):
    """Explicit DP gradient averaging for shard_map-style training loops.
    (The jit/NamedSharding path does not need this — the partitioner
    inserts the reduction.)"""
    return jax.tree.map(lambda g: jax.lax.pmean(g, axis_name), grads)


def broadcast_from(value, root: int = 0, axis_name: str = DATA_AXIS):
    """Broadcast `value` from shard `root` to all shards of `axis_name`
    (the Horovod broadcast-variables-on-init equivalent, used after an
    elastic re-init when a replacement worker must adopt rank 0's state)."""
    idx = jax.lax.axis_index(axis_name)
    masked = jax.tree.map(
        lambda v: jnp.where(idx == root, v, jnp.zeros_like(v)), value
    )
    return jax.tree.map(lambda v: jax.lax.psum(v, axis_name), masked)
