"""Fused embedding arena: every same-`dim` feature table as ONE array.

Motivation (BENCH r05, docs/PERF.md): a model with F separate
`DistributedEmbedding` tables issues F gather kernels forward and F
scatter-add kernels backward per step.  Each kernel pays its own
dispatch/fusion boundary, and on the row-sharded layout each pays its own
cross-shard routing.  Stacking all same-dimension tables into one
row-sharded **arena** — per-feature row ranges, addressed by
`offset + hash(id) % capacity` — collapses that to ONE gather and ONE
scatter-add over the concatenated ids, regardless of feature count.

Per-feature capacities survive: feature i owns rows
[offset_i, offset_i + capacity_i), and its ids are hashed mod its OWN
capacity before the offset shift, so collision behavior is identical to
an isolated table of that capacity.  The arena parameter is named
"embedding" so `embedding_param_sharding` row-shards it over the mesh
`model` axis exactly like individual tables.

The VJP stays the plain gather/scatter-add pair
(`embedding.py:_lookup`) per the round-4 re-measurement
(docs/embedding_design_note.md): the scatter is the ceiling; fancier
backwards lost.  Note the round-5 finding also stands: do NOT fuse
tables of DIFFERENT dims into one padded arena — lane padding eats the
win.  One arena per distinct dim.
"""

from __future__ import annotations

from typing import Dict, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.layers.embedding import _lookup, hash_ids, hash_ids_host


def arena_offsets(features: Tuple[Tuple[str, int], ...]) -> Dict[str, int]:
    """{feature name: first arena row} for a (name, capacity) tuple."""
    offsets, total = {}, 0
    for name, capacity in features:
        offsets[name] = total
        total += int(capacity)
    return offsets


def arena_rows(features: Tuple[Tuple[str, int], ...]) -> int:
    return sum(int(capacity) for _, capacity in features)


class EmbeddingArena(nn.Module):
    """N per-feature embedding tables fused into one parameter.

    features:   ordered ((name, capacity), ...) — one entry per logical
                table; order fixes the row layout.
    output_dim: shared embedding dimension (one arena per dim).
    hash_input: multiplicative-mix ids before the per-feature mod
                (same semantics as DistributedEmbedding).

    Call with a dict {name: int ids of any shape (..., )}; returns
    {name: (..., output_dim)} vectors.  All features' ids are hashed
    into arena rows, concatenated, and looked up with ONE `_lookup`
    (one gather forward, one scatter-add backward).

    Call with `prehashed=True` and a single int32 array of arena rows
    (host-hashed via `arena_rows_host` / the dedup'd wire format) to
    skip the on-device hashing entirely.
    """

    features: Tuple[Tuple[str, int], ...]
    output_dim: int
    pad_id: int = -1
    hash_input: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, ids, prehashed: bool = False):
        table = self.param(
            "embedding",
            nn.initializers.normal(stddev=0.05),
            (arena_rows(self.features), self.output_dim),
            self.param_dtype,
        )
        if prehashed:
            rows = jnp.asarray(ids)
            return _lookup(table, rows.reshape(-1)).reshape(
                rows.shape + (self.output_dim,)
            )
        if set(ids) != {name for name, _ in self.features}:
            raise ValueError(
                f"arena expects ids for {[n for n, _ in self.features]}, "
                f"got {sorted(ids)}"
            )
        # Per-feature hashed rows, flattened per example and concatenated:
        # the single gather's id stream.  Pure index arithmetic — XLA
        # fuses it into the gather; no extra kernels.
        batch = None
        parts, valids, shapes = [], [], []
        offset = 0
        for name, capacity in self.features:
            x = jnp.asarray(ids[name])
            if batch is None:
                batch = x.shape[0]
            valid = x != self.pad_id
            rows = hash_ids(
                jnp.where(valid, x, 0), capacity, mix=self.hash_input
            ) + jnp.int32(offset)
            parts.append(rows.reshape(batch, -1))
            valids.append(valid.reshape(batch, -1))
            shapes.append(x.shape)
            offset += int(capacity)
        all_rows = jnp.concatenate(parts, axis=1)          # (B, sum k_i)
        all_valid = jnp.concatenate(valids, axis=1)
        vecs = _lookup(table, all_rows.reshape(-1)).reshape(
            all_rows.shape + (self.output_dim,)
        )
        vecs = jnp.where(all_valid[..., None], vecs, 0.0)
        out, col = {}, 0
        for (name, _), shape in zip(self.features, shapes):
            k = int(np.prod(shape[1:], dtype=np.int64)) if len(shape) > 1 \
                else 1
            out[name] = vecs[:, col: col + k].reshape(
                shape + (self.output_dim,)
            )
            col += k
        return out

    # ---- host-side helpers (packers / equivalence tests) ---------------

    def arena_rows_host(self, ids: Dict[str, "np.ndarray"]) -> np.ndarray:
        """numpy replica of the device row computation: {name: (B, k)}
        raw ids -> (B, sum k) int32 arena rows, bit-exact vs the traced
        path.  Used by host packers (dedup'd wire format) so the device
        consumes rows directly (`prehashed=True`)."""
        parts, offset = [], 0
        for name, capacity in self.features:
            x = np.asarray(ids[name])
            if np.any(x == self.pad_id):
                raise ValueError(
                    f"arena_rows_host: feature {name!r} contains pad ids "
                    f"({self.pad_id}); the prehashed fast path cannot "
                    "represent masked positions — use the per-feature path"
                )
            rows = hash_ids_host(x, capacity, mix=self.hash_input) + offset
            parts.append(rows.reshape(x.shape[0], -1).astype(np.int32))
            offset += int(capacity)
        return np.concatenate(parts, axis=1)


def arena_table_from_feature_tables(
    features: Tuple[Tuple[str, int], ...], tables: Dict[str, jnp.ndarray]
) -> jnp.ndarray:
    """Stack per-feature tables (e.g. from trained DistributedEmbedding
    params) into the arena parameter, preserving row layout — the bridge
    for proving arena/per-feature numerical identity and for migrating
    checkpoints of per-table models."""
    parts = []
    for name, capacity in features:
        t = jnp.asarray(tables[name])
        if t.shape[0] != capacity:
            raise ValueError(
                f"table {name!r} has {t.shape[0]} rows, arena slot has "
                f"{capacity}"
            )
        parts.append(t)
    return jnp.concatenate(parts, axis=0)
