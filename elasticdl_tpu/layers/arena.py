"""Fused embedding arena: every same-`dim` feature table as ONE array.

Motivation (BENCH r05, docs/PERF.md): a model with F separate
`DistributedEmbedding` tables issues F gather kernels forward and F
scatter-add kernels backward per step.  Each kernel pays its own
dispatch/fusion boundary, and on the row-sharded layout each pays its own
cross-shard routing.  Stacking all same-dimension tables into one
row-sharded **arena** — per-feature row ranges, addressed by
`offset + hash(id) % capacity` — collapses that to ONE gather and ONE
scatter-add over the concatenated ids, regardless of feature count.

Per-feature capacities survive: feature i owns rows
[offset_i, offset_i + capacity_i), and its ids are hashed mod its OWN
capacity before the offset shift, so collision behavior is identical to
an isolated table of that capacity.  The arena parameter is named
"embedding" so `embedding_param_sharding` row-shards it over the mesh
`model` axis exactly like individual tables.

The VJP stays the plain gather/scatter-add pair
(`embedding.py:_lookup`) per the round-4 re-measurement
(docs/embedding_design_note.md): the scatter is the ceiling; fancier
backwards lost.  Note the round-5 finding also stands: do NOT fuse
tables of DIFFERENT dims into one padded arena — lane padding eats the
win.  One arena per distinct dim.

Quantized storage (`arena_dtype="int8"`, docs/PERF.md "Quantized
arena"): rows live as int8 codes with a per-row fp32 scale — a second
plane alongside the arena — and are dequantized INSIDE the fused
gather, so the step still issues one (code+scale) gather and one
scatter-add regardless of feature count while the dominant
bytes-accessed term shrinks ~4x.  The gradient/optimizer path stays
fp32: a zero fp32 "carrier" parameter keeps the trainable name/shape,
`_grad_tap` routes the scatter-add gradient into it, and
`fold_quantized_updates` folds the optimizer's per-step delta back into
the codes with STOCHASTIC rounding (seeded from the step counter) so
low-magnitude updates are unbiased rather than truncated.  All int8
plane arithmetic lives in this module — graftlint GL-QUANT
(docs/LINTS.md) rejects raw-plane math anywhere else.
"""

from __future__ import annotations

import zlib
from collections.abc import Mapping
from typing import Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.layers.embedding import (
    _PIB,
    _lookup,
    hash_ids,
    hash_ids_host,
)

ARENA_DTYPES = ("float32", "int8")

# int8 code range is symmetric [-127, 127]: -128 is unused so negation
# round-trips and scale = max|row| / 127 covers the row exactly.
_Q_MAX = 127.0

# RNG namespace for the training write-back rounding; folded with the
# step counter and the plane path so every data-parallel replica — and
# every re-trace — rounds identically (deterministic RNG plumbing).
_FOLD_SEED = 0x51A7

# ---- quantization numerics (ALL int8 plane math lives here) ------------


def quantize_rows(table):
    """fp32 (R, D) -> (int8 codes (R, D), fp32 scales (R, 1)).

    Per-row symmetric quantization: scale = max|row| / 127 (all-zero
    rows get scale 1.0 so they round-trip exactly), codes round to
    nearest.  Deterministic — used by converters and arena init; the
    TRAINING write-back uses `stochastic_round` so repeated
    low-magnitude updates are unbiased instead of truncated."""
    table = jnp.asarray(table, jnp.float32)
    max_abs = jnp.max(jnp.abs(table), axis=1, keepdims=True)
    scale = jnp.where(max_abs > 0, max_abs / _Q_MAX, 1.0)
    q8 = jnp.clip(jnp.round(table / scale), -_Q_MAX, _Q_MAX).astype(jnp.int8)
    return q8, scale


def dequantize_rows(q8, scale):
    """int8 codes + per-row scales -> the fp32 view the math runs on."""
    return q8.astype(jnp.float32) * scale


def quantize_rows_host(table: "np.ndarray"):
    """numpy mirror of `quantize_rows` for the tiered store's host tier
    (elasticdl_tpu/store/host_tier.py): fp32 (R, D) -> (int8 codes,
    fp32 (R, 1) scales), bit-identical numerics to the device version.
    Lives HERE because GL-QUANT sanctions plane arithmetic only in this
    module — the host tier stores and indexes the planes but never does
    math on them."""
    table = np.asarray(table, np.float32)
    max_abs = np.max(np.abs(table), axis=1, keepdims=True) \
        if table.size else np.zeros((table.shape[0], 1), np.float32)
    scale = np.where(max_abs > 0, max_abs / _Q_MAX, 1.0).astype(np.float32)
    q8 = np.clip(
        np.round(table / scale), -_Q_MAX, _Q_MAX
    ).astype(np.int8)
    return q8, scale


def dequantize_rows_host(q8: "np.ndarray", scale: "np.ndarray"):
    """numpy mirror of `dequantize_rows` (see quantize_rows_host)."""
    return q8.astype(np.float32) * np.asarray(scale, np.float32)


def stochastic_round(x, key):
    """Unbiased integer rounding: floor(x + U[0,1)), so E[result] == x
    and exact integers return exactly (floor(k + u) == k for u < 1) —
    codes that didn't move round-trip bit-stable."""
    u = jax.random.uniform(key, x.shape, x.dtype)
    return jnp.clip(jnp.floor(x + u), -_Q_MAX, _Q_MAX).astype(jnp.int8)


@jax.custom_vjp
def _grad_tap(carrier, flat_ids):
    """Gradient collector for the quantized arena.

    Forward contributes exact ZEROS shaped like the gather output —
    built from the carrier's shape/dtype only, so XLA folds the add
    away and never reads the fp32 carrier's bytes; the int8 planes are
    the only table bytes the forward touches.  Backward scatter-adds
    the output cotangent into the carrier's shape — the same
    scatter-add `_lookup` produces for an fp32 table — so the optimizer
    sees an ordinary fp32 embedding gradient on the zero carrier and
    `fold_quantized_updates` later folds the resulting delta into the
    codes."""
    return jnp.zeros(flat_ids.shape + (carrier.shape[1],), carrier.dtype)


def _grad_tap_fwd(carrier, flat_ids):
    return _grad_tap(carrier, flat_ids), (carrier, flat_ids)


def _grad_tap_bwd(residuals, g):
    carrier, flat_ids = residuals
    dcarrier = (
        jnp.zeros(carrier.shape, g.dtype).at[flat_ids].add(g, mode=_PIB)
    )
    return dcarrier.astype(carrier.dtype), None


_grad_tap.defvjp(_grad_tap_fwd, _grad_tap_bwd)


def arena_offsets(features: Tuple[Tuple[str, int], ...]) -> Dict[str, int]:
    """{feature name: first arena row} for a (name, capacity) tuple."""
    offsets, total = {}, 0
    for name, capacity in features:
        offsets[name] = total
        total += int(capacity)
    return offsets


def arena_rows(features: Tuple[Tuple[str, int], ...]) -> int:
    return sum(int(capacity) for _, capacity in features)


class EmbeddingArena(nn.Module):
    """N per-feature embedding tables fused into one parameter.

    features:   ordered ((name, capacity), ...) — one entry per logical
                table; order fixes the row layout.
    output_dim: shared embedding dimension (one arena per dim).
    hash_input: multiplicative-mix ids before the per-feature mod
                (same semantics as DistributedEmbedding).

    Call with a dict {name: int ids of any shape (..., )}; returns
    {name: (..., output_dim)} vectors.  All features' ids are hashed
    into arena rows, concatenated, and looked up with ONE `_lookup`
    (one gather forward, one scatter-add backward).

    Call with `prehashed=True` and a single int32 array of arena rows
    (host-hashed via `arena_rows_host` / the dedup'd wire format) to
    skip the on-device hashing entirely.

    arena_dtype: "float32" (default — bit-identical to the PR 3 path)
    or "int8" (quantized storage: int8 codes + per-row fp32 scales in
    the mutable "quantized" collection, a zero fp32 carrier param for
    the gradient; see the module docstring).
    """

    features: Tuple[Tuple[str, int], ...]
    output_dim: int
    pad_id: int = -1
    hash_input: bool = True
    param_dtype: jnp.dtype = jnp.float32
    arena_dtype: str = "float32"

    @nn.compact
    def __call__(self, ids, prehashed: bool = False):
        if self.arena_dtype not in ARENA_DTYPES:
            raise ValueError(
                f"arena_dtype must be one of {ARENA_DTYPES}, got "
                f"{self.arena_dtype!r}"
            )
        shape = (arena_rows(self.features), self.output_dim)
        if self.arena_dtype == "int8":
            # Trainable ZERO carrier: same name/shape as the fp32 table,
            # so sharding, opt_state structure, and checkpoint paths are
            # identical across modes.  It holds this step's optimizer
            # delta between apply_updates and fold_quantized_updates.
            carrier = self.param(
                "embedding", nn.initializers.zeros, shape, jnp.float32
            )

            def _init_planes():
                sample = nn.initializers.normal(stddev=0.05)(
                    self.make_rng("params"), shape, jnp.float32
                )
                q8, scale = quantize_rows(sample)
                return {"q8": q8, "scale": scale}

            planes = self.variable("quantized", "embedding", _init_planes)
            q8 = planes.value["q8"]
            scale = planes.value["scale"]

            def lookup(flat_rows):
                # dequantize INSIDE the fused gather: code gather +
                # scale gather + one multiply; `_grad_tap` adds exact
                # zeros forward and collects the scatter-add backward.
                deq = dequantize_rows(
                    q8.at[flat_rows].get(mode=_PIB),
                    scale.at[flat_rows].get(mode=_PIB),
                )
                return deq + _grad_tap(carrier, flat_rows)
        else:
            table = self.param(
                "embedding",
                nn.initializers.normal(stddev=0.05),
                shape,
                self.param_dtype,
            )

            def lookup(flat_rows):
                return _lookup(table, flat_rows)

        if prehashed:
            rows = jnp.asarray(ids)
            return lookup(rows.reshape(-1)).reshape(
                rows.shape + (self.output_dim,)
            )
        if set(ids) != {name for name, _ in self.features}:
            raise ValueError(
                f"arena expects ids for {[n for n, _ in self.features]}, "
                f"got {sorted(ids)}"
            )
        # Per-feature hashed rows, flattened per example and concatenated:
        # the single gather's id stream.  Pure index arithmetic — XLA
        # fuses it into the gather; no extra kernels.
        batch = None
        parts, valids, shapes = [], [], []
        offset = 0
        for name, capacity in self.features:
            x = jnp.asarray(ids[name])
            if batch is None:
                batch = x.shape[0]
            valid = x != self.pad_id
            rows = hash_ids(
                jnp.where(valid, x, 0), capacity, mix=self.hash_input
            ) + jnp.int32(offset)
            parts.append(rows.reshape(batch, -1))
            valids.append(valid.reshape(batch, -1))
            shapes.append(x.shape)
            offset += int(capacity)
        all_rows = jnp.concatenate(parts, axis=1)          # (B, sum k_i)
        all_valid = jnp.concatenate(valids, axis=1)
        vecs = lookup(all_rows.reshape(-1)).reshape(
            all_rows.shape + (self.output_dim,)
        )
        vecs = jnp.where(all_valid[..., None], vecs, 0.0)
        out, col = {}, 0
        for (name, _), shape in zip(self.features, shapes):
            k = int(np.prod(shape[1:], dtype=np.int64)) if len(shape) > 1 \
                else 1
            out[name] = vecs[:, col: col + k].reshape(
                shape + (self.output_dim,)
            )
            col += k
        return out

    # ---- host-side helpers (packers / equivalence tests) ---------------

    def arena_rows_host(self, ids: Dict[str, "np.ndarray"]) -> np.ndarray:
        """numpy replica of the device row computation: {name: (B, k)}
        raw ids -> (B, sum k) int32 arena rows, bit-exact vs the traced
        path.  Used by host packers (dedup'd wire format) so the device
        consumes rows directly (`prehashed=True`)."""
        parts, offset = [], 0
        for name, capacity in self.features:
            x = np.asarray(ids[name])
            if np.any(x == self.pad_id):
                raise ValueError(
                    f"arena_rows_host: feature {name!r} contains pad ids "
                    f"({self.pad_id}); the prehashed fast path cannot "
                    "represent masked positions — use the per-feature path"
                )
            rows = hash_ids_host(x, capacity, mix=self.hash_input) + offset
            parts.append(rows.reshape(x.shape[0], -1).astype(np.int32))
            offset += int(capacity)
        return np.concatenate(parts, axis=1)


class TieredArena(nn.Module):
    """Device half of the tiered embedding store (elasticdl_tpu/store).

    Where `EmbeddingArena` holds the FULL vocabulary in HBM, this module
    holds only a `cache_rows`-row hot cache; the full (lazily grown)
    vocabulary lives in the store's host-RAM tier.  The cache table is
    the ONLY trainable storage — the store's admission plan guarantees
    every row a training batch touches is cache-resident before the step
    runs, so the jitted train step is structurally identical to the flat
    arena's (one gather forward, one scatter-add backward) and
    numerically identical on an all-hot working set.

    Call with `slots` (..., F) int32 CACHE slots (from
    TieredStore.prepare).  Training always passes resident slots
    (>= 0).  Serving may pass `slot == -1` for cold/unknown ids together
    with `overlay` — a (..., F, dim) plane of host-gathered values for
    exactly those positions; overlay values are stop_gradient'ed (cold
    rows train host-side via the store's fold path, never through the
    device optimizer).

    `cache_dtype="int8"` quantizes the CACHE storage exactly like
    `EmbeddingArena`'s int8 mode: q8 codes + per-row fp32 scales in the
    "quantized" collection, dequantized inside the same fused gather, a
    zero fp32 carrier param (same "embedding" name/shape, so sharding /
    opt_state / checkpoint structure are mode-invariant) collecting the
    scatter-add gradient via `_grad_tap`, and the per-step optimizer
    delta folded back into the codes by the SAME `fold_quantized_updates`
    the flat int8 arena uses — the trainer already calls it
    unconditionally.  Admissions quantize host values into the planes
    through `store/device.py` (the store-side GL-QUANT allowlist).
    """

    cache_rows: int
    output_dim: int
    param_dtype: jnp.dtype = jnp.float32
    cache_dtype: str = "float32"

    @nn.compact
    def __call__(self, slots, overlay=None):
        if self.cache_dtype not in ARENA_DTYPES:
            raise ValueError(
                f"cache_dtype must be one of {ARENA_DTYPES}, got "
                f"{self.cache_dtype!r}"
            )
        shape = (int(self.cache_rows), self.output_dim)
        if self.cache_dtype == "int8":
            carrier = self.param(
                "embedding", nn.initializers.zeros, shape, jnp.float32
            )

            def _init_planes():
                # Same init DISTRIBUTION as the fp32 cache (and the flat
                # arena): a never-admitted slot behaves like a fresh row,
                # modulo the one-shot quantization error.
                sample = nn.initializers.normal(stddev=0.05)(
                    self.make_rng("params"), shape, jnp.float32
                )
                q8, scale = quantize_rows(sample)
                return {"q8": q8, "scale": scale}

            planes = self.variable("quantized", "embedding", _init_planes)
            q8 = planes.value["q8"]
            scale = planes.value["scale"]

            def lookup(flat_rows):
                deq = dequantize_rows(
                    q8.at[flat_rows].get(mode=_PIB),
                    scale.at[flat_rows].get(mode=_PIB),
                )
                return deq + _grad_tap(carrier, flat_rows)
        else:
            # Same initializer as the flat arena: a slot that is never
            # admitted before first use behaves like a fresh flat-arena
            # row.
            table = self.param(
                "embedding",
                nn.initializers.normal(stddev=0.05),
                shape,
                self.param_dtype,
            )

            def lookup(flat_rows):
                return _lookup(table, flat_rows)

        rows = jnp.asarray(slots)
        flat = rows.reshape(-1)
        hot = lookup(jnp.maximum(flat, 0)).reshape(
            rows.shape + (self.output_dim,)
        )
        if overlay is None:
            return hot
        cold = jax.lax.stop_gradient(
            jnp.asarray(overlay).astype(hot.dtype)
        )
        return jnp.where((rows >= 0)[..., None], hot, cold)


def arena_table_from_feature_tables(
    features: Tuple[Tuple[str, int], ...], tables: Dict[str, jnp.ndarray]
) -> jnp.ndarray:
    """Stack per-feature tables (e.g. from trained DistributedEmbedding
    params) into the arena parameter, preserving row layout — the bridge
    for proving arena/per-feature numerical identity and for migrating
    checkpoints of per-table models."""
    parts = []
    for name, capacity in features:
        t = jnp.asarray(tables[name])
        if t.shape[0] != capacity:
            raise ValueError(
                f"table {name!r} has {t.shape[0]} rows, arena slot has "
                f"{capacity}"
            )
        parts.append(t)
    return jnp.concatenate(parts, axis=0)


# ---- quantized write-back + checkpoint migration -----------------------


def is_quantized_planes(node) -> bool:
    """True for the {"q8", "scale"} plane dict a quantized arena stores
    under model_state["quantized"]/<module path>/embedding."""
    return isinstance(node, Mapping) and set(node) == {"q8", "scale"}


def _path_seed(path: Tuple[str, ...]) -> int:
    return zlib.crc32("/".join(path).encode()) & 0x7FFFFFFF


def _requantize_plane(planes, delta, key):
    q8, scale = planes["q8"], planes["scale"]
    # Rows this step never touched have delta exactly 0 (adam's update
    # is 0 when m = v = 0) — keep their codes/scales BIT-stable rather
    # than re-rounding, so idle rows don't random-walk.
    touched = jnp.any(delta != 0.0, axis=1, keepdims=True)
    table = dequantize_rows(q8, scale) + delta
    max_abs = jnp.max(jnp.abs(table), axis=1, keepdims=True)
    new_scale = jnp.where(max_abs > 0, max_abs / _Q_MAX, 1.0)
    new_q8 = stochastic_round(table / new_scale, key)
    return {
        "q8": jnp.where(touched, new_q8, q8),
        "scale": jnp.where(touched, new_scale, scale),
    }


def fold_quantized_updates(params, model_state, step):
    """Post-`optax.apply_updates` write-back for quantized arenas.

    In int8 mode the trainable "embedding" param is a ZERO fp32
    carrier, so after the optimizer applies its update the carrier
    holds exactly this step's per-row fp32 delta.  Fold it: table =
    dequant(q8, scale) + delta, re-derive the per-row scale,
    stochastic-round back to int8 (keyed on (seed, step, plane path) so
    every data-parallel replica rounds identically), and zero the
    carrier for the next step.

    A trace-time no-op (returns the inputs unchanged) when the model
    has no "quantized" collection — the fp32 path stays bit-identical.
    """
    quant = (
        model_state.get("quantized")
        if isinstance(model_state, Mapping) else None
    )
    if not quant:
        return params, model_state
    step_key = jax.random.fold_in(
        jax.random.PRNGKey(_FOLD_SEED), jnp.asarray(step, jnp.uint32)
    )

    def walk(qt, ct, path):
        if is_quantized_planes(qt):
            key = jax.random.fold_in(step_key, _path_seed(path))
            return _requantize_plane(qt, ct, key), jnp.zeros_like(ct)
        new_q, new_c = {}, dict(ct)
        for k in qt:
            new_q[k], new_c[k] = walk(qt[k], ct[k], path + (k,))
        return new_q, new_c

    new_quant, new_inner = walk(quant, params["params"], ())
    new_params = dict(params)
    new_params["params"] = new_inner
    new_state = dict(model_state)
    new_state["quantized"] = new_quant
    return new_params, new_state


def quantized_planes_like(table):
    """Abstract plane template for one arena table leaf: the shapes and
    dtypes `arena_dtype="int8"` stores for a (R, D) table."""
    rows, dim = table.shape
    return {
        "q8": jax.ShapeDtypeStruct((rows, dim), jnp.int8),
        "scale": jax.ShapeDtypeStruct((rows, 1), jnp.float32),
    }


def quantize_arena_tree(params, quantized_template):
    """fp32 -> int8 checkpoint migration: params is the inner "params"
    dict of an fp32 restore, quantized_template the configured model's
    "quantized" collection (abstract or concrete — only its STRUCTURE
    is read).  Each table found at a template plane path is quantized
    deterministically and its param slot becomes the zero carrier.
    Returns (carrier params, concrete quantized collection).  The
    carrier keeps the table's name/shape, so adam m/v restored against
    the fp32 table carry over unchanged."""

    def walk(qt, pt, path):
        if is_quantized_planes(qt):
            q8, scale = quantize_rows(pt)
            return (
                {"q8": q8, "scale": scale},
                jnp.zeros(pt.shape, jnp.float32),
            )
        new_q, new_p = {}, dict(pt)
        for k in qt:
            new_q[k], new_p[k] = walk(qt[k], pt[k], path + (k,))
        return new_q, new_p

    quant, new_params = walk(quantized_template, params, ())
    return new_params, quant


def dequantize_arena_tree(params, quantized):
    """int8 -> fp32 export (serving on an fp32 config, un-quantized
    fine-tuning): rebuild each table as dequant(q8, scale) + carrier
    (the carrier is zero between steps, but folding it keeps the
    conversion exact even mid-step) and drop the planes.  Returns the
    fp32 inner "params" dict."""

    def walk(qt, pt):
        if is_quantized_planes(qt):
            return dequantize_rows(qt["q8"], qt["scale"]) + jnp.asarray(
                pt, jnp.float32
            )
        new_p = dict(pt)
        for k in qt:
            new_p[k] = walk(qt[k], pt[k])
        return new_p

    return walk(quantized, params)
