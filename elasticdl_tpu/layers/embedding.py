"""Distributed embedding layer: mesh-sharded tables.

Parity: reference python/elasticdl/layers/embedding.py (SURVEY.md C13) and
the PS-side embedding tables + id-hash routing (C10/C11/C16).  The
reference's `elasticdl.Embedding` stores its table in parameter servers,
pulls per-minibatch vectors over gRPC and pushes IndexedSlices gradients.

TPU-native design (SURVEY.md §7): the table is ONE array sharded over the
mesh's `model` axis (PartitionSpec("model", None) — row sharding, the same
layout as the reference's id-hash partition across PS shards).  Lookup is a
plain gather inside the jitted step: the XLA SPMD partitioner turns a
gather on a row-sharded operand into the broadcast-ids/local-mask-psum
routing the PS client did by hand, and the backward scatter-add becomes the
sparse gradient push.  No RPCs, no parameter server processes.

Dynamic-vocabulary semantics (the reference's lazy-init unbounded tables)
are emulated by a fixed capacity plus id hashing: any int id maps to a row
via a multiplicative mixer mod capacity.  Collisions are the documented
trade-off (SURVEY.md hard part 2) — capacity is user-set per feature.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

# Knuth's multiplicative hash constant (2^32 / phi); enough mixing to
# de-cluster sequential ids before the mod.
_MIX = 2654435761

_PIB = lax.GatherScatterMode.PROMISE_IN_BOUNDS


@jax.custom_vjp
def _lookup(table, flat_ids):
    """Gather rows; backward is XLA's plain scatter-add.

    The custom part that remains is the FORWARD: ids are hashed mod
    capacity by construction, so the gather's bounds branch is provably
    dead — PROMISE_IN_BOUNDS makes that explicit.

    History (round-4 re-measurement, docs/embedding_design_note.md):
    rounds 2-3 shipped a duplicate-collapsing backward here (sort +
    log2(N)-pass segmented suffix scan + head-only scatter) on probes
    suggesting the scatter's cost scaled with duplicate destinations.
    Carried-table probes — the only scatter timing that survives XLA's
    partial-consumption elision — show otherwise on this stack: a raw
    1.7M x 16 scatter-add costs ~123 ms whether ids are unique, zipf, or
    mostly dropped, so the collapse machinery's ~26 ms of sort/scan was
    pure overhead (149 ms vs 129 ms for the plain VJP, full fwd+bwd).
    Keep the simple thing; the scatter itself (~14M random rows/s) is
    the ceiling SparseCore would lift.
    """
    return table.at[flat_ids].get(mode=_PIB)


def _lookup_fwd(table, flat_ids):
    # the table itself is the residual (a reference, not a copy): only
    # its shape/dtype are read in the backward
    return _lookup(table, flat_ids), (table, flat_ids)


def _lookup_bwd(residuals, g):
    table, flat_ids = residuals
    dtable = (
        jnp.zeros(table.shape, g.dtype).at[flat_ids].add(g, mode=_PIB)
    )
    return dtable.astype(table.dtype), None


_lookup.defvjp(_lookup_fwd, _lookup_bwd)


def hash_ids(ids: jnp.ndarray, capacity: int, mix: bool = True) -> jnp.ndarray:
    ids = ids.astype(jnp.uint32)
    if mix:
        ids = ids * jnp.uint32(_MIX)
    return (ids % jnp.uint32(capacity)).astype(jnp.int32)


def hash_ids_host(ids, capacity: int, mix: bool = True):
    """Bit-exact numpy replica of `hash_ids` for HOST-side packers (the
    dedup'd wire format hashes in the prefetch thread so the device can
    skip the hash and consume table rows directly).  uint32 wraparound
    arithmetic matches the device path including negative-id
    reinterpretation."""
    import numpy as np

    ids = np.asarray(ids).astype(np.uint32)
    if mix:
        with np.errstate(over="ignore"):
            ids = ids * np.uint32(_MIX)
    return (ids % np.uint32(capacity)).astype(np.int32)


class DistributedEmbedding(nn.Module):
    """Drop-in equivalent of the reference's `elasticdl.Embedding`.

    input_dim:  table capacity (vocab size after hashing).
    output_dim: embedding dimension.
    combiner:   None -> per-id vectors (input (..., ) int ids ->
                (..., output_dim)); "sum" | "mean" | "sqrtn" -> bag
                reduction over the last input axis with `pad_id` masking
                (the reference's combiner semantics for multivalent
                features).
    hash_input: apply the multiplicative mixer (set False when ids are
                already uniform, e.g. pre-hashed Criteo features).
    """

    input_dim: int
    output_dim: int
    combiner: Optional[str] = None
    pad_id: int = -1
    hash_input: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, ids, prehashed: bool = False):
        table = self.param(
            "embedding",
            nn.initializers.normal(stddev=0.05),
            (self.input_dim, self.output_dim),
            self.param_dtype,
        )
        ids = jnp.asarray(ids)
        if prehashed:
            # ids are already table rows in [0, input_dim) — computed on
            # the HOST by the dedup'd wire format (hash_ids_host) so the
            # device skips the hash/mod.  Pad masking does not apply:
            # the packer asserts the stream carries no pad ids.
            vecs = _lookup(table, ids.reshape(-1)).reshape(
                ids.shape + (self.output_dim,)
            )
            if self.combiner is None:
                return vecs
            valid = jnp.ones(ids.shape, bool)
            return self._combine(vecs, valid)
        valid = ids != self.pad_id
        rows = hash_ids(jnp.where(valid, ids, 0), self.input_dim,
                        mix=self.hash_input)
        vecs = _lookup(table, rows.reshape(-1)).reshape(
            rows.shape + (self.output_dim,)
        )
        vecs = jnp.where(valid[..., None], vecs, 0.0)
        if self.combiner is None:
            return vecs
        return self._combine(vecs, valid)

    def _combine(self, vecs, valid):
        count = jnp.maximum(
            jnp.sum(valid, axis=-1, keepdims=True).astype(vecs.dtype), 1.0
        )
        total = jnp.sum(vecs, axis=-2)
        if self.combiner == "sum":
            return total
        if self.combiner == "mean":
            return total / count
        if self.combiner == "sqrtn":
            return total / jnp.sqrt(count)
        raise ValueError(f"unknown combiner {self.combiner!r}")


def embedding_param_sharding(path, value) -> Optional[P]:
    """`param_sharding` helper for zoo modules: shard every
    DistributedEmbedding table over the `model` axis, replicate the rest.

    Usage in a model-zoo module:
        from elasticdl_tpu.layers.embedding import embedding_param_sharding
        param_sharding = embedding_param_sharding
    """
    names = [getattr(k, "key", str(k)) for k in path]
    if "embedding" in names and getattr(value, "ndim", 0) >= 2:
        return P("model", None)
    return None
