"""Distributed embedding layer: mesh-sharded tables.

Parity: reference python/elasticdl/layers/embedding.py (SURVEY.md C13) and
the PS-side embedding tables + id-hash routing (C10/C11/C16).  The
reference's `elasticdl.Embedding` stores its table in parameter servers,
pulls per-minibatch vectors over gRPC and pushes IndexedSlices gradients.

TPU-native design (SURVEY.md §7): the table is ONE array sharded over the
mesh's `model` axis (PartitionSpec("model", None) — row sharding, the same
layout as the reference's id-hash partition across PS shards).  Lookup is a
plain gather inside the jitted step: the XLA SPMD partitioner turns a
gather on a row-sharded operand into the broadcast-ids/local-mask-psum
routing the PS client did by hand, and the backward scatter-add becomes the
sparse gradient push.  No RPCs, no parameter server processes.

Dynamic-vocabulary semantics (the reference's lazy-init unbounded tables)
are emulated by a fixed capacity plus id hashing: any int id maps to a row
via a multiplicative mixer mod capacity.  Collisions are the documented
trade-off (SURVEY.md hard part 2) — capacity is user-set per feature.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

# Knuth's multiplicative hash constant (2^32 / phi); enough mixing to
# de-cluster sequential ids before the mod.
_MIX = 2654435761

_PIB = lax.GatherScatterMode.PROMISE_IN_BOUNDS


@jax.custom_vjp
def _lookup(table, flat_ids):
    """Gather rows with a duplicate-collapsing backward.

    Measured on TPU v5e (1M x 16 table, 852K zipf ids/step — the DeepFM
    north-star shape): the naive path spends ~80ms/step in the embedding
    ops (23ms gather + 58ms scatter-add with duplicate indices, which the
    TPU serializes per-op); this path runs the same math in ~18ms:

    - forward: gather with PROMISE_IN_BOUNDS (ids are hashed mod capacity
      by construction, so the bounds branch is provably dead) — 23 -> 8ms;
    - backward: sort ids, permute grads, collapse duplicate-id runs with a
      log2(N)-pass segmented suffix scan (2.7ms), then scatter-add ONLY
      each run's head row — non-heads are sent out of bounds and dropped,
      so scatter traffic is proportional to UNIQUE ids (zipf CTR traffic:
      ~13K of 852K) — 58 -> ~9ms.

    CTR id skew is exactly what makes the naive scatter pathological and
    this one fast; uniform ids degrade gracefully (scan passes are cheap,
    scatter approaches the naive cost).
    """
    return table.at[flat_ids].get(mode=_PIB)


def _lookup_fwd(table, flat_ids):
    # the table itself is the residual (a reference, not a copy): only
    # its shape/dtype are read in the backward
    return _lookup(table, flat_ids), (table, flat_ids)


def _lookup_bwd(residuals, g):
    table, flat_ids = residuals
    shape, dtype = table.shape, table.dtype
    n = flat_ids.shape[0]
    sid, perm = lax.sort_key_val(
        flat_ids, jnp.arange(n, dtype=jnp.int32)
    )
    gs = g.at[perm].get(mode=_PIB)            # grads ordered by id
    # segmented suffix scan (Hillis-Steele): after pass k, gs[i] covers
    # rows [i, i + 2^(k+1)) of its run; log2(n) passes leave each run's
    # HEAD holding the run's full sum
    span = 1
    while span < n:
        same = jnp.concatenate(
            [sid[:-span] == sid[span:], jnp.zeros((span,), bool)]
        )
        shifted = jnp.concatenate(
            [gs[span:], jnp.zeros((span,) + gs.shape[1:], gs.dtype)]
        )
        gs = gs + jnp.where(same[:, None], shifted, 0.0)
        span <<= 1
    head = jnp.concatenate(
        [jnp.ones((1,), bool), sid[1:] != sid[:-1]]
    )
    # non-heads point out of bounds and are DROPPED: writes ~ unique ids
    sentinel = jnp.where(head, sid, jnp.int32(shape[0]))
    dtable = jnp.zeros(shape, g.dtype).at[sentinel].add(gs, mode="drop")
    return dtable.astype(dtype), None


_lookup.defvjp(_lookup_fwd, _lookup_bwd)


def hash_ids(ids: jnp.ndarray, capacity: int, mix: bool = True) -> jnp.ndarray:
    ids = ids.astype(jnp.uint32)
    if mix:
        ids = ids * jnp.uint32(_MIX)
    return (ids % jnp.uint32(capacity)).astype(jnp.int32)


class DistributedEmbedding(nn.Module):
    """Drop-in equivalent of the reference's `elasticdl.Embedding`.

    input_dim:  table capacity (vocab size after hashing).
    output_dim: embedding dimension.
    combiner:   None -> per-id vectors (input (..., ) int ids ->
                (..., output_dim)); "sum" | "mean" | "sqrtn" -> bag
                reduction over the last input axis with `pad_id` masking
                (the reference's combiner semantics for multivalent
                features).
    hash_input: apply the multiplicative mixer (set False when ids are
                already uniform, e.g. pre-hashed Criteo features).
    """

    input_dim: int
    output_dim: int
    combiner: Optional[str] = None
    pad_id: int = -1
    hash_input: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, ids):
        table = self.param(
            "embedding",
            nn.initializers.normal(stddev=0.05),
            (self.input_dim, self.output_dim),
            self.param_dtype,
        )
        ids = jnp.asarray(ids)
        valid = ids != self.pad_id
        rows = hash_ids(jnp.where(valid, ids, 0), self.input_dim,
                        mix=self.hash_input)
        vecs = _lookup(table, rows.reshape(-1)).reshape(
            rows.shape + (self.output_dim,)
        )
        vecs = jnp.where(valid[..., None], vecs, 0.0)
        if self.combiner is None:
            return vecs
        count = jnp.maximum(
            jnp.sum(valid, axis=-1, keepdims=True).astype(vecs.dtype), 1.0
        )
        total = jnp.sum(vecs, axis=-2)
        if self.combiner == "sum":
            return total
        if self.combiner == "mean":
            return total / count
        if self.combiner == "sqrtn":
            return total / jnp.sqrt(count)
        raise ValueError(f"unknown combiner {self.combiner!r}")


def embedding_param_sharding(path, value) -> Optional[P]:
    """`param_sharding` helper for zoo modules: shard every
    DistributedEmbedding table over the `model` axis, replicate the rest.

    Usage in a model-zoo module:
        from elasticdl_tpu.layers.embedding import embedding_param_sharding
        param_sharding = embedding_param_sharding
    """
    names = [getattr(k, "key", str(k)) for k in path]
    if "embedding" in names and getattr(value, "ndim", 0) >= 2:
        return P("model", None)
    return None
