from elasticdl_tpu.layers.embedding import (  # noqa: F401
    DistributedEmbedding,
    embedding_param_sharding,
)
