"""Flax wrapper for pipeline parallelism: a stacked block repeated
`num_layers` times and applied as a GPipe microbatch pipeline over the
mesh `pipe` axis (ops/pipeline.py).

The whole stack is ONE param subtree with a leading layer axis
(`stack/<block params>`, leaves shaped (num_layers, ...)), so:

- `pipeline_param_sharding` shards every leaf P('pipe') on that axis —
  stage s holds its contiguous slice of layers, the optimizer state
  mirrors it (Trainer.state_sharding matches param structure);
- the param tree is IDENTICAL whatever the mesh: on a pipe=1 mesh the
  apply degenerates to a sequential scan, so checkpoints move freely
  between pipelined and non-pipelined meshes (cross-mesh restore,
  tests/test_remesh.py) — elasticity does not care about the schedule.
"""

from __future__ import annotations

import logging
from typing import Any, Mapping, Type

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.ops.pipeline import gpipe_spmd
from elasticdl_tpu.parallel.mesh import (
    PIPE_AXIS,
    get_current_mesh,
    in_export_mode,
)


class GPipeBlocks(nn.Module):
    """num_layers x block_cls(**block_kwargs), pipelined over `pipe`.

    The block must be shape-preserving ((B', ...) -> (B', ...)) and must
    not open its own shard_map (it executes inside the pipeline's) — use
    mesh-free blocks (plain attention/MLP), not ring-attention blocks.
    """

    block_cls: Type[nn.Module]
    block_kwargs: Mapping[str, Any]
    num_layers: int
    num_microbatches: int = 8
    remat: bool = False

    @nn.compact
    def __call__(self, x):
        block = self.block_cls(**dict(self.block_kwargs))
        mesh = get_current_mesh()
        stages = mesh.shape.get(PIPE_AXIS, 1)

        # Param shapes are batch-size independent, so init always traces
        # the block at batch 1 — this also keeps param() usable when the
        # batch dimension is SYMBOLIC (serving export traces a
        # polymorphic batch; flax eval_shapes the init_fn to validate
        # stored params even on bound modules).
        def init_stack(rng):
            def one(r):
                return block.init(
                    r, jnp.zeros((1,) + x.shape[1:], x.dtype)
                )["params"]

            return jax.vmap(one)(jax.random.split(rng, self.num_layers))

        stack = self.param("gpipe_stack", init_stack)

        def apply_one(p, h):
            return block.apply({"params": p}, h)

        if in_export_mode():
            # Serving export: microbatch arithmetic (min/mod on the
            # batch size) is inconclusive on symbolic dims, and
            # gpipe_spmd runs the sequential formulation anyway.
            return gpipe_spmd(
                apply_one, stack, x, mesh,
                num_microbatches=1, remat=self.remat,
            )
        # microbatches divide the PER-DATA-SHARD batch inside shard_map
        local = max(x.shape[0] // max(mesh.shape.get("data", 1), 1), 1)
        mcount = min(self.num_microbatches, local) if stages > 1 else 1
        while local % mcount:
            mcount -= 1
        if stages > 1 and mcount != self.num_microbatches:
            # clamped to a divisor of the local batch; at mcount=1 the
            # schedule degenerates to one stage active at a time
            # (bubble = (P-1)/P) — surface it rather than hide it
            logging.getLogger(__name__).warning(
                "GPipeBlocks: num_microbatches=%d does not divide the "
                "per-data-shard batch %d; running with %d microbatches "
                "(pipeline bubble %.0f%%)",
                self.num_microbatches, local, mcount,
                100.0 * (stages - 1) / (mcount + stages - 1),
            )

        return gpipe_spmd(
            apply_one, stack, x, mesh,
            num_microbatches=mcount, remat=self.remat,
        )


def pipeline_param_sharding(path, value):
    """PartitionSpec for GPipeBlocks params: leaves under a `gpipe_stack`
    param subtree are layer-sharded over `pipe` on their leading axis.
    Compose into a zoo `param_sharding` before other rules.  The name is
    deliberately distinctive (ADVICE r3): matching a generic `stack`
    would mis-shard any unrelated user param of that name."""
    names = [getattr(k, "key", str(k)) for k in path]
    if "gpipe_stack" in names:
        return P(PIPE_AXIS)
    return None
