"""Mixture-of-Experts layer with expert parallelism over the mesh
`expert` axis.

Net-new capability relative to the reference (SURVEY.md §2: upstream has
NO expert parallelism), completing the framework's fourth mesh axis.
Design follows the GShard/Switch dense-dispatch recipe, expressed the
pjit way (SURVEY.md §7: annotate shardings, let XLA insert collectives):

- the router computes top-1 gates per token; dispatch/combine are DENSE
  one-hot tensors (tokens, experts, capacity) built with static shapes —
  no sorting, no dynamic shapes, nothing the TPU can't tile;
- expert weights are stacked as (experts, ...) arrays whose leading dim
  is sharded `P("expert", ...)` (see `moe_param_sharding`); the dispatch
  einsum then contracts a token-sharded operand against an
  expert-sharded one, and the XLA SPMD partitioner emits the all-to-all
  over ICI that hand-written MoE frameworks schedule manually;
- fixed expert capacity (capacity_factor * tokens / experts) bounds
  memory; overflowing tokens fall through the residual connection
  (standard Switch semantics — the layer returns gate-weighted expert
  output, zeros for dropped tokens, so callers add the residual).

Capacity assignment uses the standard position-in-expert cumsum, which
is deterministic and position-biased (earlier tokens win slots), exactly
like the reference implementations.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


class MoEMLP(nn.Module):
    """Top-1 (Switch) MoE feed-forward block: (..., hidden) -> (..., hidden).

    num_experts:     total experts (shard over the mesh `expert` axis)
    ffn_dim:         per-expert intermediate width
    capacity_factor: slots per expert = ceil(tokens/experts * factor)
    aux_loss_coef:   weight of the sown Switch load-balancing loss; the
                     Trainer adds every sown `moe_aux_loss` to the
                     training objective, so routing cannot collapse onto
                     one expert
    """

    num_experts: int
    ffn_dim: int
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        *batch_dims, hidden = x.shape
        tokens = x.reshape(-1, hidden)                      # (N, H)
        n_tokens = tokens.shape[0]
        capacity = max(
            1,
            int(-(-n_tokens * self.capacity_factor // self.num_experts)),
        )

        logits = nn.Dense(self.num_experts, name="router")(
            tokens.astype(jnp.float32)
        )                                                   # (N, E)
        probs = jax.nn.softmax(logits, axis=-1)
        expert_idx = jnp.argmax(probs, axis=-1)             # (N,)
        gate = jnp.take_along_axis(
            probs, expert_idx[:, None], axis=-1
        )[:, 0]                                             # (N,)

        # position of each token within its expert's queue (static shapes)
        onehot = jax.nn.one_hot(
            expert_idx, self.num_experts, dtype=jnp.int32
        )                                                   # (N, E)
        position = jnp.cumsum(onehot, axis=0) * onehot - 1  # (N, E)
        kept = (position >= 0) & (position < capacity)
        # dispatch: (N, E, C) one-hot; combine adds the gate weight
        pos_clipped = jnp.clip(position, 0, capacity - 1)
        dispatch = (
            jax.nn.one_hot(pos_clipped, capacity, dtype=tokens.dtype)
            * kept.astype(tokens.dtype)[..., None]
        )                                                   # (N, E, C)
        combine = dispatch * gate[:, None, None].astype(tokens.dtype)

        # route tokens to experts: XLA shards `e` (expert axis) and emits
        # the all-to-all from the shardings
        expert_in = jnp.einsum(
            "nec,nh->ech", dispatch, tokens.astype(self.compute_dtype)
        )                                                   # (E, C, H)

        w_in = self.param(
            "expert_w_in",
            nn.initializers.lecun_normal(),
            (self.num_experts, hidden, self.ffn_dim),
        )
        b_in = self.param(
            "expert_b_in", nn.initializers.zeros,
            (self.num_experts, self.ffn_dim),
        )
        w_out = self.param(
            "expert_w_out",
            nn.initializers.lecun_normal(),
            (self.num_experts, self.ffn_dim, hidden),
        )
        b_out = self.param(
            "expert_b_out", nn.initializers.zeros,
            (self.num_experts, hidden),
        )
        h = jnp.einsum(
            "ech,ehf->ecf", expert_in, w_in.astype(self.compute_dtype)
        ) + b_in[:, None, :].astype(self.compute_dtype)
        h = nn.relu(h)
        expert_out = jnp.einsum(
            "ecf,efh->ech", h, w_out.astype(self.compute_dtype)
        ) + b_out[:, None, :].astype(self.compute_dtype)    # (E, C, H)

        out = jnp.einsum(
            "nec,ech->nh", combine, expert_out.astype(jnp.float32)
        )
        # auxiliary load-balancing loss (Switch eq.4), pre-scaled by its
        # coefficient; the Trainer sums every sown `moe_aux_loss` into the
        # training objective (worker/trainer.py)
        density = onehot.astype(jnp.float32).mean(axis=0)
        density_proxy = probs.mean(axis=0)
        self.sow(
            "intermediates", "moe_aux_loss",
            self.aux_loss_coef
            * self.num_experts
            * jnp.sum(density * density_proxy),
        )
        return out.astype(x.dtype).reshape(*batch_dims, hidden)


def moe_param_sharding(path, value) -> Optional[P]:
    """`param_sharding` helper: stack-of-experts params shard their
    leading (expert) dim over the mesh `expert` axis; compose with other
    helpers for models that also have sharded embeddings."""
    names = [getattr(k, "key", str(k)) for k in path]
    if any(str(n).startswith("expert_") for n in names):
        ndim = getattr(value, "ndim", 0)
        if ndim >= 2:
            return P("expert", *([None] * (ndim - 1)))
        if ndim == 1:
            return P("expert")
    return None
