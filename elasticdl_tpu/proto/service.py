"""Hand-written gRPC service glue for the Master control-plane service.

grpcio-tools is unavailable in this environment, so instead of generated
`_pb2_grpc.py` service classes we register the service with grpc's generic
handler API and build client stubs from `channel.unary_unary`.  The wire
format (method paths, protobuf request/response types) is identical to what
`protoc --grpc_python_out` would have produced for the `Master` service
declared in elasticdl.proto.

Parity: reference `elasticdl/proto/elasticdl.proto` service `Master`
(SURVEY.md C1/C2).  The `Pserver` service is intentionally absent — tensor
traffic lives on the device mesh in the TPU-native design.
"""

from __future__ import annotations

from elasticdl_tpu.proto import elasticdl_pb2 as pb

SERVICE_NAME = "elasticdl_tpu.Master"

# method name -> (request class, response class)
MASTER_METHODS = {
    "get_task": (pb.GetTaskRequest, pb.GetTaskResponse),
    "get_spmd_task": (pb.GetSpmdTaskRequest, pb.SpmdTaskResponse),
    "report_task_result": (pb.ReportTaskResultRequest, pb.Empty),
    "report_evaluation_metrics": (pb.ReportEvaluationMetricsRequest, pb.Empty),
    "get_cluster_spec": (pb.GetClusterSpecRequest, pb.ClusterSpec),
    "keep_alive": (pb.KeepAliveRequest, pb.Empty),
    "report_version": (pb.ReportVersionRequest, pb.Empty),
}


def add_master_servicer_to_server(servicer, server) -> None:
    """Register `servicer` (an object with MASTER_METHODS-named methods
    accepting (request, context)) on a `grpc.Server`."""
    import grpc

    handlers = {}
    for name, (req_cls, resp_cls) in MASTER_METHODS.items():
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req_cls.FromString,
            response_serializer=lambda msg, _cls=resp_cls: msg.SerializeToString(),
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )


class MasterStub:
    """Client stub over a grpc channel; method-for-method mirror of the
    servicer so `InProcessMasterClient` (direct servicer calls, used by the
    tests and local mode) and this stub are interchangeable."""

    def __init__(self, channel):
        for name, (req_cls, resp_cls) in MASTER_METHODS.items():
            callable_ = channel.unary_unary(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString,
            )
            setattr(self, name, _StripContext(callable_))


class _StripContext:
    """Adapts stub(request) to the servicer-side (request, context) shape so
    both transports expose `fn(request)`."""

    def __init__(self, callable_):
        self._callable = callable_

    def __call__(self, request, timeout=None):
        return self._callable(request, timeout=timeout)


class InProcessMasterClient:
    """Calls a MasterServicer directly, no sockets.  Used by tests and by
    `--distribution_strategy=Local` where master and worker share a process
    (the reference exercises its protocol the same way in
    worker_ps_interaction_test.py — SURVEY.md §4.2)."""

    def __init__(self, servicer):
        for name in MASTER_METHODS:
            method = getattr(servicer, name)
            setattr(
                self,
                name,
                lambda request, timeout=None, _m=method: _m(request, None),
            )
