"""Hand-written gRPC service glue for the Master control-plane service.

grpcio-tools is unavailable in this environment, so instead of generated
`_pb2_grpc.py` service classes we register the service with grpc's generic
handler API and build client stubs from `channel.unary_unary`.  The wire
format (method paths, protobuf request/response types) is identical to what
`protoc --grpc_python_out` would have produced for the `Master` service
declared in elasticdl.proto.

Parity: reference `elasticdl/proto/elasticdl.proto` service `Master`
(SURVEY.md C1/C2).  The `Pserver` service is intentionally absent — tensor
traffic lives on the device mesh in the TPU-native design.
"""

from __future__ import annotations

import threading
import time

from elasticdl_tpu.common import events
from elasticdl_tpu.common import faults
from elasticdl_tpu.common import metrics as _metrics
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.proto import serving_pb2 as spb

# Server-side RPC counters, shared by both transports: the gRPC handler
# wrapper below and the in-process direct-call path count through the
# same series, so tests and real-socket runs read identically.
_requests_counter = _metrics.default_registry().counter(
    "rpc_server_requests_total",
    "RPC handler invocations, by service and method",
    labelnames=("service", "method"),
)
_errors_counter = _metrics.default_registry().counter(
    "rpc_server_errors_total",
    "RPC handler invocations that raised, by service and method",
    labelnames=("service", "method"),
)


def _observed(handler, service: str, method: str):
    """Wrap a (request, context) handler with the request/error series."""

    def _wrapped(request, context):
        _requests_counter.labels(service=service, method=method).inc()
        try:
            return handler(request, context)
        except Exception:
            _errors_counter.labels(service=service, method=method).inc()
            raise

    return _wrapped

SERVICE_NAME = "elasticdl_tpu.Master"
SERVING_SERVICE_NAME = "elasticdl_tpu.Serving"

# method name -> (request class, response class)
MASTER_METHODS = {
    "get_task": (pb.GetTaskRequest, pb.GetTaskResponse),
    "get_spmd_task": (pb.GetSpmdTaskRequest, pb.SpmdTaskResponse),
    "report_task_result": (pb.ReportTaskResultRequest, pb.Empty),
    "report_evaluation_metrics": (pb.ReportEvaluationMetricsRequest, pb.Empty),
    "get_cluster_spec": (pb.GetClusterSpecRequest, pb.ClusterSpec),
    "keep_alive": (pb.KeepAliveRequest, pb.Empty),
    "report_version": (pb.ReportVersionRequest, pb.Empty),
}

# method name -> fault-injection point (common/faults.py).  Both client
# transports fire the method's point per attempt, so a chaos schedule
# exercises in-process tests and real-socket runs identically.
METHOD_FAULT_POINTS = {
    "get_task": faults.POINT_RPC_GET_TASK,
    "get_spmd_task": faults.POINT_RPC_GET_TASK,
    "report_task_result": faults.POINT_RPC_REPORT,
    "report_evaluation_metrics": faults.POINT_RPC_REPORT,
    "report_version": faults.POINT_RPC_REPORT,
    "get_cluster_spec": faults.POINT_RENDEZVOUS_JOIN,
    "keep_alive": faults.POINT_WORKER_HEARTBEAT,
}


# The online-serving data plane (serving.proto; docs/SERVING.md).
# `health` fires its own point, distinct from the data path: the fleet
# manager's probe loop is itself a chaos surface (a probe that errors must
# count toward the relaunch threshold deterministically), and a separate
# point means a schedule can flap the prober without touching predict
# traffic — or vice versa.
SERVING_METHODS = {
    "predict": (spb.PredictRequest, spb.PredictResponse),
    "health": (spb.HealthRequest, spb.HealthResponse),
}

SERVING_METHOD_FAULT_POINTS = {
    "predict": faults.POINT_RPC_PREDICT,
    "health": faults.POINT_RPC_HEALTH_PROBE,
}


def method_fault_point_paths() -> dict:
    """Full-path variant ('/elasticdl_tpu.Master/get_task' -> point) for
    the gRPC client interceptor, which only sees method paths."""
    return {
        f"/{SERVICE_NAME}/{name}": point
        for name, point in METHOD_FAULT_POINTS.items()
    }


def serving_fault_point_paths() -> dict:
    return {
        f"/{SERVING_SERVICE_NAME}/{name}": point
        for name, point in SERVING_METHOD_FAULT_POINTS.items()
    }


def _add_servicer_to_server(servicer, server, service_name, methods) -> None:
    import grpc

    handlers = {}
    for name, (req_cls, resp_cls) in methods.items():
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            _observed(getattr(servicer, name), service_name, name),
            request_deserializer=req_cls.FromString,
            response_serializer=lambda msg, _cls=resp_cls: msg.SerializeToString(),
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(service_name, handlers),)
    )


def add_master_servicer_to_server(servicer, server) -> None:
    """Register `servicer` (an object with MASTER_METHODS-named methods
    accepting (request, context)) on a `grpc.Server`."""
    _add_servicer_to_server(servicer, server, SERVICE_NAME, MASTER_METHODS)


def add_serving_servicer_to_server(servicer, server) -> None:
    """Register a serving servicer (predict/health methods accepting
    (request, context)) on a `grpc.Server`."""
    _add_servicer_to_server(
        servicer, server, SERVING_SERVICE_NAME, SERVING_METHODS
    )


class _StubBase:
    """Builds stub methods from a channel; subclasses pin the service name,
    method table, and fault-point map.

    With `retry_policy`, every method goes through the resilience
    interceptor: per-attempt deadline, exponential backoff + full jitter,
    max-elapsed budget, and per-attempt fault injection."""

    _service_name: str
    _methods: dict
    _fault_point_paths: staticmethod

    def __init__(self, channel, retry_policy=None):
        if retry_policy is not None:
            import grpc

            from elasticdl_tpu.common.resilience import (
                RetryingClientInterceptor,
            )

            channel = grpc.intercept_channel(
                channel,
                RetryingClientInterceptor(
                    retry_policy, fault_points=type(self)._fault_point_paths()
                ),
            )
        for name, (req_cls, resp_cls) in self._methods.items():
            callable_ = channel.unary_unary(
                f"/{self._service_name}/{name}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString,
            )
            setattr(self, name, _StripContext(callable_))


class MasterStub(_StubBase):
    """Client stub over a grpc channel; method-for-method mirror of the
    servicer so `InProcessMasterClient` (direct servicer calls, used by the
    tests and local mode) and this stub are interchangeable."""

    _service_name = SERVICE_NAME
    _methods = MASTER_METHODS
    _fault_point_paths = staticmethod(method_fault_point_paths)


class ServingStub(_StubBase):
    """Client stub for the Serving data plane; interchangeable with
    `InProcessServingClient` the same way MasterStub mirrors its
    in-process twin."""

    _service_name = SERVING_SERVICE_NAME
    _methods = SERVING_METHODS
    _fault_point_paths = staticmethod(serving_fault_point_paths)


class _StripContext:
    """Adapts stub(request) to the servicer-side (request, context) shape so
    both transports expose `fn(request)`."""

    def __init__(self, callable_):
        self._callable = callable_

    def __call__(self, request, timeout=None):
        return self._callable(request, timeout=timeout)


class _InProcessClientBase:
    """Calls a servicer directly, no sockets; subclasses pin the method
    table and fault-point map."""

    _methods: dict
    _fault_points: dict

    _service_name: str = ""

    def __init__(self, servicer, retry_policy=None):
        for name in self._methods:
            method = _observed(
                getattr(servicer, name), self._service_name, name
            )
            point = self._fault_points.get(name)
            call = self._make_call(method, point, retry_policy, name)
            setattr(self, name, call)

    @staticmethod
    def _make_call(method, point, retry_policy, name):
        def _attempt(request):
            if point is not None:
                faults.fire(point)
            return method(request, None)

        if retry_policy is None:
            return lambda request, timeout=None: _attempt(request)
        return lambda request, timeout=None: retry_policy.call(
            lambda: _attempt(request), description=name
        )


class InProcessMasterClient(_InProcessClientBase):
    """Calls a MasterServicer directly, no sockets.  Used by tests and by
    `--distribution_strategy=Local` where master and worker share a process
    (the reference exercises its protocol the same way in
    worker_ps_interaction_test.py — SURVEY.md §4.2)."""

    _service_name = SERVICE_NAME
    _methods = MASTER_METHODS
    _fault_points = METHOD_FAULT_POINTS


class InProcessServingClient(_InProcessClientBase):
    """Direct-call twin of ServingStub for tests and in-process benches."""

    _service_name = SERVING_SERVICE_NAME
    _methods = SERVING_METHODS
    _fault_points = SERVING_METHOD_FAULT_POINTS


# Router-side fan-out counters: how often a request left its first-choice
# replica, and why.  Shared across router instances on purpose — the
# cluster-wide view is the one `elasticdl top` and the bench read.
_fleet_requests_counter = _metrics.default_registry().counter(
    "rpc_fleet_requests_total",
    "Predict requests entering the fleet router",
)
_fleet_failovers_counter = _metrics.default_registry().counter(
    "rpc_fleet_failovers_total",
    "requests re-offered to another replica, by reason",
    labelnames=("reason",),
)
_fleet_request_errors_counter = _metrics.default_registry().counter(
    "rpc_fleet_request_errors_total",
    "Predict requests that failed after every replica and retry was "
    "exhausted — the bad events of the predict_availability SLO",
)
_fleet_sheds_counter = _metrics.default_registry().counter(
    "rpc_fleet_sheds_total",
    "requests the whole fleet shed (admission control answered for "
    "every replica) — with rpc_fleet_requests_total, the windowed shed "
    "ratio the serving policy engine and the backpressure signal read",
)
_fleet_route_histogram = _metrics.default_registry().histogram(
    "rpc_fleet_route_seconds",
    "router-side end-to-end Predict latency (the `route` phase of the "
    "request span: sweeps + backoff until a response or exhaustion)",
)

#: In-band codes the router treats as routing signals: the replica is up
#: but refusing load, so re-offer elsewhere — never re-offer through the
#: retry interceptor (that would re-load a shedding server).
SHED_CODES = (spb.SERVING_OVERLOADED, spb.SERVING_SHUTTING_DOWN)


class FleetRouter:
    """Client-side Predict fan-out across serving replicas
    (docs/SERVING.md "Fleet").

    Holds one client per replica id — `ServingStub` or
    `InProcessServingClient`, the transports are interchangeable — and
    routes every request through the unified resilience policy
    (common/resilience.py): `predict()` wraps a single sweep of the
    fleet in `retry_policy.call`, so the public entry point is the
    interceptor (scripts/check_no_naked_retries.py enforces this shape).

    Failure semantics, per sweep:

    - A transport error (killed replica, injected fault) demotes the
      replica and moves on to the next candidate.  Only when EVERY
      replica errors does the sweep raise — the policy then backs off
      and re-sweeps, so a replica kill costs retries, not client errors.
    - In-band OVERLOADED / SHUTTING_DOWN responses are routing signals,
      not errors: the shedding replica is demoted and the request is
      offered to at most one other replica per candidate; when the whole
      fleet sheds, the shed response is returned as-is (rerouting must
      not turn admission control into a retry storm).
    - Ranking is deterministic (no RNG): demotion bucket first, then the
      batcher fill-ratio bucket fed by `observe_health()` (the fleet
      manager's probe loop scrapes it from each replica's Health RPC),
      with round-robin rotation breaking ties — so equal replicas share
      load and a loaded replica drains before it sheds.
    """

    def __init__(self, clients=None, retry_policy=None, freshness=None,
                 trace_sample_rate: float = 1.0, clock=time.monotonic):
        if retry_policy is None:
            from elasticdl_tpu.common.resilience import default_policy

            retry_policy = default_policy()
        self._retry_policy = retry_policy
        # master/freshness.py FreshnessTracker: when present, every
        # successful response's echoed model_step is scored against the
        # latest produced checkpoint (train-to-serve staleness)
        self._freshness = freshness
        self._lock = threading.Lock()
        self._clients = dict(clients or {})
        self._penalty = {rid: 0 for rid in self._clients}
        self._fill = {rid: 0.0 for rid in self._clients}
        self._down = set()
        self._steps = {}
        self._produced = {}
        self._rr = 0
        self._max_skew = 0
        self._failovers = {"error": 0, "overloaded": 0, "shutdown": 0}
        self._requests = 0
        self._sheds = 0
        self._last_staleness = (0, 0.0)
        # Trace context (docs/OBSERVABILITY.md "Request tracing"): ids
        # come off a monotonic per-router counter — deterministic under
        # the fault harness, unlike uuid/wall-clock — and sampling is the
        # deterministic every-k'th request for the same reason.  k=0
        # (rate<=0) disables sampling; errors/sheds/failovers are
        # captured regardless (the always-on forensic path).
        rate = max(0.0, min(1.0, float(trace_sample_rate)))
        self._trace_every = int(round(1.0 / rate)) if rate > 0 else 0
        self._seq = 0
        self._clock = clock

    # ---- fleet membership (driven by the ServingFleetManager) ---------

    def set_client(self, replica_id, client) -> None:
        """Install or replace the client for one replica (a relaunch
        hands the router a fresh transport and a clean slate)."""
        with self._lock:
            self._clients[replica_id] = client
            self._penalty[replica_id] = 0
            self._fill.setdefault(replica_id, 0.0)
            self._down.discard(replica_id)

    def remove_client(self, replica_id) -> None:
        with self._lock:
            self._clients.pop(replica_id, None)
            self._penalty.pop(replica_id, None)
            self._fill.pop(replica_id, None)
            self._steps.pop(replica_id, None)
            self._produced.pop(replica_id, None)
            self._down.discard(replica_id)

    def mark_down(self, replica_id) -> None:
        """Probe-driven: stop offering traffic until `set_client` or
        `mark_live` readmits the replica."""
        with self._lock:
            self._down.add(replica_id)

    def mark_live(self, replica_id) -> None:
        with self._lock:
            self._down.discard(replica_id)
            if replica_id in self._clients:
                # a probe racing remove_client must not resurrect a
                # penalty bucket for a retired replica
                self._penalty[replica_id] = 0

    def observe_health(self, replica_id, fill_ratio=0.0, queue_depth=0,
                       model_step=None, produced_unix_s=None) -> None:
        """Feed one probe result into the ranking (fill-ratio weighting)
        and the cross-replica skew/freshness bookkeeping.
        `produced_unix_s` is the producer stamp the replica's engine
        carries for its served checkpoint (end-to-end freshness)."""
        del queue_depth  # fill-ratio is the load signal; depth rides along
        with self._lock:
            if replica_id not in self._clients:
                return
            self._fill[replica_id] = float(fill_ratio)
            if model_step is not None:
                self._note_step_locked(replica_id, int(model_step))
            if produced_unix_s is not None:
                self._produced[replica_id] = float(produced_unix_s)

    def replica_ids(self):
        with self._lock:
            return sorted(self._clients)

    # ---- skew observation ---------------------------------------------

    def _note_step_locked(self, replica_id, step: int) -> None:
        self._steps[replica_id] = step
        live = [s for r, s in self._steps.items() if r in self._clients]
        if len(live) > 1:
            self._max_skew = max(self._max_skew, max(live) - min(live))

    def observed_step_skew(self) -> int:
        """Current max-min `model_step` across replicas, from the steps
        echoed in responses and probes."""
        with self._lock:
            live = [s for r, s in self._steps.items() if r in self._clients]
            return max(live) - min(live) if len(live) > 1 else 0

    @property
    def max_observed_step_skew(self) -> int:
        with self._lock:
            return self._max_skew

    def stats(self) -> dict:
        with self._lock:
            return {
                "replicas": len(self._clients),
                "down": sorted(self._down),
                "requests": self._requests,
                "sheds": self._sheds,
                "failovers": dict(self._failovers),
                "max_model_step_skew": self._max_skew,
                "last_staleness_steps": self._last_staleness[0],
                "last_staleness_seconds": self._last_staleness[1],
                "produced_unix_s": dict(self._produced),
            }

    # ---- routing ------------------------------------------------------

    def _ranked(self):
        """Candidate order for one sweep: demotion bucket, then fill
        bucket, round-robin rotation within equal buckets.  All-down
        fleets still return candidates — a stale down-mark must not turn
        into an outage when the replicas are actually back."""
        with self._lock:
            rids = [r for r in sorted(self._clients) if r not in self._down]
            if not rids:
                rids = sorted(self._clients)
            if not rids:
                return []
            offset = self._rr % len(rids)
            self._rr += 1
            rotated = rids[offset:] + rids[:offset]
            return sorted(
                rotated,
                key=lambda r: (
                    min(self._penalty.get(r, 0), 3),
                    round(self._fill.get(r, 0.0), 1),
                ),
            )

    def _sweep(self, request, timeout=None):
        """One pass over the ranked fleet; raises (retryably) only when
        every replica failed at the transport layer."""
        order = self._ranked()
        if not order:
            raise ConnectionError("fleet router has no serving replicas")
        shed_response = None
        last_error = None
        for rid in order:
            with self._lock:
                client = self._clients.get(rid)
            if client is None:
                continue
            try:
                response = client.predict(request, timeout=timeout)
            except Exception as exc:  # transport/injected: demote, move on
                last_error = exc
                with self._lock:
                    # a replica retired while its call was in flight
                    # must not get a resurrected penalty bucket
                    if rid in self._clients:
                        self._penalty[rid] = self._penalty.get(rid, 0) + 1
                    self._failovers["error"] += 1
                _fleet_failovers_counter.labels(reason="error").inc()
                continue
            if response.code in SHED_CODES:
                reason = (
                    "overloaded"
                    if response.code == spb.SERVING_OVERLOADED
                    else "shutdown"
                )
                with self._lock:
                    if rid in self._clients:
                        self._penalty[rid] = self._penalty.get(rid, 0) + 1
                    self._failovers[reason] += 1
                _fleet_failovers_counter.labels(reason=reason).inc()
                shed_response = response
                continue
            with self._lock:
                if rid in self._clients:
                    self._penalty[rid] = 0
                    self._note_step_locked(rid, int(response.model_step))
            if self._freshness is not None:
                steps, seconds = self._freshness.observe_response(
                    int(response.model_step)
                )
                with self._lock:
                    self._last_staleness = (steps, round(seconds, 6))
            return response
        if shed_response is not None:
            return shed_response
        if last_error is None:
            # Every candidate was retired mid-sweep (scale_down racing
            # this request): retryable, the next sweep sees the new
            # membership — never `raise None`.
            raise ConnectionError(
                "no serving replica survived the sweep"
            )
        raise last_error

    def predict(self, request, timeout=None):
        """Route one Predict through the resilience policy: each attempt
        is a full fleet sweep, so backoff only happens when no replica
        could take the request at all.

        Every request gets a deterministic `request_id`; sampled-in
        requests carry it on the wire (the replica stamps its span
        against it), and the router emits its own span — always for
        errors/sheds/failovers, per `trace_sample_rate` otherwise."""
        _fleet_requests_counter.inc()
        with self._lock:
            self._seq += 1
            self._requests += 1
            seq = self._seq
            failovers_before = sum(self._failovers.values())
        sampled = self._trace_every > 0 and seq % self._trace_every == 0
        request_id = f"rq-{seq:08d}"
        if hasattr(request, "request_id"):
            # always (re)stamp: a caller-reused request proto must not
            # ride the wire with the previous call's trace context
            request.request_id = request_id if sampled else ""
        route_start = self._clock()
        try:
            response = self._retry_policy.call(
                lambda: self._sweep(request, timeout=timeout),
                description="fleet_predict",
            )
        except Exception as exc:
            _fleet_request_errors_counter.inc()
            route_s = max(0.0, self._clock() - route_start)
            _fleet_route_histogram.record(route_s)
            events.emit(
                events.PREDICT_SPAN, request_id=request_id,
                reason="error", error=type(exc).__name__,
                phases_s={"route": route_s},
            )
            raise
        route_s = max(0.0, self._clock() - route_start)
        _fleet_route_histogram.record(route_s)
        if hasattr(response, "request_id") and not response.request_id:
            response.request_id = request_id
        with self._lock:
            failed_over = sum(self._failovers.values()) > failovers_before
        phases = {"route": route_s}
        if response.code in SHED_CODES:
            _fleet_sheds_counter.inc()
            with self._lock:
                self._sheds += 1
            # whole-fleet shed: admission control spoke — always capture
            events.emit(
                events.PREDICT_SPAN, request_id=request_id,
                reason="shed", code=int(response.code), phases_s=phases,
            )
        elif response.code == spb.SERVING_INVALID:
            events.emit(
                events.PREDICT_SPAN, request_id=request_id,
                reason="invalid", code=int(response.code), phases_s=phases,
            )
        elif response.code == spb.SERVING_INTERNAL:
            events.emit(
                events.PREDICT_SPAN, request_id=request_id,
                reason="internal", code=int(response.code),
                phases_s=phases,
            )
        elif failed_over:
            # served OK but not by the first choice: capture the hop
            events.emit(
                events.PREDICT_SPAN, request_id=request_id,
                reason="failover", code=int(response.code),
                phases_s=phases,
            )
        elif sampled:
            events.emit(
                events.PREDICT_SPAN, request_id=request_id,
                reason="sampled", code=int(response.code),
                phases_s=phases,
            )
        return response
