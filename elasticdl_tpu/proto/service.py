"""Hand-written gRPC service glue for the Master control-plane service.

grpcio-tools is unavailable in this environment, so instead of generated
`_pb2_grpc.py` service classes we register the service with grpc's generic
handler API and build client stubs from `channel.unary_unary`.  The wire
format (method paths, protobuf request/response types) is identical to what
`protoc --grpc_python_out` would have produced for the `Master` service
declared in elasticdl.proto.

Parity: reference `elasticdl/proto/elasticdl.proto` service `Master`
(SURVEY.md C1/C2).  The `Pserver` service is intentionally absent — tensor
traffic lives on the device mesh in the TPU-native design.
"""

from __future__ import annotations

from elasticdl_tpu.common import faults
from elasticdl_tpu.common import metrics as _metrics
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.proto import serving_pb2 as spb

# Server-side RPC counters, shared by both transports: the gRPC handler
# wrapper below and the in-process direct-call path count through the
# same series, so tests and real-socket runs read identically.
_requests_counter = _metrics.default_registry().counter(
    "rpc_server_requests_total",
    "RPC handler invocations, by service and method",
    labelnames=("service", "method"),
)
_errors_counter = _metrics.default_registry().counter(
    "rpc_server_errors_total",
    "RPC handler invocations that raised, by service and method",
    labelnames=("service", "method"),
)


def _observed(handler, service: str, method: str):
    """Wrap a (request, context) handler with the request/error series."""

    def _wrapped(request, context):
        _requests_counter.labels(service=service, method=method).inc()
        try:
            return handler(request, context)
        except Exception:
            _errors_counter.labels(service=service, method=method).inc()
            raise

    return _wrapped

SERVICE_NAME = "elasticdl_tpu.Master"
SERVING_SERVICE_NAME = "elasticdl_tpu.Serving"

# method name -> (request class, response class)
MASTER_METHODS = {
    "get_task": (pb.GetTaskRequest, pb.GetTaskResponse),
    "get_spmd_task": (pb.GetSpmdTaskRequest, pb.SpmdTaskResponse),
    "report_task_result": (pb.ReportTaskResultRequest, pb.Empty),
    "report_evaluation_metrics": (pb.ReportEvaluationMetricsRequest, pb.Empty),
    "get_cluster_spec": (pb.GetClusterSpecRequest, pb.ClusterSpec),
    "keep_alive": (pb.KeepAliveRequest, pb.Empty),
    "report_version": (pb.ReportVersionRequest, pb.Empty),
}

# method name -> fault-injection point (common/faults.py).  Both client
# transports fire the method's point per attempt, so a chaos schedule
# exercises in-process tests and real-socket runs identically.
METHOD_FAULT_POINTS = {
    "get_task": faults.POINT_RPC_GET_TASK,
    "get_spmd_task": faults.POINT_RPC_GET_TASK,
    "report_task_result": faults.POINT_RPC_REPORT,
    "report_evaluation_metrics": faults.POINT_RPC_REPORT,
    "report_version": faults.POINT_RPC_REPORT,
    "get_cluster_spec": faults.POINT_RENDEZVOUS_JOIN,
    "keep_alive": faults.POINT_WORKER_HEARTBEAT,
}


# The online-serving data plane (serving.proto; docs/SERVING.md).
# `health` carries no fault point: it is the probe used to decide whether
# to restart a replica, and injecting failures into the prober makes every
# chaos schedule flap the fleet instead of testing the data path.
SERVING_METHODS = {
    "predict": (spb.PredictRequest, spb.PredictResponse),
    "health": (spb.HealthRequest, spb.HealthResponse),
}

SERVING_METHOD_FAULT_POINTS = {
    "predict": faults.POINT_RPC_PREDICT,
}


def method_fault_point_paths() -> dict:
    """Full-path variant ('/elasticdl_tpu.Master/get_task' -> point) for
    the gRPC client interceptor, which only sees method paths."""
    return {
        f"/{SERVICE_NAME}/{name}": point
        for name, point in METHOD_FAULT_POINTS.items()
    }


def serving_fault_point_paths() -> dict:
    return {
        f"/{SERVING_SERVICE_NAME}/{name}": point
        for name, point in SERVING_METHOD_FAULT_POINTS.items()
    }


def _add_servicer_to_server(servicer, server, service_name, methods) -> None:
    import grpc

    handlers = {}
    for name, (req_cls, resp_cls) in methods.items():
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            _observed(getattr(servicer, name), service_name, name),
            request_deserializer=req_cls.FromString,
            response_serializer=lambda msg, _cls=resp_cls: msg.SerializeToString(),
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(service_name, handlers),)
    )


def add_master_servicer_to_server(servicer, server) -> None:
    """Register `servicer` (an object with MASTER_METHODS-named methods
    accepting (request, context)) on a `grpc.Server`."""
    _add_servicer_to_server(servicer, server, SERVICE_NAME, MASTER_METHODS)


def add_serving_servicer_to_server(servicer, server) -> None:
    """Register a serving servicer (predict/health methods accepting
    (request, context)) on a `grpc.Server`."""
    _add_servicer_to_server(
        servicer, server, SERVING_SERVICE_NAME, SERVING_METHODS
    )


class _StubBase:
    """Builds stub methods from a channel; subclasses pin the service name,
    method table, and fault-point map.

    With `retry_policy`, every method goes through the resilience
    interceptor: per-attempt deadline, exponential backoff + full jitter,
    max-elapsed budget, and per-attempt fault injection."""

    _service_name: str
    _methods: dict
    _fault_point_paths: staticmethod

    def __init__(self, channel, retry_policy=None):
        if retry_policy is not None:
            import grpc

            from elasticdl_tpu.common.resilience import (
                RetryingClientInterceptor,
            )

            channel = grpc.intercept_channel(
                channel,
                RetryingClientInterceptor(
                    retry_policy, fault_points=type(self)._fault_point_paths()
                ),
            )
        for name, (req_cls, resp_cls) in self._methods.items():
            callable_ = channel.unary_unary(
                f"/{self._service_name}/{name}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString,
            )
            setattr(self, name, _StripContext(callable_))


class MasterStub(_StubBase):
    """Client stub over a grpc channel; method-for-method mirror of the
    servicer so `InProcessMasterClient` (direct servicer calls, used by the
    tests and local mode) and this stub are interchangeable."""

    _service_name = SERVICE_NAME
    _methods = MASTER_METHODS
    _fault_point_paths = staticmethod(method_fault_point_paths)


class ServingStub(_StubBase):
    """Client stub for the Serving data plane; interchangeable with
    `InProcessServingClient` the same way MasterStub mirrors its
    in-process twin."""

    _service_name = SERVING_SERVICE_NAME
    _methods = SERVING_METHODS
    _fault_point_paths = staticmethod(serving_fault_point_paths)


class _StripContext:
    """Adapts stub(request) to the servicer-side (request, context) shape so
    both transports expose `fn(request)`."""

    def __init__(self, callable_):
        self._callable = callable_

    def __call__(self, request, timeout=None):
        return self._callable(request, timeout=timeout)


class _InProcessClientBase:
    """Calls a servicer directly, no sockets; subclasses pin the method
    table and fault-point map."""

    _methods: dict
    _fault_points: dict

    _service_name: str = ""

    def __init__(self, servicer, retry_policy=None):
        for name in self._methods:
            method = _observed(
                getattr(servicer, name), self._service_name, name
            )
            point = self._fault_points.get(name)
            call = self._make_call(method, point, retry_policy, name)
            setattr(self, name, call)

    @staticmethod
    def _make_call(method, point, retry_policy, name):
        def _attempt(request):
            if point is not None:
                faults.fire(point)
            return method(request, None)

        if retry_policy is None:
            return lambda request, timeout=None: _attempt(request)
        return lambda request, timeout=None: retry_policy.call(
            lambda: _attempt(request), description=name
        )


class InProcessMasterClient(_InProcessClientBase):
    """Calls a MasterServicer directly, no sockets.  Used by tests and by
    `--distribution_strategy=Local` where master and worker share a process
    (the reference exercises its protocol the same way in
    worker_ps_interaction_test.py — SURVEY.md §4.2)."""

    _service_name = SERVICE_NAME
    _methods = MASTER_METHODS
    _fault_points = METHOD_FAULT_POINTS


class InProcessServingClient(_InProcessClientBase):
    """Direct-call twin of ServingStub for tests and in-process benches."""

    _service_name = SERVING_SERVICE_NAME
    _methods = SERVING_METHODS
    _fault_points = SERVING_METHOD_FAULT_POINTS
