import os
import subprocess

_HERE = os.path.dirname(__file__)


def _ensure_generated():
    """Regenerate elasticdl_pb2.py from the .proto if missing or stale."""
    proto = os.path.join(_HERE, "elasticdl.proto")
    gen = os.path.join(_HERE, "elasticdl_pb2.py")
    if not os.path.exists(gen) or os.path.getmtime(gen) < os.path.getmtime(proto):
        subprocess.run(
            ["protoc", f"--python_out={_HERE}", f"--proto_path={_HERE}", proto],
            check=True,
        )


_ensure_generated()

from elasticdl_tpu.proto import elasticdl_pb2  # noqa: E402,F401
