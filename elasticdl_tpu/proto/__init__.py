import os
import subprocess

_HERE = os.path.dirname(__file__)


def _ensure_generated():
    """Regenerate elasticdl_pb2.py from the .proto if missing or stale."""
    proto = os.path.join(_HERE, "elasticdl.proto")
    gen = os.path.join(_HERE, "elasticdl_pb2.py")
    if not os.path.exists(gen) or os.path.getmtime(gen) < os.path.getmtime(proto):
        subprocess.run(
            ["protoc", f"--python_out={_HERE}", f"--proto_path={_HERE}", proto],
            check=True,
        )


def _ensure_serving_generated():
    """Regenerate serving_pb2.py if serving.proto changed.

    serving_pb2.py is built by scripts/gen_serving_pb2.py (pure python —
    no protoc needed) and checked in; best-effort here because the script
    lives outside the installed package, and the checked-in module is
    valid whenever the .proto hasn't been edited."""
    proto = os.path.join(_HERE, "serving.proto")
    gen = os.path.join(_HERE, "serving_pb2.py")
    script = os.path.normpath(
        os.path.join(_HERE, "..", "..", "scripts", "gen_serving_pb2.py")
    )
    if not os.path.exists(proto) or not os.path.exists(script):
        return
    if os.path.exists(gen) and (
        os.path.getmtime(gen) >= os.path.getmtime(proto)
    ):
        return
    import sys

    # strict only when the generated module is missing outright; a stale
    # regen failure still leaves a working (if outdated) checked-in module
    subprocess.run([sys.executable, script], check=not os.path.exists(gen))


_ensure_generated()
_ensure_serving_generated()

from elasticdl_tpu.proto import elasticdl_pb2  # noqa: E402,F401
