"""Online continuous learning: one loop from stream to served model.

The batch system in this repo runs stream -> train -> checkpoint ->
hot-reload as four separately-benched pieces.  `OnlinePipeline` closes
them into one measured loop (docs/ONLINE.md):

    ClickStreamSource -> StreamReader (bounded windows, watermark)
        -> TaskManager(perpetual=True).arm_window  (queue re-arms forever)
        -> Trainer.train_on_batch per leased task
        -> CheckpointSaver every `checkpoint_every_windows` windows
           (keep-last-K sweep + freshness stamp)
        -> ServingFleetManager.tick  (sequenced hot-swaps behind the
           FleetRouter, live traffic keeps flowing)
        -> FreshnessTracker + MetricHistory + SloEvaluator
           (staleness_p99 measures REAL stream-to-serve lag)

Elasticity (this PR's tentpole): training fans out over `workers`
LOGICAL trainer workers — distinct lease identities against the task
manager and distinct shard owners in a `ShardedTieredStore` (per-row
CTR statistics sharded `row % num_shards`).  `kill_worker` requeues a
dead trainer's leases and hands its shard slices to the survivors
(`store.shard_handoff` fault-covered); `restart_master` rebuilds the
perpetual queue from the window-ledger journal so every unfinished
window re-arms exactly its undone shards — no window trains twice, none
is silently lost.  With `max_workers > workers` a `PolicyEngine`
scales the trainer pool mid-stream on watermark lag and armed-window
backlog.

Every time-reading collaborator shares ONE injectable clock, and every
decision maker (task manager, fleet manager, SLO evaluator, policy
engine, shard map, fault registry) is already deterministic under a
fake clock — so the chaos variant of `bench.py --online` replays
byte-identically across same-seed runs while a stream stall, a trainer
kill, a master restart, a shard-handoff fault, and a reload fault land
mid-loop.

Single-process by design: the serving replicas are in-process servicers
behind killable clients (the bench_serving_fleet harness shape,
bench.py), which keeps the full loop runnable in CI seconds.  The
multi-process story reuses the same pieces unchanged — the reader and
task manager already speak the worker lease protocol.
"""

from __future__ import annotations

import math
import os
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

import numpy as np

from elasticdl_tpu.common import events
from elasticdl_tpu.common import metrics as metrics_lib
from elasticdl_tpu.common.history import MetricHistory
from elasticdl_tpu.common.lineage import WindowLineage
from elasticdl_tpu.common.k8s_client import FakeK8sClient
from elasticdl_tpu.common.constants import PodStatus
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.resilience import RetryPolicy
from elasticdl_tpu.common.save_utils import CheckpointSaver
from elasticdl_tpu.common.slo import (
    SLO_PREDICT_SHED_RATIO,
    SLO_STALENESS_P99,
    SloEvaluator,
    shipped_specs,
)
from elasticdl_tpu.data.reader.stream_reader import (
    ClickStreamSource,
    StreamReader,
)
from elasticdl_tpu.master.freshness import FreshnessTracker
from elasticdl_tpu.master.policy import (
    PolicyConfig,
    PolicyEngine,
    ServingPolicyConfig,
    ServingPolicyEngine,
)
from elasticdl_tpu.master.serving_fleet import (
    ServingFleetConfig,
    ServingFleetManager,
)
from elasticdl_tpu.master.task_manager import TaskManager
from elasticdl_tpu.proto.service import FleetRouter, InProcessServingClient
from elasticdl_tpu.store import checkpoint as store_checkpoint
from elasticdl_tpu.store.sharding import ShardedTieredStore

logger = get_logger(__name__)


@dataclass
class OnlineConfig:
    """Shape of one online loop.  Defaults are CI-sized: a few hundred
    records per window, two replicas, a checkpoint every other window."""

    seed: int = 0
    window_records: int = 128
    records_per_task: int = 32
    records_per_poll: int = 64
    max_buffered_windows: int = 64
    checkpoint_every_windows: int = 2
    keep_max: int = 3
    replicas: int = 2
    probe_failures: int = 2
    step_skew_slo: int = 16
    source_users: int = 512
    source_items: int = 128
    # ---- elastic training pool + sharded store ----
    workers: int = 1                 # logical trainer workers
    num_shards: int = 4              # store row-space shards (row % N)
    store_cache_rows: int = 512      # total hot-row capacity, all shards
    max_workers: int = 0             # > workers enables the PolicyEngine
    stream_lag_s: float = 60.0       # scale-up threshold (watermark lag)
    stream_lag_ticks: int = 2
    # ---- serving autoscaler + train/serve backpressure ----
    max_serving_replicas: int = 0    # > replicas enables the autoscaler
    min_serving_replicas: int = 0    # 0 = `replicas` (the placed size)
    serving_up_ticks: int = 2        # autoscaler hysteresis streaks
    serving_down_ticks: int = 3
    serving_scale_hold_ticks: int = 2
    serving_shed_window_s: float = 30.0
    serving_burn_threshold: float = 1.0
    serving_shed_threshold: float = 0.02
    backpressure_threshold: float = 0.25  # serving_pressure gate
    backpressure_stride: int = 4     # poll/arm every Nth tick when over


class _KillableClient:
    """In-process serving client with a kill switch standing in for a
    dead pod (same harness shape as bench_serving_fleet)."""

    def __init__(self, servicer):
        self._inner = InProcessServingClient(servicer)
        self.killed = False

    def predict(self, request, timeout=None):
        if self.killed:
            raise ConnectionError("replica killed")
        return self._inner.predict(request, timeout=timeout)

    def health(self, request, timeout=None):
        if self.killed:
            raise ConnectionError("replica killed")
        return self._inner.health(request, timeout=timeout)


class _TrainerPool:
    """PodManager-shaped adapter over the pipeline's LOGICAL trainer
    workers — distinct lease identities + shard owners, not processes.
    Implements exactly the surface the PolicyEngine drives
    (alive_workers / evict_worker / scale_up / scale_down), so the
    master's one policy loop actuates the perpetual trainer fleet the
    same way it actuates batch pods."""

    def __init__(self, pipeline: "OnlinePipeline", worker_ids):
        self._pipeline = pipeline
        self._alive: List[int] = sorted(int(w) for w in worker_ids)
        self._next_id = (max(self._alive) + 1) if self._alive else 0

    def alive_workers(self) -> List[int]:
        return list(self._alive)

    def drop_worker(self, worker_id: int) -> bool:
        """Remove WITHOUT replacement (the chaos kill path); shard
        evacuation and lease recovery are the pipeline's job."""
        if worker_id not in self._alive or len(self._alive) <= 1:
            return False
        self._alive.remove(worker_id)
        return True

    def evict_worker(self, worker_id: int) -> bool:
        """Evict + relaunch on a fresh id (the group-restart shape the
        real PodManager has): the victim's shards hand off to the
        survivors, then the replacement joins and takes a fair share
        back — both sides of the handoff protocol in one action."""
        if worker_id not in self._alive or len(self._alive) <= 1:
            return False
        self._alive.remove(worker_id)
        self._pipeline._retire_worker(worker_id)
        new_id = self._next_id
        self._next_id += 1
        self._alive.append(new_id)
        self._alive.sort()
        self._pipeline._admit_worker(new_id)
        return True

    def scale_up(self, n: int) -> int:
        launched = 0
        for _ in range(max(0, int(n))):
            new_id = self._next_id
            self._next_id += 1
            self._alive.append(new_id)
            self._pipeline._admit_worker(new_id)
            launched += 1
        self._alive.sort()
        return launched

    def scale_down(self, n: int, prefer=()) -> List[int]:
        victims: List[int] = []
        preferred = [w for w in prefer if w in self._alive]
        rest = [
            w for w in sorted(self._alive, reverse=True)
            if w not in preferred
        ]
        for w in preferred + rest:
            if len(victims) >= int(n):
                break
            if len(self._alive) - len(victims) <= 1:
                break
            victims.append(w)
        for w in victims:
            self._alive.remove(w)
            self._pipeline._retire_worker(w)
        return victims


class _TaskManagerProxy:
    """The PolicyEngine holds its task manager by reference, but the
    pipeline REPLACES the task manager on a master restart.  This thin
    forwarder keeps the engine pointed at whichever instance is live."""

    def __init__(self, pipeline: "OnlinePipeline"):
        self._pipeline = pipeline

    def snapshot(self) -> dict:
        return self._pipeline.task_manager.snapshot()

    def straggler_snapshot(self) -> dict:
        return self._pipeline.task_manager.straggler_snapshot()


class OnlinePipeline:
    """Builds and drives the whole loop.  `tick()` is one iteration:
    poll the stream, arm sealed windows, train the leased tasks,
    checkpoint on cadence, tick the serving fleet and the SLO watcher.
    Call it forever (the real deployment) or N times (bench/tests)."""

    def __init__(
        self,
        checkpoint_dir: str,
        spec,
        config: Optional[OnlineConfig] = None,
        clock: Callable[[], float] = time.time,
        source=None,
        client_wrapper: Optional[Callable] = None,
    ):
        # `client_wrapper(rid, client) -> client` interposes on every
        # replica client the router sees (including ones the autoscaler
        # launches later) — how bench.py --traffic models a replica's
        # finite per-tick serving capacity without faking the servicer.
        import jax

        from elasticdl_tpu.serving.batcher import DynamicBatcher
        from elasticdl_tpu.serving.engine import ServingEngine
        from elasticdl_tpu.serving.reloader import CheckpointReloader
        from elasticdl_tpu.serving.server import ServingServicer
        from elasticdl_tpu.worker.trainer import Trainer

        self.config = cfg = config or OnlineConfig()
        self.spec = spec
        self._clock = clock

        # ---- window lineage (docs/OBSERVABILITY.md "Window lineage") ----
        # Tapped on the event stream BEFORE any collaborator can emit a
        # `window_span`, so every hop of every window joins.  The
        # broadcast hops (checkpoint / reload / first serve) fan out to
        # per-window stamps below via the lineage's join queries.
        self.lineage = WindowLineage(clock=clock)
        self.lineage.install()

        # ---- stream -> windows ------------------------------------------
        self.source = source if source is not None else ClickStreamSource(
            seed=cfg.seed, users=cfg.source_users, items=cfg.source_items,
            records_per_poll=cfg.records_per_poll, clock=clock,
        )
        self.reader = StreamReader(
            self.source, window_records=cfg.window_records,
            max_buffered_windows=cfg.max_buffered_windows, clock=clock,
        )
        self._pending_windows = []          # sealed, not yet armed
        self._window_tasks_left = {}        # window name -> tasks open
        self._window_ids = {}               # window name -> window id

        # ---- perpetual task queue (journaled window ledger) -------------
        # The journal is what makes `restart_master` exactly-once: the
        # replacement re-arms unfinished windows' UNDONE shards only.
        self._checkpoint_dir = checkpoint_dir
        self._journal_path = os.path.join(
            checkpoint_dir, "window_ledger.json"
        )
        self.task_manager = TaskManager(
            perpetual=True, clock=clock, persist_path=self._journal_path,
        )
        self.master_restarts = 0

        # ---- sharded tiered store (per-row CTR statistics) --------------
        # Row space = user rows then item rows (HostTier field-disjoint
        # assignment over fields {0: user, 1: item}); the "ctr" plane
        # accumulates [impressions, clicks] per row.  Host tier is
        # master-resident, so a trainer death loses only cache residency
        # — the handoff protocol's whole point.
        self.store = ShardedTieredStore(
            planes={"ctr": 2},
            num_fields=2,
            cache_rows=cfg.store_cache_rows,
            num_shards=cfg.num_shards,
            workers=range(max(1, cfg.workers)),
        )
        self._sidecar_steps: List[int] = []

        # ---- elastic trainer pool + policy engine -----------------------
        self.pool = _TrainerPool(self, range(max(1, cfg.workers)))
        self._rr = 0                        # round-robin lease cursor
        self.policy: Optional[PolicyEngine] = None
        if cfg.max_workers > cfg.workers:
            self.policy = PolicyEngine(
                _TaskManagerProxy(self),
                self.pool,
                PolicyConfig(
                    min_workers=1,
                    max_workers=cfg.max_workers,
                    stream_lag_s=cfg.stream_lag_s,
                    stream_lag_ticks=cfg.stream_lag_ticks,
                ),
                clock=clock,
                stream_lag_fn=self._stream_lag,
            )

        # ---- trainer -----------------------------------------------------
        self.trainer = Trainer(spec.model, spec.optimizer, spec.loss)
        sample = spec.feed(
            ClickStreamSource(
                seed=cfg.seed, users=cfg.source_users,
                items=cfg.source_items, clock=lambda: 0.0,
            ).poll(2),
            self.reader.metadata,
        )["features"]
        self._sample = np.asarray(sample)
        self.state = self.trainer.init_state(
            jax.random.PRNGKey(cfg.seed), self._sample
        )

        # ---- checkpoints -------------------------------------------------
        self.saver = CheckpointSaver(
            checkpoint_dir, keep_max=cfg.keep_max, async_save=False,
            clock=clock,
        )
        # An initial step-0 checkpoint so the serving fleet has a model
        # before the first window finishes training.
        self.saver.save(self.state, force=True)
        self.saver.wait_until_finished()
        self._latest_saved = int(self.state.step)
        self._windows_since_save = 0
        self._windows_trained = 0
        self._examples_trained = 0
        self._last_loss = float("nan")

        # ---- serving fleet (in-process replicas) ------------------------
        self.k8s = FakeK8sClient()
        self.freshness = FreshnessTracker(
            clock=clock,
            produced_time_fn=lambda step: (
                self.saver.produced_meta(step) or {}
            ).get("produced_unix_s"),
            on_first_serve=self._note_first_serve,
        )
        self.router = FleetRouter(
            retry_policy=RetryPolicy(
                initial_backoff_s=0.001, max_backoff_s=0.01,
                max_elapsed_s=30.0, max_attempts=8,
            ),
            freshness=self.freshness,
        )
        self._fleet = {}

        def make_replica(rid):
            # Lazily materialised so the autoscaler's scale_up can mint
            # replicas past the initial placement — a scaled-in replica
            # that returns later reuses its warmed engine.
            if rid not in self._fleet:
                engine = ServingEngine.from_checkpoint(
                    checkpoint_dir, spec, self._sample, buckets=(2, 8)
                )
                batcher = DynamicBatcher(engine, max_latency_s=0.002)
                reloader = CheckpointReloader(
                    engine, checkpoint_dir, poll_interval_s=3600.0
                )
                self._fleet[rid] = {
                    "engine": engine,
                    "batcher": batcher,
                    "reloader": reloader,
                    "servicer": ServingServicer(engine, batcher, reloader),
                    "client": None,
                }
            return self._fleet[rid]

        for rid in range(cfg.replicas):
            make_replica(rid)

        def client_factory(rid, _addr):
            rep = make_replica(rid)
            # kill_replica flips the INNER client's switch, so a wrapped
            # client still dies when chaos asks it to
            rep["client"] = _KillableClient(rep["servicer"])
            if client_wrapper is not None:
                return client_wrapper(rid, rep["client"])
            return rep["client"]

        self.fleet_manager = ServingFleetManager(
            self.k8s,
            ServingFleetConfig(
                replicas=cfg.replicas, interval_s=0.0,
                probe_failures=cfg.probe_failures,
                step_skew_slo=cfg.step_skew_slo,
            ),
            job_name="online",
            client_factory=client_factory,
            reload_fn=lambda rid: self._fleet[rid][
                "reloader"
            ].check_once(),
            pending_step_fn=lambda: self._latest_saved,
            router=self.router,
            clock=clock,
            freshness=self.freshness,
        )
        self.fleet_manager.place()
        self.fleet_manager.tick()   # prime: every replica probed healthy

        # ---- SLO watcher -------------------------------------------------
        # The history samples the stream-lag gauges alongside the
        # freshness/fleet series, so `elasticdl slo` history coverage
        # includes the stream-lag series (docs/OBSERVABILITY.md).
        # The process-wide default registry carries the router's
        # rpc_fleet_requests/sheds counters — the windowed shed-ratio
        # evidence the serving autoscaler reads.
        self.history = MetricHistory(
            registries=[
                metrics_lib.default_registry(),
                self.freshness.metrics_registry,
                self.fleet_manager.metrics_registry,
                self.reader.metrics_registry,
                self.task_manager.counters.registry,
                self.store.registry,
                self.lineage.registry,
            ],
            clock=clock,
        )
        # Staleness (the train->serve freshness promise) plus the
        # shed-ratio SLO whose burn is the autoscaler's and the
        # backpressure signal's overload evidence.
        self.evaluator = SloEvaluator(
            self.history,
            specs=[
                s for s in shipped_specs()
                if s.name in (SLO_STALENESS_P99, SLO_PREDICT_SHED_RATIO)
            ],
            clock=clock,
        )
        self.max_burn = 0.0
        self.ticks = 0

        # ---- serving autoscaler + backpressure --------------------------
        self.serving_policy: Optional[ServingPolicyEngine] = None
        if cfg.max_serving_replicas > cfg.replicas:
            self.serving_policy = ServingPolicyEngine(
                self.fleet_manager,
                ServingPolicyConfig(
                    min_replicas=cfg.min_serving_replicas or cfg.replicas,
                    max_replicas=cfg.max_serving_replicas,
                    up_ticks=cfg.serving_up_ticks,
                    down_ticks=cfg.serving_down_ticks,
                    scale_hold_ticks=cfg.serving_scale_hold_ticks,
                    shed_window_s=cfg.serving_shed_window_s,
                    burn_threshold=cfg.serving_burn_threshold,
                    shed_threshold=cfg.serving_shed_threshold,
                ),
                history=self.history,
                evaluator=self.evaluator,
                clock=clock,
            )
        # serving_pressure = burn rate x shed ratio, refreshed each tick
        # from the router's own request/shed counters: when serving is
        # overloaded, training slows its ingest instead of racing the
        # serve tier for the machine (docs/SERVING.md "Autoscaling &
        # backpressure").
        self._serving_pressure = 0.0
        self._polls_skipped = 0
        self._router_seen = {"requests": 0, "sheds": 0}
        self.metrics_registry = metrics_lib.MetricsRegistry()
        self.metrics_registry.gauge_fn(
            "master_serving_pressure_ratio",
            lambda: self._serving_pressure,
            "burn rate x fleet shed ratio at the last tick — the "
            "train-side backpressure signal",
        )
        self._backpressure_skips = self.metrics_registry.counter(
            "master_backpressure_skipped_polls_total",
            "stream poll/arm rounds skipped because serving pressure "
            "was over --backpressure_threshold",
        )

    # ---- one loop iteration ---------------------------------------------

    def tick(self, max_train_tasks: Optional[int] = None) -> dict:
        """Poll -> arm -> policy -> train -> checkpoint -> serve.
        Returns a small progress dict for the caller's loop telemetry.
        The policy tick runs BETWEEN arming and draining so its signals
        (armed-window backlog, watermark lag) see the queue at its
        fullest — the moment a scaling decision is actionable.
        `max_train_tasks` caps this tick's training (a slow trainer
        fleet in miniature): leftover tasks stay queued, which is what
        lets chaos land a master restart while windows are mid-flight
        and lets backlog build for the policy signals.

        Backpressure: while last tick's `serving_pressure` (burn rate x
        fleet shed ratio) is over `backpressure_threshold`, the stream
        poll/arm pair runs only every `backpressure_stride`-th tick —
        ingest slows, already-queued tasks still drain, and the serve
        tier gets the machine back until the pressure clears."""
        cfg = self.config
        backpressured = (
            self._serving_pressure > cfg.backpressure_threshold
            and self.ticks % max(1, cfg.backpressure_stride) != 0
        )
        if backpressured:
            polled = 0
            self._polls_skipped += 1
            self._backpressure_skips.inc()
        else:
            polled = self.reader.poll()
            self._arm_pending()
        if self.policy is not None:
            self.policy.tick()
        trained = self._drain_tasks(max_train_tasks)
        saved = self._maybe_checkpoint()
        self.fleet_manager.tick()
        self._stamp_reloads()
        self.history.tick()
        self.evaluator.tick()
        if self.serving_policy is not None:
            self.serving_policy.tick()
        self._refresh_pressure()
        self.max_burn = max(self.max_burn, self.evaluator.max_burn())
        self.ticks += 1
        return {
            "polled": polled,
            "trained_tasks": trained,
            "checkpointed": saved,
            "model_step": int(self.state.step),
            "loss": self._last_loss,
            "backpressured": backpressured,
        }

    def _stamp_reloads(self) -> None:
        """Fan the fleet's latest sequenced reload out into per-window
        `reload_wait` lineage stamps.  `windows_awaiting_reload` only
        matches windows whose covering checkpoint step the reload
        actually carries, so a stale record from an earlier tick can
        never stamp a window produced after it."""
        info = self.fleet_manager.last_reload()
        if not info:
            return
        for window_id in self.lineage.windows_awaiting_reload(
                info["step"]):
            events.emit(
                events.WINDOW_SPAN,
                window_id=int(window_id),
                phase="reload_wait",
                reason="reloaded",
                at_unix_s=round(float(info["unix_s"]), 6),
                step=int(info["step"]),
                replica=int(info["replica"]),
            )

    def _note_first_serve(self, model_step: int, at_unix_s: float) -> None:
        """FreshnessTracker hook: the first Predict response echoing a
        new model step closes serve_wait for every window that step's
        checkpoint covered."""
        for window_id in self.lineage.windows_awaiting_serve(model_step):
            events.emit(
                events.WINDOW_SPAN,
                window_id=int(window_id),
                phase="serve_wait",
                reason="served",
                at_unix_s=round(float(at_unix_s), 6),
                step=int(model_step),
            )

    def _refresh_pressure(self) -> None:
        """Recompute `serving_pressure` from this tick's router deltas
        (clock-free: instance counters, not wall-clock windows)."""
        stats = self.router.stats()
        requests = int(stats.get("requests", 0))
        sheds = int(stats.get("sheds", 0))
        d_requests = requests - self._router_seen["requests"]
        d_sheds = sheds - self._router_seen["sheds"]
        self._router_seen = {"requests": requests, "sheds": sheds}
        shed_ratio = d_sheds / d_requests if d_requests > 0 else 0.0
        self._serving_pressure = round(
            self.evaluator.max_burn() * shed_ratio, 6
        )

    def _arm_pending(self) -> None:
        self._pending_windows.extend(self.reader.take_new_windows())
        still_pending = []
        for window in self._pending_windows:
            n = self.task_manager.arm_window(
                window.name, len(window.records),
                self.config.records_per_task,
                watermark_unix_s=window.watermark_unix_s,
                window_id=window.window_id,
                start_index=window.start_index,
            )
            if n is None:
                # injected task.rearm fault: the window stays pending and
                # is re-offered next tick (docs/ROBUSTNESS.md)
                still_pending.append(window)
            elif n > 0:
                self._window_tasks_left[window.name] = n
                self._window_ids[window.name] = window.window_id
            # n == 0: the ledger already tracks (or released) this id —
            # a re-offer after a master restart; bookkeeping was rebuilt
            # from open_windows(), nothing to add.
        self._pending_windows = still_pending

    def _lease_next(self):
        """Round-robin one lease attempt over the alive trainer pool.
        Returns (worker_id, task) or (None, None) when the queue is
        drained for this tick."""
        alive = self.pool.alive_workers()
        for _ in range(len(alive)):
            wid = alive[self._rr % len(alive)]
            self._rr += 1
            task = self.task_manager.get(wid)
            if task is not None:
                return wid, task
        return None, None

    def _drain_tasks(self, budget: Optional[int] = None) -> int:
        trained = 0
        while budget is None or trained < budget:
            wid, task = self._lease_next()
            if task is None:
                return trained
            name = task.shard.name
            try:
                records = list(self.reader.read_records(task))
            except LookupError:
                # Not buffered — replay it from the deterministic source
                # (the journal knows the window's stream offsets) instead
                # of dropping the task blind.
                if self._restore_window(name):
                    records = list(self.reader.read_records(task))
                else:
                    self._forfeit(wid, task)
                    continue
            batch = self.spec.feed(records, self.reader.metadata)
            self.state, loss = self.trainer.train_on_batch(
                self.state, batch
            )
            lineage_wid = self._window_ids.get(name)
            if lineage_wid is not None:
                # Per-task train-completion stamp; the lineage join keeps
                # the LAST task's stamp as the window's train boundary.
                events.emit(
                    events.WINDOW_SPAN,
                    window_id=int(lineage_wid),
                    phase="train",
                    reason="trained",
                    at_unix_s=round(float(self._clock()), 6),
                    step=int(self.state.step),
                    start=int(task.shard.start),
                )
            self._fold_store_stats(records)
            if lineage_wid is not None:
                # Admission stamp right after the tiered-store fold: the
                # admission phase is the store's plan+fold latency for
                # this window's rows.
                events.emit(
                    events.WINDOW_SPAN,
                    window_id=int(lineage_wid),
                    phase="admission",
                    reason="admitted",
                    at_unix_s=round(float(self._clock()), 6),
                    rows=2 * len(records),
                )
            self._last_loss = float(loss)
            self._examples_trained += len(records)
            trained += 1
            self.task_manager.report(
                task.task_id, True, worker_id=wid, records=len(records),
                model_version=int(self.state.step),
            )
            self._window_done(name)
        return trained

    def _fold_store_stats(self, records) -> None:
        """Per trained task: admit the batch's (user, item) rows through
        the sharded cache plan, then fold [impressions, clicks] into the
        host "ctr" plane — the live state a shard handoff must not lose
        (the chaos test pins its byte stability)."""
        if not records:
            return
        sparse = np.array(
            [[r["user"], r["item"]] for r in records], np.int64
        )
        plan = self.store.prepare(sparse)
        clicked = np.array([r["clicked"] for r in records], np.float32)
        # rows flatten row-major (user, item per record): each record's
        # click applies to both of its rows
        self.store.fold_stats(
            plan.rows, np.repeat(clicked, plan.rows.shape[1])
        )

    def _restore_window(self, name: str) -> bool:
        """Re-buffer an un-acked window's records from the source (exact
        replay: the stream is a pure function of (seed, index))."""
        for entry in self.task_manager.open_windows():
            if entry["name"] == name:
                return self.reader.restore_window(
                    name, entry["window_id"], entry["start"],
                    entry["records"], entry["watermark"],
                )
        return False

    def _forfeit(self, wid: int, task) -> None:
        """Last resort for a window that can neither train nor replay
        (non-replayable source): retire the task and close the ledger
        entry as LOST so the queue is not wedged forever."""
        name = task.shard.name
        self.task_manager.report(task.task_id, True, worker_id=wid)
        window_id = self._window_ids.pop(name, None)
        if window_id is not None:
            self.task_manager.forfeit_window(window_id)
            # Lineage drop stamp: the window died mid-train; its partial
            # decomposition finalizes flagged `dropped`.
            events.emit(
                events.WINDOW_SPAN,
                window_id=int(window_id),
                phase="train",
                reason="dropped",
                at_unix_s=round(float(self._clock()), 6),
            )
        self._window_tasks_left.pop(name, None)
        released = self.reader.release_window(name)
        logger.error(
            "window %s forfeited (buffer=%s)", name, released,
        )

    def _window_done(self, name: str) -> None:
        left = self._window_tasks_left.get(name)
        if left is None:
            return
        left -= 1
        if left > 0:
            self._window_tasks_left[name] = left
            return
        del self._window_tasks_left[name]
        # BOTH acknowledgments are consumed (GL-LEDGER): the ledger's
        # release journals the window as done, the reader's frees the
        # buffered records.
        window_id = self._window_ids.pop(name, None)
        acked = (
            self.task_manager.release_window(window_id)
            if window_id is not None else False
        )
        released = self.reader.release_window(name)
        if window_id is not None and not acked:
            logger.warning(
                "window %s (%s) release not acked by the ledger",
                name, window_id,
            )
        if not released:
            logger.warning("window %s was not buffered at release", name)
        self._windows_trained += 1
        self._windows_since_save += 1

    def _maybe_checkpoint(self) -> bool:
        if self._windows_since_save < self.config.checkpoint_every_windows:
            return False
        self._windows_since_save = 0
        if not self.saver.save(self.state, force=True):
            return False   # injected checkpoint.write fault: next cadence
        self.saver.wait_until_finished()
        self._latest_saved = int(self.state.step)
        # Checkpoint lineage stamps, one per covered window, timed by
        # the manifest's own `produced` stamp (the PR 10 freshness
        # reference) so the reload_wait segment is measured from the
        # exact instant the staleness histograms measure from.
        produced = (
            self.saver.produced_meta(self._latest_saved) or {}
        ).get("produced_unix_s")
        if produced is None:
            produced = float(self._clock())
        for window_id in self.lineage.windows_awaiting_checkpoint(
                self._latest_saved):
            events.emit(
                events.WINDOW_SPAN,
                window_id=int(window_id),
                phase="checkpoint",
                reason="produced",
                at_unix_s=round(float(produced), 6),
                step=self._latest_saved,
            )
        # Sharded-store sidecar rides the same cadence: it is the state
        # `rebuild_shard` recovers a handed-off shard's host rows from.
        store_checkpoint.save_sharded_sidecar(
            self._checkpoint_dir, self._latest_saved, self.store
        )
        self._sidecar_steps.append(self._latest_saved)
        if len(self._sidecar_steps) > self.config.keep_max:
            self._sidecar_steps = self._sidecar_steps[
                -self.config.keep_max:
            ]
            store_checkpoint.prune_sidecars(
                self._checkpoint_dir, self._sidecar_steps
            )
        return True

    # ---- elasticity: trainer pool, shard handoff, master restart --------

    def _load_sharded_sidecar(self):
        """Latest sharded sidecar, or None before the first save."""
        for step in reversed(self._sidecar_steps):
            if store_checkpoint.has_sharded_sidecar(
                    self._checkpoint_dir, step):
                return store_checkpoint.load_sharded_sidecar(
                    self._checkpoint_dir, step
                )
        return None

    def _retire_worker(self, worker_id: int) -> None:
        """Pool callback (evict / scale_down): requeue the worker's
        leases, evacuate its shard slices."""
        recovered = self.task_manager.recover_tasks(worker_id)
        moves = self.store.handoff(
            dead_worker=worker_id, sidecar=self._load_sharded_sidecar()
        )
        logger.info(
            "trainer %d retired: %d tasks recovered, %d shards moved",
            worker_id, recovered, len(moves),
        )

    def _admit_worker(self, worker_id: int) -> None:
        """Pool callback (evict relaunch / scale_up): rebalance shards
        toward the joiner."""
        moves = self.store.join(worker_id)
        logger.info(
            "trainer %d admitted: %d shards moved", worker_id, len(moves)
        )

    def _stream_lag(self) -> float:
        online = self.task_manager.online_snapshot() or {}
        return float(online.get("watermark_lag_s", 0.0))

    def kill_worker(self, worker_id: int) -> dict:
        """Chaos helper: a trainer dies mid-run.  Its leases requeue
        (lease recovery), its shard slices hand off to the survivors
        (`store.shard_handoff` fault-covered), and the pool shrinks —
        subsequent ticks drain with the survivors."""
        if not self.pool.drop_worker(worker_id):
            raise ValueError(
                f"cannot kill trainer {worker_id}: not alive, or last one"
            )
        recovered = self.task_manager.recover_tasks(worker_id)
        moves = self.store.handoff(
            dead_worker=worker_id, sidecar=self._load_sharded_sidecar()
        )
        logger.info(
            "trainer %d killed: %d tasks recovered, %d shards handed off",
            worker_id, recovered, len(moves),
        )
        return {"recovered_tasks": recovered, "handoffs": len(moves)}

    def drop_window_buffers(self) -> int:
        """Chaos helper: evict every still-open window's buffered
        records (the amnesia a full master-process loss would inflict)
        so subsequent leases must replay them from the deterministic
        source — the path that proves replayed windows keep their
        original ingest attribution."""
        dropped = 0
        for entry in self.task_manager.open_windows():
            if self.reader.release_window(entry["name"]):
                dropped += 1
        return dropped

    def restart_master(self) -> dict:
        """Chaos helper: the master's brain dies and a replacement
        rebuilds the perpetual queue from the window-ledger journal.
        Unfinished windows re-arm exactly their UNDONE shards (completed
        shards never retrain); nothing is lost because un-acked windows
        replay from the deterministic source on demand.  The replacement
        adopts the predecessor's metrics registry, so the released/lost
        counters read as one continuous job."""
        self.task_manager = TaskManager(
            perpetual=True, clock=self._clock,
            persist_path=self._journal_path,
            metrics_registry=self.task_manager.counters.registry,
        )
        self.master_restarts += 1
        # Per-window bookkeeping is in-memory master state: rebuild it
        # from the restored ledger.  A window whose every shard was done
        # but whose release was lost with the old master releases now.
        self._window_tasks_left = {}
        self._window_ids = {}
        restored = self.task_manager.open_windows()
        for entry in restored:
            total = math.ceil(entry["records"] / entry["per_task"])
            left = total - len(entry["done"])
            self._window_ids[entry["name"]] = entry["window_id"]
            if left > 0:
                self._window_tasks_left[entry["name"]] = left
            else:
                acked = self.task_manager.release_window(
                    entry["window_id"]
                )
                released = self.reader.release_window(entry["name"])
                self._window_ids.pop(entry["name"], None)
                logger.info(
                    "window %s completed under the old master; released "
                    "on restore (ledger=%s buffer=%s)",
                    entry["name"], acked, released,
                )
        logger.info(
            "master restarted (#%d): %d open windows restored",
            self.master_restarts, len(restored),
        )
        return {
            "windows_restored": len(restored),
            "tasks_rearmed": sum(self._window_tasks_left.values()),
        }

    # ---- serve side -------------------------------------------------------

    def predict(self, request):
        """Route one predict through the live fleet (retries/failover per
        the router's policy)."""
        return self.router.predict(request)

    def kill_replica(self, rid: int) -> None:
        """Chaos helper: kill transport AND pod so the next fleet tick
        sees a FAILED replica and relaunches it."""
        client = self._fleet[rid]["client"]
        if client is not None:
            client.killed = True
        pod = self.fleet_manager.snapshot()["replicas"][rid]["pod"]
        self.k8s.emit(pod, PodStatus.FAILED, exit_code=1)

    # ---- introspection ----------------------------------------------------

    def online_snapshot(self) -> dict:
        """The task manager's online progress, merged with the serving
        side's last reloaded step — the `elasticdl top` online line."""
        online = self.task_manager.online_snapshot() or {}
        fleet = self.fleet_manager.snapshot()
        steps = [
            rep.get("model_step", 0)
            for rep in fleet.get("replicas", {}).values()
        ]
        online["last_reload_step"] = max(steps) if steps else 0
        store_stats = self.store.stats()
        online["handoffs"] = store_stats["handoffs"]
        online["pending_handoffs"] = store_stats["pending_handoffs"]
        online["alive_trainers"] = len(self.pool.alive_workers())
        online["master_restarts"] = self.master_restarts
        return online

    def snapshot(self) -> dict:
        slo = self.evaluator.snapshot()
        slo["history"] = self.history.snapshot()
        # stream-lag coverage for `elasticdl slo` (same annotation the
        # master makes for perpetual jobs)
        slo["history"]["stream_lag_samples"] = len(
            self.history.series("master_stream_watermark_lag_seconds")
        )
        return {
            "ticks": self.ticks,
            "online": self.online_snapshot(),
            "stream": self.reader.snapshot(),
            "tasks": self.task_manager.snapshot(),
            "serving_fleet": self.fleet_manager.snapshot(),
            "freshness": self.freshness.snapshot(),
            "lineage": self.lineage.snapshot(),
            "slo": slo,
            "store": self.store.stats(),
            "trainers": {
                "alive": self.pool.alive_workers(),
                "master_restarts": self.master_restarts,
            },
            "policy": (
                self.policy.snapshot() if self.policy is not None else None
            ),
            "serving_policy": (
                self.serving_policy.snapshot()
                if self.serving_policy is not None else None
            ),
            "backpressure": {
                "serving_pressure": self._serving_pressure,
                "polls_skipped": self._polls_skipped,
                "threshold": self.config.backpressure_threshold,
                "stride": self.config.backpressure_stride,
            },
            "windows_trained": self._windows_trained,
            "examples_trained": self._examples_trained,
            "model_step": int(self.state.step),
            "latest_saved_step": self._latest_saved,
            "max_burn": round(self.max_burn, 6),
        }

    def shutdown(self) -> None:
        self.lineage.close()
        for rep in self._fleet.values():
            rep["batcher"].shutdown()
        self.saver.close()
