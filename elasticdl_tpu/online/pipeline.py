"""Online continuous learning: one loop from stream to served model.

The batch system in this repo runs stream -> train -> checkpoint ->
hot-reload as four separately-benched pieces.  `OnlinePipeline` closes
them into one measured loop (docs/ONLINE.md):

    ClickStreamSource -> StreamReader (bounded windows, watermark)
        -> TaskManager(perpetual=True).arm_window  (queue re-arms forever)
        -> Trainer.train_on_batch per leased task
        -> CheckpointSaver every `checkpoint_every_windows` windows
           (keep-last-K sweep + freshness stamp)
        -> ServingFleetManager.tick  (sequenced hot-swaps behind the
           FleetRouter, live traffic keeps flowing)
        -> FreshnessTracker + MetricHistory + SloEvaluator
           (staleness_p99 measures REAL stream-to-serve lag)

Every time-reading collaborator shares ONE injectable clock, and every
decision maker (task manager, fleet manager, SLO evaluator, fault
registry) is already deterministic under a fake clock — so the chaos
variant of `bench.py --online` replays byte-identically across
same-seed runs while a stream stall, a replica kill, and a reload fault
land mid-loop.

Single-process by design: the serving replicas are in-process servicers
behind killable clients (the bench_serving_fleet harness shape,
bench.py), which keeps the full loop runnable in CI seconds.  The
multi-process story reuses the same pieces unchanged — the reader and
task manager already speak the worker lease protocol.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from elasticdl_tpu.common.history import MetricHistory
from elasticdl_tpu.common.k8s_client import FakeK8sClient
from elasticdl_tpu.common.constants import PodStatus
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.resilience import RetryPolicy
from elasticdl_tpu.common.save_utils import CheckpointSaver
from elasticdl_tpu.common.slo import SloEvaluator, shipped_specs
from elasticdl_tpu.data.reader.stream_reader import (
    ClickStreamSource,
    StreamReader,
)
from elasticdl_tpu.master.freshness import FreshnessTracker
from elasticdl_tpu.master.serving_fleet import (
    ServingFleetConfig,
    ServingFleetManager,
)
from elasticdl_tpu.master.task_manager import TaskManager
from elasticdl_tpu.proto.service import FleetRouter, InProcessServingClient

logger = get_logger(__name__)


@dataclass
class OnlineConfig:
    """Shape of one online loop.  Defaults are CI-sized: a few hundred
    records per window, two replicas, a checkpoint every other window."""

    seed: int = 0
    window_records: int = 128
    records_per_task: int = 32
    records_per_poll: int = 64
    max_buffered_windows: int = 64
    checkpoint_every_windows: int = 2
    keep_max: int = 3
    replicas: int = 2
    probe_failures: int = 2
    step_skew_slo: int = 16
    source_users: int = 512
    source_items: int = 128


class _KillableClient:
    """In-process serving client with a kill switch standing in for a
    dead pod (same harness shape as bench_serving_fleet)."""

    def __init__(self, servicer):
        self._inner = InProcessServingClient(servicer)
        self.killed = False

    def predict(self, request, timeout=None):
        if self.killed:
            raise ConnectionError("replica killed")
        return self._inner.predict(request, timeout=timeout)

    def health(self, request, timeout=None):
        if self.killed:
            raise ConnectionError("replica killed")
        return self._inner.health(request, timeout=timeout)


class OnlinePipeline:
    """Builds and drives the whole loop.  `tick()` is one iteration:
    poll the stream, arm sealed windows, train the leased tasks,
    checkpoint on cadence, tick the serving fleet and the SLO watcher.
    Call it forever (the real deployment) or N times (bench/tests)."""

    def __init__(
        self,
        checkpoint_dir: str,
        spec,
        config: Optional[OnlineConfig] = None,
        clock: Callable[[], float] = time.time,
        source=None,
    ):
        import jax

        from elasticdl_tpu.serving.batcher import DynamicBatcher
        from elasticdl_tpu.serving.engine import ServingEngine
        from elasticdl_tpu.serving.reloader import CheckpointReloader
        from elasticdl_tpu.serving.server import ServingServicer
        from elasticdl_tpu.worker.trainer import Trainer

        self.config = cfg = config or OnlineConfig()
        self.spec = spec
        self._clock = clock

        # ---- stream -> windows ------------------------------------------
        self.source = source if source is not None else ClickStreamSource(
            seed=cfg.seed, users=cfg.source_users, items=cfg.source_items,
            records_per_poll=cfg.records_per_poll, clock=clock,
        )
        self.reader = StreamReader(
            self.source, window_records=cfg.window_records,
            max_buffered_windows=cfg.max_buffered_windows, clock=clock,
        )
        self._pending_windows = []          # sealed, not yet armed
        self._window_tasks_left = {}        # window name -> tasks open

        # ---- perpetual task queue ---------------------------------------
        self.task_manager = TaskManager(perpetual=True, clock=clock)

        # ---- trainer -----------------------------------------------------
        self.trainer = Trainer(spec.model, spec.optimizer, spec.loss)
        sample = spec.feed(
            ClickStreamSource(
                seed=cfg.seed, users=cfg.source_users,
                items=cfg.source_items, clock=lambda: 0.0,
            ).poll(2),
            self.reader.metadata,
        )["features"]
        self._sample = np.asarray(sample)
        self.state = self.trainer.init_state(
            jax.random.PRNGKey(cfg.seed), self._sample
        )

        # ---- checkpoints -------------------------------------------------
        self.saver = CheckpointSaver(
            checkpoint_dir, keep_max=cfg.keep_max, async_save=False,
            clock=clock,
        )
        # An initial step-0 checkpoint so the serving fleet has a model
        # before the first window finishes training.
        self.saver.save(self.state, force=True)
        self.saver.wait_until_finished()
        self._latest_saved = int(self.state.step)
        self._windows_since_save = 0
        self._windows_trained = 0
        self._examples_trained = 0
        self._last_loss = float("nan")

        # ---- serving fleet (in-process replicas) ------------------------
        self.k8s = FakeK8sClient()
        self.freshness = FreshnessTracker(
            clock=clock,
            produced_time_fn=lambda step: (
                self.saver.produced_meta(step) or {}
            ).get("produced_unix_s"),
        )
        self.router = FleetRouter(
            retry_policy=RetryPolicy(
                initial_backoff_s=0.001, max_backoff_s=0.01,
                max_elapsed_s=30.0, max_attempts=8,
            ),
            freshness=self.freshness,
        )
        self._fleet = {}
        for rid in range(cfg.replicas):
            engine = ServingEngine.from_checkpoint(
                checkpoint_dir, spec, self._sample, buckets=(2, 8)
            )
            batcher = DynamicBatcher(engine, max_latency_s=0.002)
            reloader = CheckpointReloader(
                engine, checkpoint_dir, poll_interval_s=3600.0
            )
            self._fleet[rid] = {
                "engine": engine,
                "batcher": batcher,
                "reloader": reloader,
                "servicer": ServingServicer(engine, batcher, reloader),
                "client": None,
            }

        def client_factory(rid, _addr):
            self._fleet[rid]["client"] = _KillableClient(
                self._fleet[rid]["servicer"]
            )
            return self._fleet[rid]["client"]

        self.fleet_manager = ServingFleetManager(
            self.k8s,
            ServingFleetConfig(
                replicas=cfg.replicas, interval_s=0.0,
                probe_failures=cfg.probe_failures,
                step_skew_slo=cfg.step_skew_slo,
            ),
            job_name="online",
            client_factory=client_factory,
            reload_fn=lambda rid: self._fleet[rid][
                "reloader"
            ].check_once(),
            pending_step_fn=lambda: self._latest_saved,
            router=self.router,
            clock=clock,
            freshness=self.freshness,
        )
        self.fleet_manager.place()
        self.fleet_manager.tick()   # prime: every replica probed healthy

        # ---- SLO watcher -------------------------------------------------
        # The history samples the stream-lag gauges alongside the
        # freshness/fleet series, so `elasticdl slo` history coverage
        # includes the stream-lag series (docs/OBSERVABILITY.md).
        self.history = MetricHistory(
            registries=[
                self.freshness.metrics_registry,
                self.fleet_manager.metrics_registry,
                self.reader.metrics_registry,
                self.task_manager.counters.registry,
            ],
            clock=clock,
        )
        self.evaluator = SloEvaluator(
            self.history, specs=[shipped_specs()[0]], clock=clock,
        )
        self.max_burn = 0.0
        self.ticks = 0

    # ---- one loop iteration ---------------------------------------------

    def tick(self) -> dict:
        """Poll -> arm -> train -> checkpoint -> serve.  Returns a small
        progress dict for the caller's loop telemetry."""
        polled = self.reader.poll()
        self._arm_pending()
        trained = self._drain_tasks()
        saved = self._maybe_checkpoint()
        self.fleet_manager.tick()
        self.history.tick()
        self.evaluator.tick()
        self.max_burn = max(self.max_burn, self.evaluator.max_burn())
        self.ticks += 1
        return {
            "polled": polled,
            "trained_tasks": trained,
            "checkpointed": saved,
            "model_step": int(self.state.step),
            "loss": self._last_loss,
        }

    def _arm_pending(self) -> None:
        self._pending_windows.extend(self.reader.take_new_windows())
        still_pending = []
        for window in self._pending_windows:
            n = self.task_manager.arm_window(
                window.name, len(window.records),
                self.config.records_per_task,
                watermark_unix_s=window.watermark_unix_s,
                window_id=window.window_id,
            )
            if n is None:
                # injected task.rearm fault: the window stays pending and
                # is re-offered next tick (docs/ROBUSTNESS.md)
                still_pending.append(window)
            else:
                self._window_tasks_left[window.name] = n
        self._pending_windows = still_pending

    def _drain_tasks(self) -> int:
        trained = 0
        while True:
            task = self.task_manager.get(0)
            if task is None:
                return trained
            name = task.shard.name
            try:
                records = list(self.reader.read_records(task))
            except LookupError:
                # The window was dropped past the buffer cap: its data is
                # gone for good, so retire the task (success, 0 records)
                # rather than retry-looping on an unservable shard.
                self.task_manager.report(task.task_id, True, worker_id=0)
                self._window_done(name)
                continue
            batch = self.spec.feed(records, self.reader.metadata)
            self.state, loss = self.trainer.train_on_batch(
                self.state, batch
            )
            self._last_loss = float(loss)
            self._examples_trained += len(records)
            trained += 1
            self.task_manager.report(
                task.task_id, True, worker_id=0, records=len(records),
                model_version=int(self.state.step),
            )
            self._window_done(name)

    def _window_done(self, name: str) -> None:
        left = self._window_tasks_left.get(name)
        if left is None:
            return
        left -= 1
        if left > 0:
            self._window_tasks_left[name] = left
            return
        del self._window_tasks_left[name]
        self.reader.release_window(name)
        self._windows_trained += 1
        self._windows_since_save += 1

    def _maybe_checkpoint(self) -> bool:
        if self._windows_since_save < self.config.checkpoint_every_windows:
            return False
        self._windows_since_save = 0
        if not self.saver.save(self.state, force=True):
            return False   # injected checkpoint.write fault: next cadence
        self.saver.wait_until_finished()
        self._latest_saved = int(self.state.step)
        return True

    # ---- serve side -------------------------------------------------------

    def predict(self, request):
        """Route one predict through the live fleet (retries/failover per
        the router's policy)."""
        return self.router.predict(request)

    def kill_replica(self, rid: int) -> None:
        """Chaos helper: kill transport AND pod so the next fleet tick
        sees a FAILED replica and relaunches it."""
        client = self._fleet[rid]["client"]
        if client is not None:
            client.killed = True
        pod = self.fleet_manager.snapshot()["replicas"][rid]["pod"]
        self.k8s.emit(pod, PodStatus.FAILED, exit_code=1)

    # ---- introspection ----------------------------------------------------

    def online_snapshot(self) -> dict:
        """The task manager's online progress, merged with the serving
        side's last reloaded step — the `elasticdl top` online line."""
        online = self.task_manager.online_snapshot() or {}
        fleet = self.fleet_manager.snapshot()
        steps = [
            rep.get("model_step", 0)
            for rep in fleet.get("replicas", {}).values()
        ]
        online["last_reload_step"] = max(steps) if steps else 0
        return online

    def snapshot(self) -> dict:
        slo = self.evaluator.snapshot()
        slo["history"] = self.history.snapshot()
        # stream-lag coverage for `elasticdl slo` (same annotation the
        # master makes for perpetual jobs)
        slo["history"]["stream_lag_samples"] = len(
            self.history.series("master_stream_watermark_lag_seconds")
        )
        return {
            "ticks": self.ticks,
            "online": self.online_snapshot(),
            "stream": self.reader.snapshot(),
            "tasks": self.task_manager.snapshot(),
            "serving_fleet": self.fleet_manager.snapshot(),
            "freshness": self.freshness.snapshot(),
            "slo": slo,
            "windows_trained": self._windows_trained,
            "examples_trained": self._examples_trained,
            "model_step": int(self.state.step),
            "latest_saved_step": self._latest_saved,
            "max_burn": round(self.max_burn, 6),
        }

    def shutdown(self) -> None:
        for rep in self._fleet.values():
            rep["batcher"].shutdown()
        self.saver.close()
