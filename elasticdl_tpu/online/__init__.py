from elasticdl_tpu.online.pipeline import (  # noqa: F401
    OnlineConfig,
    OnlinePipeline,
)
