"""Declarative SLOs with multi-window burn-rate alerting.

`MetricHistory` (common/history.py) records what happened; this module
judges it.  An `SloSpec` states an objective over one history series
and the evaluator turns windowed evidence into a burn rate — how fast
the error budget is being spent — using the standard multi-window rule:

    bad_ratio(window) = fraction of bad observations in the window
    burn_rate(window) = bad_ratio / (1 - target)

A burn rate of 1.0 spends exactly the budget the target allows; 14x
over a short window means the budget is gone within hours.  The state
machine: `breach` when the fast-window burn crosses `fast_burn` or the
slow-window burn crosses `slow_burn`; recovery back to `ok` only once
the fast-window burn drops under 1.0 (fully inside budget again) —
hysteresis so a breach does not flap while the budget is still being
spent.  `no_data` before any evidence exists.

Three spec kinds cover the shipped SLOs:

- `gauge`: bad sample = windowed gauge sample over `objective`.
- `histogram`: bad observation = windowed bucket-delta observation over
  `objective` (so a past stall ages out of the window — a lifetime p99
  would never recover).
- `ratio`: bad/total counter deltas (e.g. request errors / requests).

Like the policy engine, the evaluator runs on an injectable clock
(`interval_s=0` disables the thread; tests tick by hand), keeps a
clock-free `decisions` list that is byte-comparable across same-seed
runs, and emits the `slo_breach`/`slo_recovered` span-event pair.

The SLO name vocabulary is closed (`SLO_NAMES`, like
`POLICY_ACTIONS`); GL-DRIFT cross-checks it against the
docs/OBSERVABILITY.md SLO table in both directions.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from elasticdl_tpu.common import events
from elasticdl_tpu.common import metrics as metrics_lib
from elasticdl_tpu.common.history import MetricHistory
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)

# ---- closed SLO-name vocabulary (GL-DRIFT checks the doc table) --------

SLO_STALENESS_P99 = "staleness_p99"
SLO_FLEET_SKEW = "fleet_skew"
SLO_PREDICT_AVAILABILITY = "predict_availability"
SLO_PREDICT_SHED_RATIO = "predict_shed_ratio"

SLO_NAMES = frozenset({
    SLO_STALENESS_P99,
    SLO_FLEET_SKEW,
    SLO_PREDICT_AVAILABILITY,
    SLO_PREDICT_SHED_RATIO,
})

STATE_NO_DATA = "no_data"
STATE_OK = "ok"
STATE_BREACH = "breach"
STATES = (STATE_NO_DATA, STATE_OK, STATE_BREACH)

KINDS = ("gauge", "histogram", "ratio")


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """One objective over one history series."""

    name: str             # member of SLO_NAMES
    kind: str             # member of KINDS
    series: str           # gauge/histogram series; ratio: bad counter
    objective: float      # value bound (gauge/histogram); unused: ratio
    target: float = 0.99  # promised good fraction; budget = 1 - target
    total_series: str = ""    # ratio kind: the total counter
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    fast_burn: float = 14.0
    slow_burn: float = 6.0

    def __post_init__(self):
        assert self.name in SLO_NAMES, self.name
        assert self.kind in KINDS, self.kind
        assert 0.0 < self.target < 1.0, self.target
        if self.kind == "ratio":
            assert self.total_series, "ratio kind needs total_series"


def shipped_specs(args=None) -> List[SloSpec]:
    """The SLOs every master evaluates, parameterized by flags
    (docs/OBSERVABILITY.md "Metric history & SLOs")."""
    staleness_s = float(getattr(args, "slo_staleness_p99_s", 60.0) or 60.0)
    skew = int(getattr(args, "serving_step_skew_slo", 0) or 0)
    return [
        SloSpec(
            name=SLO_STALENESS_P99,
            kind="histogram",
            series="master_train_to_serve_staleness_seconds",
            objective=staleness_s,
        ),
        SloSpec(
            name=SLO_FLEET_SKEW,
            kind="gauge",
            series="serving_fleet_model_step_skew_steps",
            objective=float(skew if skew > 0 else 8),
        ),
        SloSpec(
            name=SLO_PREDICT_AVAILABILITY,
            kind="ratio",
            series="rpc_fleet_request_errors_total",
            total_series="rpc_fleet_requests_total",
            objective=0.0,
            target=0.999,
        ),
        # A whole-fleet shed is a request the caller did not get served
        # even though no replica errored — admission control answering
        # for everyone.  Distinct from availability (errors) because the
        # remediation differs: sheds want capacity (the serving policy
        # engine scales on this burn), errors want repair.
        SloSpec(
            name=SLO_PREDICT_SHED_RATIO,
            kind="ratio",
            series="rpc_fleet_sheds_total",
            total_series="rpc_fleet_requests_total",
            objective=0.0,
            target=0.95,
            fast_burn=8.0,
        ),
    ]


class SloEvaluator:
    """Evaluates SloSpecs over a MetricHistory on an injectable-clock
    loop; exports `master_slo_status_info{slo,state}` one-hot gauges."""

    def __init__(
        self,
        history: MetricHistory,
        specs: Optional[Sequence[SloSpec]] = None,
        interval_s: float = 0.0,
        clock: Callable[[], float] = time.time,
        on_breach: Optional[Callable[[dict], None]] = None,
    ):
        self.history = history
        # Incident hook (common/flight.py): called once per new breach
        # decision, OUTSIDE the evaluator lock — the flight recorder's
        # capture walks Master.snapshot(), which re-enters this
        # evaluator's snapshot() and would deadlock under the lock.
        self._on_breach = on_breach
        self.specs = list(specs if specs is not None else shipped_specs())
        self.interval_s = float(interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._state: Dict[str, str] = {
            spec.name: STATE_NO_DATA for spec in self.specs
        }
        self._last: Dict[str, dict] = {}
        self.decisions: List[dict] = []
        self.ticks = 0
        self.metrics_registry = metrics_lib.MetricsRegistry()
        self._status = self.metrics_registry.gauge(
            "master_slo_status_info",
            "One-hot SLO state: 1 on the {slo,state} child matching the "
            "evaluator's current judgment, 0 elsewhere",
            labelnames=("slo", "state"),
        )
        for spec in self.specs:
            self._set_status_locked(spec.name, STATE_NO_DATA)

    # ---- loop (policy-engine style) -------------------------------------

    def start(self) -> bool:
        if self.interval_s <= 0 or self._thread is not None:
            return False
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="slo-evaluator", daemon=True
        )
        self._thread.start()
        return True

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                logger.exception("slo evaluation failed")

    # ---- evaluation -----------------------------------------------------

    def tick(self) -> None:
        with self._lock:
            breaches = self._tick_locked()
        if self._on_breach is not None:
            for decision in breaches:
                try:
                    self._on_breach(dict(decision))
                except Exception:
                    logger.exception("slo on_breach hook failed")

    def _tick_locked(self) -> List[dict]:
        self.ticks += 1
        breaches: List[dict] = []
        for spec in self.specs:
            decision = self._evaluate_locked(spec)
            if decision is not None \
                    and decision.get("event") == events.SLO_BREACH:
                breaches.append(decision)
        return breaches

    def _bad_ratio(self, spec: SloSpec,
                   window_s: float) -> Optional[float]:
        if spec.kind == "gauge":
            return self.history.exceedance_ratio(
                spec.series, spec.objective, window_s
            )
        if spec.kind == "histogram":
            win = self.history.histogram_exceedance(
                spec.series, spec.objective, window_s
            )
            if win is None:
                return None
            bad, total = win
            return bad / total if total else 0.0
        # ratio: no traffic in the window burns nothing
        if self.history.latest(spec.total_series) is None:
            return None
        total = self.history.counter_delta(spec.total_series, window_s)
        if total <= 0:
            return 0.0
        bad = self.history.counter_delta(spec.series, window_s)
        return min(1.0, bad / total)

    def _evaluate_locked(self, spec: SloSpec) -> Optional[dict]:
        budget = max(1e-9, 1.0 - spec.target)
        fast_ratio = self._bad_ratio(spec, spec.fast_window_s)
        slow_ratio = self._bad_ratio(spec, spec.slow_window_s)
        prev = self._state[spec.name]
        if fast_ratio is None:
            state = STATE_NO_DATA if prev == STATE_NO_DATA else prev
            fast_burn = slow_burn = 0.0
        else:
            fast_burn = fast_ratio / budget
            slow_burn = (slow_ratio or 0.0) / budget
            if (fast_burn >= spec.fast_burn
                    or slow_burn >= spec.slow_burn):
                state = STATE_BREACH
            elif prev == STATE_BREACH:
                # hysteresis: recover only once fully inside budget
                state = STATE_OK if fast_burn < 1.0 else STATE_BREACH
            else:
                state = STATE_OK
        evidence = {
            "slo": spec.name,
            "state": state,
            "fast_burn": round(fast_burn, 4),
            "slow_burn": round(slow_burn, 4),
            "fast_window_s": spec.fast_window_s,
            "slow_window_s": spec.slow_window_s,
            "objective": spec.objective,
            "target": spec.target,
        }
        self._last[spec.name] = evidence
        if state == prev:
            return None
        self._state[spec.name] = state
        self._set_status_locked(spec.name, state)
        if state == STATE_BREACH:
            return self._record_locked(events.SLO_BREACH, evidence)
        if prev == STATE_BREACH:
            return self._record_locked(events.SLO_RECOVERED, evidence)
        return None

    def _set_status_locked(self, slo: str, state: str) -> None:
        assert state in STATES, state
        for candidate in STATES:
            self._status.labels(slo=slo, state=candidate).set(
                1.0 if candidate == state else 0.0
            )

    def _record_locked(self, event: str, evidence: dict) -> dict:
        assert event in events.VOCABULARY, event
        decision = dict(evidence)
        decision["event"] = event
        decision["tick"] = self.ticks
        self.decisions.append(decision)
        events.emit(event, **evidence)
        logger.info("slo %s: %s", evidence["slo"], event)
        return decision

    # ---- reads ----------------------------------------------------------

    def state(self, slo: str) -> str:
        with self._lock:
            return self._state[slo]

    def report(self) -> List[dict]:
        """Per-SLO state + burn rates + window evidence, spec order —
        the payload `elasticdl slo` renders."""
        with self._lock:
            out = []
            for spec in self.specs:
                row = self._last.get(spec.name) or {
                    "slo": spec.name,
                    "state": self._state[spec.name],
                    "fast_burn": 0.0,
                    "slow_burn": 0.0,
                    "fast_window_s": spec.fast_window_s,
                    "slow_window_s": spec.slow_window_s,
                    "objective": spec.objective,
                    "target": spec.target,
                }
                out.append(dict(row))
            return out

    def max_burn(self) -> float:
        """Largest fast-window burn rate across SLOs right now (bench)."""
        with self._lock:
            return max(
                (row.get("fast_burn", 0.0) for row in self._last.values()),
                default=0.0,
            )

    def set_on_breach(self, fn: Optional[Callable[[dict], None]]) -> None:
        """Attach (or replace) the breach hook after construction — the
        online pipeline builds its evaluator before any flight recorder
        exists to capture on it."""
        self._on_breach = fn

    def burn_rates(self) -> Dict[str, float]:
        """Per-SLO fast-window burn rates right now — the signal surface
        the serving policy engine reads when it wants to attribute a
        scale decision to one SLO rather than the fleet-wide max."""
        with self._lock:
            return {
                name: row.get("fast_burn", 0.0)
                for name, row in sorted(self._last.items())
            }

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "ticks": self.ticks,
                "states": dict(self._state),
                "slos": [dict(self._last.get(s.name, {"slo": s.name}))
                         for s in self.specs],
                "decisions": list(self.decisions),
            }
