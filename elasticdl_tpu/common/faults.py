"""Deterministic, seeded fault injection for the elastic control plane.

The north-star elasticity claims (BASELINE.md: survive >= 2 preemptions;
ROADMAP.md: recovery time is the headline metric) are only *provable* when
failures happen on a schedule the test controls.  This module is that
schedule: a process-wide registry of named injection points that the
control plane calls `fire()` on, and a seed-driven plan deciding, per
point and per hit index, whether to raise, delay, or drop.

Design constraints:

- **Deterministic trace.**  The plan is a pure function of the seed, and a
  firing is identified by (point, hit_index, action) — never by wall
  clock.  Two runs with the same seed and the same workload therefore emit
  byte-identical `trace_text()` output no matter how threads interleave,
  as long as every scheduled fault actually fires (`all_fired()`), which
  the chaos soak asserts before comparing traces.
- **Zero cost when disabled.**  Production code calls the module-level
  `fire(point)`, which is a single attribute read + None check when no
  registry is installed.
- **No dependencies.**  Importable from anywhere (proto glue, k8s client,
  Orbax wrapper) without cycles.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple

# Canonical injection points.  Adding one is cheap; each names the
# boundary it guards, not the module that hosts it.
POINT_RPC_GET_TASK = "rpc.get_task"
POINT_RPC_REPORT = "rpc.report"
POINT_RENDEZVOUS_JOIN = "rendezvous.join"
POINT_CHECKPOINT_WRITE = "checkpoint.write"
POINT_WORKER_HEARTBEAT = "worker.heartbeat"
POINT_POD_WATCH = "pod.watch"
POINT_RPC_PREDICT = "rpc.predict"
POINT_SERVING_RELOAD = "serving.reload"
# Scaling/actuation boundaries (master/policy.py + pod_manager scale
# paths): apiserver errors mid-scale are part of the chaos surface.
POINT_POD_CREATE = "pod.create"
POINT_POD_DELETE = "pod.delete"
POINT_POLICY_TICK = "policy.tick"
# Serving-fleet boundaries (master/serving_fleet.py + the Health RPC):
# a probe that errors, an apiserver that fails the replica replacement,
# and a rolling-reload step that dies mid-swap are each one scheduled
# fault away.
POINT_RPC_HEALTH_PROBE = "rpc.health_probe"
POINT_SERVING_REPLICA_KILL = "serving.replica_kill"
POINT_FLEET_RELOAD_STEP = "fleet.reload_step"
# Online continuous-learning boundaries (data/reader/stream_reader.py +
# master/task_manager.py perpetual mode): a stream poll that stalls and
# a window re-arm the queue never sees are the two ways fresh data stops
# reaching training without anything crashing.
POINT_STREAM_POLL = "stream.poll"
POINT_TASK_REARM = "task.rearm"
# Sharded-store boundary (store/sharding.py): the master reassigns a dead
# or evicted worker's row range to a successor; a handoff that errors
# mid-move leaves the shard orphaned until the next retry — exactly the
# window the chaos soak aims at.
POINT_STORE_SHARD_HANDOFF = "store.shard_handoff"
# Serving control-loop boundaries (traffic/generator.py +
# master/serving_fleet.py scale paths): a traffic tick that dies must
# not corrupt the offered-request schedule, and an apiserver error
# mid-scale must abort the whole action atomically — the serving policy
# engine retries it next tick with its streaks frozen.
POINT_TRAFFIC_TICK = "traffic.tick"
POINT_FLEET_SCALE = "fleet.scale"

POINTS = (
    POINT_RPC_GET_TASK,
    POINT_RPC_REPORT,
    POINT_RENDEZVOUS_JOIN,
    POINT_CHECKPOINT_WRITE,
    POINT_WORKER_HEARTBEAT,
    POINT_POD_WATCH,
    POINT_RPC_PREDICT,
    POINT_SERVING_RELOAD,
    POINT_POD_CREATE,
    POINT_POD_DELETE,
    POINT_POLICY_TICK,
    POINT_RPC_HEALTH_PROBE,
    POINT_SERVING_REPLICA_KILL,
    POINT_FLEET_RELOAD_STEP,
    POINT_STREAM_POLL,
    POINT_TASK_REARM,
    POINT_STORE_SHARD_HANDOFF,
    POINT_TRAFFIC_TICK,
    POINT_FLEET_SCALE,
)

ACTIONS = ("raise", "delay", "drop")

# Registry-backed injection counters (common/metrics.py): the plan /
# firing bookkeeping below stays the deterministic-trace source of truth
# (trace_text), while these series are the cluster-wide observability
# surface (/metrics, Master.snapshot, `elasticdl top`).
from elasticdl_tpu.common import metrics as _metrics  # noqa: E402

_hits_counter = _metrics.default_registry().counter(
    "faults_point_hits_total",
    "fire() calls per injection point (plan scheduled or not)",
    labelnames=("point",),
)
_injected_counter = _metrics.default_registry().counter(
    "faults_injected_total",
    "scheduled faults actually executed, by action",
    labelnames=("action",),
)

# Env wire format for subprocess workers (ProcessK8sClient pods): the
# parent serializes its registry's plan; `configure_from_env()` rebuilds
# an identical one in the child.
ENV_SCHEDULE = "ELASTICDL_FAULT_SCHEDULE"
ENV_SEED = "ELASTICDL_FAULT_SEED"


class InjectedFault(Exception):
    """An injected failure (the `raise` action).  Classified as retryable
    by resilience.is_retryable_error — injected faults model transient
    infrastructure errors."""


class DroppedRequest(InjectedFault):
    """An injected drop: the request/event is lost in flight.  At RPC
    sites this surfaces as an error (the caller cannot tell a dropped
    request from a failed one); at event sites the caller swallows it and
    skips delivery."""


@dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault: at the `at`-th hit of `point`, do `action`."""

    point: str
    at: int
    action: str  # "raise" | "delay" | "drop"
    delay_s: float = 0.0

    def key(self) -> Tuple[str, int]:
        return (self.point, self.at)

    def describe(self) -> str:
        extra = f" delay={self.delay_s:.3f}s" if self.action == "delay" else ""
        return f"{self.point}#{self.at} {self.action}{extra}"


class FaultRegistry:
    """Seeded fault plan + thread-safe hit counting + canonical trace."""

    def __init__(
        self,
        schedule: Iterable[FaultSpec] = (),
        seed: Optional[int] = None,
    ):
        self.seed = seed
        self._lock = threading.Lock()
        self._plan: Dict[str, Dict[int, FaultSpec]] = {}
        for spec in schedule:
            if spec.action not in ACTIONS:
                raise ValueError(f"unknown fault action {spec.action!r}")
            self._plan.setdefault(spec.point, {})[spec.at] = spec
        self._hits: Dict[str, int] = {}
        self._fired: Dict[Tuple[str, int], FaultSpec] = {}
        self._notes: Dict[str, List[str]] = {}

    # ---- construction ---------------------------------------------------

    @classmethod
    def from_seed(
        cls,
        seed: int,
        points: Iterable[str] = POINTS,
        faults_per_point: int = 2,
        max_hit: int = 8,
        actions: Iterable[str] = ACTIONS,
    ) -> "FaultRegistry":
        """Derive a schedule purely from `seed`: for each point (in the
        given, fixed order) pick `faults_per_point` distinct hit indices
        below `max_hit` and an action for each.  Same seed => same plan,
        on any host."""
        import random

        rng = random.Random(seed)
        actions = tuple(actions)
        schedule = []
        for point in points:
            for at in sorted(rng.sample(range(max_hit), faults_per_point)):
                action = rng.choice(actions)
                delay = (
                    round(rng.uniform(0.01, 0.05), 3)
                    if action == "delay"
                    else 0.0
                )
                schedule.append(FaultSpec(point, at, action, delay))
        return cls(schedule, seed=seed)

    # ---- the hot path ---------------------------------------------------

    def fire(self, point: str) -> None:
        """Count one hit of `point` and execute any fault scheduled at
        this hit index.  Raises InjectedFault/DroppedRequest for the
        raise/drop actions; sleeps for delay; no-op otherwise."""
        with self._lock:
            hit = self._hits.get(point, 0)
            self._hits[point] = hit + 1
            spec = self._plan.get(point, {}).get(hit)
            if spec is not None:
                self._fired[spec.key()] = spec
        _hits_counter.labels(point=point).inc()
        if spec is None:
            return
        _injected_counter.labels(action=spec.action).inc()
        if spec.action == "delay":
            time.sleep(spec.delay_s)
            return
        if spec.action == "drop":
            raise DroppedRequest(f"injected drop at {spec.describe()}")
        raise InjectedFault(f"injected failure at {spec.describe()}")

    def note(self, key: str, detail: str = "") -> None:
        """Record a test-driven chaos event (a kill, a corruption) in the
        trace.  Keep `detail` free of run-variant data (clocks, pids) —
        notes are part of the byte-compared trace."""
        with self._lock:
            self._notes.setdefault(key, []).append(detail)

    # ---- introspection --------------------------------------------------

    def hits(self, point: str) -> int:
        with self._lock:
            return self._hits.get(point, 0)

    def all_fired(self) -> bool:
        """True when every scheduled fault has fired (the workload drove
        each point past its highest scheduled hit index)."""
        with self._lock:
            planned = sum(len(v) for v in self._plan.values())
            return len(self._fired) == planned

    def unfired(self) -> List[str]:
        with self._lock:
            return sorted(
                spec.describe()
                for by_hit in self._plan.values()
                for spec in by_hit.values()
                if spec.key() not in self._fired
            )

    def stats(self) -> dict:
        with self._lock:
            by_action: Dict[str, int] = {}
            for spec in self._fired.values():
                by_action[spec.action] = by_action.get(spec.action, 0) + 1
            return {
                "planned": sum(len(v) for v in self._plan.values()),
                "injected": len(self._fired),
                "by_action": by_action,
                "hits": dict(sorted(self._hits.items())),
                "notes": sum(len(v) for v in self._notes.values()),
            }

    def trace_text(self) -> str:
        """Canonical fault trace: plan, firings, and notes in a fixed
        sort order with no timestamps — byte-identical across same-seed
        runs that fired the full plan and issued the same notes."""
        with self._lock:
            lines = [f"fault-trace v1 seed={self.seed}"]
            plan = sorted(
                (spec for by_hit in self._plan.values()
                 for spec in by_hit.values()),
                key=lambda s: (s.point, s.at),
            )
            for spec in plan:
                lines.append(f"plan {spec.describe()}")
            for key in sorted(self._fired):
                lines.append(f"fired {self._fired[key].describe()}")
            for key in sorted(self._notes):
                for i, detail in enumerate(self._notes[key]):
                    suffix = f" {detail}" if detail else ""
                    lines.append(f"note {key}#{i}{suffix}")
        return "\n".join(lines) + "\n"

    # ---- (de)serialization ---------------------------------------------

    def schedule_json(self) -> str:
        with self._lock:
            specs = sorted(
                (spec for by_hit in self._plan.values()
                 for spec in by_hit.values()),
                key=lambda s: (s.point, s.at),
            )
            return json.dumps(
                [
                    {
                        "point": s.point,
                        "at": s.at,
                        "action": s.action,
                        "delay_s": s.delay_s,
                    }
                    for s in specs
                ]
            )

    @classmethod
    def from_schedule_json(
        cls, text: str, seed: Optional[int] = None
    ) -> "FaultRegistry":
        schedule = [
            FaultSpec(
                point=str(e["point"]),
                at=int(e["at"]),
                action=str(e["action"]),
                delay_s=float(e.get("delay_s", 0.0)),
            )
            for e in json.loads(text)
        ]
        return cls(schedule, seed=seed)

    def env(self) -> Dict[str, str]:
        """Env vars that reproduce this registry in a subprocess worker
        (pair with configure_from_env)."""
        out = {ENV_SCHEDULE: self.schedule_json()}
        if self.seed is not None:
            out[ENV_SEED] = str(self.seed)
        return out


# ---- process-wide singleton ---------------------------------------------

_active: Optional[FaultRegistry] = None


def install(registry: FaultRegistry) -> FaultRegistry:
    global _active
    _active = registry
    return registry


def uninstall() -> None:
    global _active
    _active = None


def get_registry() -> Optional[FaultRegistry]:
    return _active


def fire(point: str) -> None:
    """Module-level hot path: no-op unless a registry is installed."""
    registry = _active
    if registry is not None:
        registry.fire(point)


def note(key: str, detail: str = "") -> None:
    registry = _active
    if registry is not None:
        registry.note(key, detail)


def configure_from_env(environ=None) -> Optional[FaultRegistry]:
    """Install a registry described by the environment (subprocess
    workers of a chaos run).  ELASTICDL_FAULT_SCHEDULE carries an explicit
    plan; ELASTICDL_FAULT_SEED alone derives the default seeded plan.
    Returns the installed registry, or None when neither is set."""
    environ = os.environ if environ is None else environ
    schedule = environ.get(ENV_SCHEDULE, "")
    seed_text = environ.get(ENV_SEED, "")
    seed = int(seed_text) if seed_text else None
    if schedule:
        return install(FaultRegistry.from_schedule_json(schedule, seed=seed))
    if seed is not None:
        return install(FaultRegistry.from_seed(seed))
    return None


def stats() -> dict:
    registry = _active
    return registry.stats() if registry is not None else {}
