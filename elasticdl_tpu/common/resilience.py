"""One retry policy for the whole control plane.

Before this module, every RPC call site hand-rolled its own loop: the
SPMD dispatch loop slept a fixed interval forever, the channel wait was a
bare 60s `channel_ready_future`, the pod manager retried a delete exactly
once.  This module replaces all of them with a single `RetryPolicy`
(exponential backoff + full jitter, per-attempt deadline, max-elapsed
budget, pluggable retryable classification, giving-up hook) and a gRPC
client interceptor that applies it uniformly to every stub method.

Budget exhaustion is a first-class outcome: `RetryBudgetExhausted` is
raised (never retried), and workers translate it into
`RETRY_EXHAUSTED_EXIT_CODE` so the pod manager restarts them through the
normal relaunch-budget path instead of leaving a zombie spinning on a
dead master.
"""

from __future__ import annotations

import collections
import logging
import os
import random
import time
from typing import Callable, Optional

from elasticdl_tpu.common import faults, metrics

logger = logging.getLogger(__name__)

try:  # the container always has grpc, but keep the module importable
    import grpc
except Exception:  # pragma: no cover
    grpc = None

# Distinct from the intentional-restart codes (43 wedge, 44 topology):
# exhausting a retry budget is a real failure and must be charged against
# the pod's relaunch budget, not relaunched for free.
RETRY_EXHAUSTED_EXIT_CODE = 45

# Env knobs (see docs/ROBUSTNESS.md); CLI flags in common/args.py override.
ENV_MAX_ELAPSED_S = "ELASTICDL_RPC_MAX_ELAPSED_S"
ENV_INITIAL_BACKOFF_S = "ELASTICDL_RPC_INITIAL_BACKOFF_S"
ENV_MAX_BACKOFF_S = "ELASTICDL_RPC_MAX_BACKOFF_S"
ENV_ATTEMPT_TIMEOUT_S = "ELASTICDL_RPC_ATTEMPT_TIMEOUT_S"

_RETRYABLE_GRPC_CODES = None


class RetryBudgetExhausted(Exception):
    """A call gave up: every attempt failed and the elapsed/attempt budget
    ran out.  Carries the last underlying error as __cause__."""

    def __init__(self, description: str, attempts: int, elapsed_s: float,
                 last_error: Optional[BaseException] = None):
        self.description = description
        self.attempts = attempts
        self.elapsed_s = elapsed_s
        self.last_error = last_error
        super().__init__(
            f"{description or 'call'}: gave up after {attempts} attempts "
            f"({elapsed_s:.1f}s elapsed): {last_error!r}"
        )


def _retryable_grpc_codes():
    global _RETRYABLE_GRPC_CODES
    if _RETRYABLE_GRPC_CODES is None and grpc is not None:
        _RETRYABLE_GRPC_CODES = frozenset({
            grpc.StatusCode.UNAVAILABLE,
            grpc.StatusCode.DEADLINE_EXCEEDED,
            grpc.StatusCode.RESOURCE_EXHAUSTED,
            grpc.StatusCode.ABORTED,
            grpc.StatusCode.UNKNOWN,
        })
    return _RETRYABLE_GRPC_CODES or frozenset()


def is_retryable_error(exc: BaseException) -> bool:
    """Default classification: transient infrastructure errors retry,
    application errors and exhausted budgets do not."""
    if isinstance(exc, RetryBudgetExhausted):
        return False
    if isinstance(exc, faults.InjectedFault):
        return True
    if isinstance(exc, ConnectionError):
        return True
    if grpc is not None:
        if isinstance(exc, grpc.FutureTimeoutError):
            return True
        if isinstance(exc, grpc.RpcError):
            try:
                code = exc.code()
            except Exception:
                return True  # malformed RpcError: assume transient
            return code in _retryable_grpc_codes()
    return False


# ---- process-wide counters (exported via master/worker snapshots) --------
# The unified registry (common/metrics.py) IS the storage: /metrics,
# Master.snapshot(), and these stats() helpers all read the same series.

_retry_counter = metrics.default_registry().counter(
    "rpc_client_retries_total",
    "RPC attempts retried under the shared policy, by call description",
    labelnames=("call",),
)
_giveup_counter = metrics.default_registry().counter(
    "rpc_client_giveups_total",
    "RPC calls that exhausted their retry budget, by call description",
    labelnames=("call",),
)


def _record_retry(description: str) -> None:
    _retry_counter.labels(call=description or "?").inc()


def _record_giveup(description: str) -> None:
    _giveup_counter.labels(call=description or "?").inc()


def _by_call(counter) -> dict:
    return {
        key[0]: int(value)
        for key, value in sorted(counter.child_values().items())
        if value
    }


def stats() -> dict:
    return {
        "retries": int(_retry_counter.value()),
        "giveups": int(_giveup_counter.value()),
        "retries_by_call": _by_call(_retry_counter),
        "giveups_by_call": _by_call(_giveup_counter),
    }


def reset_stats() -> None:
    _retry_counter.reset()
    _giveup_counter.reset()


class RetryPolicy:
    """Exponential backoff with full jitter, bounded by wall-clock budget
    and/or attempt count.

    `call(fn)` retries `fn()` while `retryable(exc)` holds and budget
    remains.  Only `Exception` is caught — BaseException control flow
    (PreemptedError, KeyboardInterrupt, SystemExit) always propagates.
    """

    def __init__(
        self,
        initial_backoff_s: float = 0.1,
        max_backoff_s: float = 5.0,
        multiplier: float = 2.0,
        attempt_timeout_s: Optional[float] = None,
        max_elapsed_s: Optional[float] = 60.0,
        max_attempts: int = 0,  # 0 = unbounded by count
        retryable: Callable[[BaseException], bool] = is_retryable_error,
        on_give_up: Optional[Callable[..., None]] = None,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.initial_backoff_s = initial_backoff_s
        self.max_backoff_s = max_backoff_s
        self.multiplier = multiplier
        self.attempt_timeout_s = attempt_timeout_s
        self.max_elapsed_s = max_elapsed_s
        self.max_attempts = max_attempts
        self.retryable = retryable
        self.on_give_up = on_give_up
        self._rng = rng if rng is not None else random.Random()
        self._sleep = sleep
        self._clock = clock

    def backoff_s(self, attempt: int) -> float:
        """Full jitter: uniform in [0, min(cap, initial * mult^attempt)]."""
        ceiling = min(
            self.max_backoff_s,
            self.initial_backoff_s * (self.multiplier ** attempt),
        )
        return self._rng.uniform(0.0, ceiling)

    def with_overrides(self, **kw) -> "RetryPolicy":
        fields = dict(
            initial_backoff_s=self.initial_backoff_s,
            max_backoff_s=self.max_backoff_s,
            multiplier=self.multiplier,
            attempt_timeout_s=self.attempt_timeout_s,
            max_elapsed_s=self.max_elapsed_s,
            max_attempts=self.max_attempts,
            retryable=self.retryable,
            on_give_up=self.on_give_up,
        )
        fields.update(kw)
        return RetryPolicy(
            sleep=self._sleep, clock=self._clock, rng=self._rng, **fields
        )

    def call(self, fn: Callable[[], object], description: str = ""):
        start = self._clock()
        attempt = 0
        while True:
            try:
                return fn()
            except Exception as exc:
                if not self.retryable(exc):
                    raise
                attempt += 1
                elapsed = self._clock() - start
                delay = self.backoff_s(attempt - 1)
                out_of_attempts = (
                    self.max_attempts > 0 and attempt >= self.max_attempts
                )
                out_of_time = (
                    self.max_elapsed_s is not None
                    and elapsed + delay >= self.max_elapsed_s
                )
                if out_of_attempts or out_of_time:
                    _record_giveup(description)
                    if self.on_give_up is not None:
                        try:
                            self.on_give_up(description, attempt, elapsed, exc)
                        except Exception:
                            logger.exception("on_give_up hook failed")
                    raise RetryBudgetExhausted(
                        description, attempt, elapsed, exc
                    ) from exc
                _record_retry(description)
                logger.warning(
                    "%s failed (attempt %d, %.1fs elapsed): %r; "
                    "retrying in %.2fs",
                    description or "call", attempt, elapsed, exc, delay,
                )
                self._sleep(delay)


def default_policy(**overrides) -> RetryPolicy:
    """A policy with env-tunable defaults (docs/ROBUSTNESS.md)."""
    def _env_f(name, default):
        raw = os.environ.get(name, "")
        try:
            return float(raw) if raw else default
        except ValueError:
            return default

    kw = dict(
        initial_backoff_s=_env_f(ENV_INITIAL_BACKOFF_S, 0.1),
        max_backoff_s=_env_f(ENV_MAX_BACKOFF_S, 5.0),
        max_elapsed_s=_env_f(ENV_MAX_ELAPSED_S, 120.0),
        attempt_timeout_s=_env_f(ENV_ATTEMPT_TIMEOUT_S, 20.0),
    )
    kw.update(overrides)
    return RetryPolicy(**kw)


def wait_for_channel_ready(channel, policy: RetryPolicy,
                           description: str = "channel_ready") -> None:
    """Replace the bare `channel_ready_future(...).result(timeout=60)`:
    per-attempt timeout + policy budget, RetryBudgetExhausted on a master
    that never comes up."""
    attempt_timeout = policy.attempt_timeout_s or 5.0

    def _wait():
        grpc.channel_ready_future(channel).result(timeout=attempt_timeout)

    policy.call(_wait, description=description)


# ---- gRPC client interceptor ---------------------------------------------

if grpc is not None:

    class _ClientCallDetails(
        collections.namedtuple(
            "_ClientCallDetails",
            ("method", "timeout", "metadata", "credentials",
             "wait_for_ready", "compression"),
        ),
        grpc.ClientCallDetails,
    ):
        pass

    class RetryingClientInterceptor(grpc.UnaryUnaryClientInterceptor):
        """Applies a RetryPolicy to every unary-unary call on a channel,
        and fires the method's fault-injection point on each attempt so
        chaos runs exercise the real network stub path too."""

        def __init__(self, policy: RetryPolicy,
                     fault_points: Optional[dict] = None):
            self._policy = policy
            # method path -> faults.POINT_*; late import avoids a cycle
            self._fault_points = dict(fault_points or {})

        def intercept_unary_unary(self, continuation, client_call_details,
                                  request):
            method = client_call_details.method
            point = self._fault_points.get(method)
            details = client_call_details
            if self._policy.attempt_timeout_s is not None:
                details = _ClientCallDetails(
                    method=client_call_details.method,
                    timeout=self._policy.attempt_timeout_s,
                    metadata=getattr(client_call_details, "metadata", None),
                    credentials=getattr(
                        client_call_details, "credentials", None),
                    wait_for_ready=getattr(
                        client_call_details, "wait_for_ready", None),
                    compression=getattr(
                        client_call_details, "compression", None),
                )

            def _attempt():
                if point is not None:
                    faults.fire(point)
                outcome = continuation(details, request)
                outcome.result()  # materialize so errors hit the policy
                return outcome

            return self._policy.call(_attempt, description=str(method))

else:  # pragma: no cover

    class RetryingClientInterceptor:  # type: ignore[no-redef]
        def __init__(self, *a, **kw):
            raise RuntimeError("grpcio is not available")
