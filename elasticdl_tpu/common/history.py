"""Metric history: ring-buffered samples of metric registries.

Every telemetry surface so far (/metrics, /varz, Master.snapshot) is a
point-in-time read — nothing can answer "what was the p99 over the last
five minutes" or "how fast is this counter burning".  `MetricHistory`
closes that gap: it samples a set of `MetricsRegistry` objects on a
policy-engine-style loop (injectable clock, `interval_s=0` disables the
thread so tests tick by hand) and keeps a fixed-capacity ring buffer of
(timestamp, value) points per series.

Three read surfaces feed the SLO layer (common/slo.py):

- **Gauge series** — the raw windowed points plus an exceedance ratio
  (fraction of samples over a bound).
- **Counters** — windowed deltas/rates that survive process restarts:
  a sample lower than its predecessor is treated as a counter reset and
  contributes its full post-reset value, the standard increase() rule.
- **Histograms** — per-bucket cumulative counts are sampled alongside
  the flat `_p50`/`_p99` quantile series, so windowed quantiles and
  windowed exceedance ratios come from bucket *deltas* (what happened
  in the window), not lifetime aggregates that never recover.

Thread-safety: `tick()` mutates the ring under `self._lock`; reads copy
under the same lock.  The sampled registries use their own locks, so a
concurrent /metrics scrape and a history sample never tear each other.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

from elasticdl_tpu.common import metrics as metrics_lib
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.profiler import LatencyHistogram

logger = get_logger(__name__)


class MetricHistory:
    """Fixed-capacity ring-buffer recorder over metric registries."""

    def __init__(
        self,
        registries: Sequence[object] = (),
        capacity: int = 512,
        interval_s: float = 0.0,
        clock: Callable[[], float] = time.time,
    ):
        self.capacity = max(2, int(capacity))
        self.interval_s = float(interval_s)
        self._clock = clock
        self._registries = list(registries)
        self._lock = threading.Lock()
        # series key -> ring of (ts, value)
        self._series: Dict[str, Deque[Tuple[float, float]]] = {}
        # histogram series key -> (uppers, ring of (ts, cumulative counts))
        self._buckets: Dict[
            str, Tuple[List[float], Deque[Tuple[float, List[int]]]]
        ] = {}
        self._samples_total = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- wiring ---------------------------------------------------------

    def add_registry(self, registry) -> None:
        with self._lock:
            if registry not in self._registries:
                self._registries.append(registry)

    # ---- sampling loop (policy-engine style) ----------------------------

    def start(self) -> bool:
        """Background sampling; False when interval_s <= 0 (tests tick
        by hand) or already started."""
        if self.interval_s <= 0 or self._thread is not None:
            return False
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="metric-history", daemon=True
        )
        self._thread.start()
        return True

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                logger.exception("metric-history sample failed")

    def tick(self) -> None:
        """Take one sample of every registry now."""
        now = float(self._clock())
        with self._lock:
            registries = list(self._registries)
        scalars: Dict[str, float] = {}
        hists: List[Tuple[str, List[float], List[int]]] = []
        for registry in registries:
            scalars.update(registry.snapshot())
            for fam in registry.families():
                if not isinstance(fam, metrics_lib._HistogramFamily):
                    continue
                for key, child in fam.child_items():
                    labelpairs = tuple(zip(fam.labelnames, key))
                    series = metrics_lib._series_key(fam.name, labelpairs)
                    uppers, counts, _total, _sum = child.bucket_snapshot()
                    hists.append((series, uppers, counts))
        with self._lock:
            self._samples_total += 1
            for name, value in scalars.items():
                ring = self._series.get(name)
                if ring is None:
                    ring = self._series[name] = deque(maxlen=self.capacity)
                ring.append((now, float(value)))
            for name, uppers, counts in hists:
                entry = self._buckets.get(name)
                if entry is None or entry[0] != uppers:
                    entry = self._buckets[name] = (
                        uppers, deque(maxlen=self.capacity)
                    )
                entry[1].append((now, counts))

    # ---- reads ----------------------------------------------------------

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def series(self, name: str) -> List[Tuple[float, float]]:
        with self._lock:
            ring = self._series.get(name)
            return list(ring) if ring else []

    def latest(self, name: str) -> Optional[float]:
        with self._lock:
            ring = self._series.get(name)
            return ring[-1][1] if ring else None

    def window(self, name: str,
               window_s: float) -> List[Tuple[float, float]]:
        """Points within the trailing window (inclusive cutoff)."""
        cutoff = float(self._clock()) - float(window_s)
        return [(ts, v) for ts, v in self.series(name) if ts >= cutoff]

    def counter_delta(self, name: str, window_s: float) -> float:
        """Reset-aware increase over the window: a sample lower than its
        predecessor means the counter restarted, so its full value is
        the increment (a fresh sampler sees no phantom delta either —
        one point yields 0)."""
        points = self.window(name, window_s)
        delta = 0.0
        for (_, prev), (_, cur) in zip(points, points[1:]):
            delta += cur - prev if cur >= prev else cur
        return delta

    def rate(self, name: str, window_s: float) -> float:
        """counter_delta / elapsed-sample-span, per second."""
        points = self.window(name, window_s)
        if len(points) < 2:
            return 0.0
        span = points[-1][0] - points[0][0]
        if span <= 0:
            return 0.0
        return self.counter_delta(name, window_s) / span

    def exceedance_ratio(self, name: str, bound: float,
                         window_s: float) -> Optional[float]:
        """Fraction of windowed gauge samples strictly over `bound`;
        None when the window holds no samples."""
        points = self.window(name, window_s)
        if not points:
            return None
        bad = sum(1 for _, v in points if v > bound)
        return bad / len(points)

    # ---- histogram reads ------------------------------------------------

    def histogram_window(
        self, name: str, window_s: float,
    ) -> Optional[Tuple[List[float], List[int], int]]:
        """(uppers, windowed per-bucket counts, total) from cumulative
        bucket deltas over the window, reset-aware like counter_delta.
        None when fewer than one bucket sample exists in the window."""
        cutoff = float(self._clock()) - float(window_s)
        with self._lock:
            entry = self._buckets.get(name)
            if entry is None:
                return None
            uppers, ring = entry[0], [
                (ts, counts) for ts, counts in entry[1] if ts >= cutoff
            ]
        if not ring:
            return None
        deltas = [0] * len(uppers)
        for (_, prev), (_, cur) in zip(ring, ring[1:]):
            reset = any(c < p for p, c in zip(prev, cur))
            for i, c in enumerate(cur):
                deltas[i] += c if reset else c - prev[i]
        return uppers, deltas, sum(deltas)

    def histogram_quantile(self, name: str, q: float,
                           window_s: float) -> Optional[float]:
        """Bounded-error quantile of the observations made *inside* the
        window (None without data) — unlike the flat `_p99` series,
        which is a lifetime aggregate."""
        win = self.histogram_window(name, window_s)
        if win is None or win[2] == 0:
            return None
        uppers, counts, total = win
        return LatencyHistogram._quantile_from(uppers, counts, total, q)

    def histogram_exceedance(
        self, name: str, bound: float, window_s: float,
    ) -> Optional[Tuple[int, int]]:
        """(observations possibly over `bound`, total observations) in
        the window.  A bucket counts as bad when its upper edge exceeds
        the bound — conservative by at most one log bucket."""
        win = self.histogram_window(name, window_s)
        if win is None:
            return None
        uppers, counts, total = win
        bad = sum(c for u, c in zip(uppers, counts) if u > bound)
        return bad, total

    # ---- introspection --------------------------------------------------

    def snapshot(self) -> dict:
        """Clock-free health summary for Master.snapshot()/varz."""
        with self._lock:
            return {
                "series": len(self._series),
                "histograms": len(self._buckets),
                "samples": self._samples_total,
                "capacity": self.capacity,
                "interval_s": self.interval_s,
            }
