"""Model-zoo contract loading.

Parity: reference python/common/model_handler.py + model_utils.py
(SURVEY.md C14).  The zoo contract keeps the reference's function names so
model definitions port by re-implementing bodies in Flax/Optax:

    custom_model()            -> flax.linen Module (predictions = apply())
    loss(labels, predictions) -> scalar jnp loss
    optimizer(lr=...)         -> optax.GradientTransformation
    feed(records, metadata)   -> batch dict {"features":..., "labels":...}
    eval_metrics_fn()         -> {name: fn(labels, predictions) -> scalar}
    custom_data_reader(**kw)  -> AbstractDataReader (optional)
    callbacks()               -> list (optional)
    feed_bulk(buffer, sizes, metadata) -> batch dict (optional; vectorized
                                 parse of a contiguous uint8 payload
                                 buffer + int64 per-record sizes — the
                                 fast path for fixed-width records)
    feed_bulk_compact(buffer, sizes, metadata) -> batch dict (optional;
                                 feed_bulk in the zoo's compact device
                                 wire format — elasticdl_tpu.data.wire —
                                 selected by --compact_wire; the model
                                 must accept the compact dtypes)
    feed_bulk_dedup(buffer, sizes, metadata) -> batch dict (optional;
                                 feed_bulk in the dedup'd device wire
                                 format — ids hashed host-side and
                                 shipped as frequency-ranked uniques +
                                 1-byte inverse (wire.pack_rows_dedup);
                                 selected by --wire_format=dedup; the
                                 model must consume prehashed rows)
    param_sharding(path,leaf) -> PartitionSpec | None (optional; TPU-native
                                 extension for sharded embeddings / TP)

The reference's ModelHandler also rewrote `elasticdl.Embedding` <->
`keras.Embedding` depending on distribution strategy; in the TPU design
DistributedEmbedding is mesh-sharded transparently, so export needs no
layer rewrite — see layers/embedding.py.
"""

from __future__ import annotations

import ast
import importlib
import inspect
import os
import sys
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)


@dataclass
class ModelSpec:
    model: Any
    loss: Callable
    optimizer: Any
    feed: Callable
    feed_bulk: Optional[Callable] = None
    feed_bulk_compact: Optional[Callable] = None
    feed_bulk_dedup: Optional[Callable] = None
    eval_metrics: Dict[str, Callable] = field(default_factory=dict)
    custom_data_reader: Optional[Callable] = None
    callbacks: list = field(default_factory=list)
    param_sharding: Optional[Callable] = None
    # reference C18 surface: an object with process(predictions, worker_id)
    # invoked on each prediction batch (e.g. streaming rows to a sink)
    prediction_outputs_processor: Any = None
    module: Any = None


def resolve_wire_format(
    spec: "ModelSpec", wire_format: str = "", compact_wire: bool = False,
    log=logger,
) -> str:
    """Pick the batch wire format a worker will actually run.

    --wire_format wins; empty defers to the legacy --compact_wire bool.
    A requested format the zoo doesn't implement degrades to the
    next-best one it does (dedup -> compact -> plain), with a warning —
    mirroring the original --compact_wire fallback so a job never dies
    over a missing optional feed."""
    requested = (wire_format or "").strip().lower() or (
        "compact" if compact_wire else "plain"
    )
    if requested not in ("plain", "compact", "dedup"):
        raise ValueError(
            f"unknown wire format {requested!r}; "
            "expected plain | compact | dedup"
        )
    resolved = requested
    if resolved == "dedup" and spec.feed_bulk_dedup is None:
        log.warning(
            "--wire_format=dedup requested but the zoo module defines no "
            "feed_bulk_dedup; falling back"
        )
        resolved = "compact"
    if resolved == "compact" and spec.feed_bulk_compact is None:
        if requested == "compact":
            log.warning(
                "--compact_wire requested but the zoo module defines no "
                "feed_bulk_compact; using the standard feed"
            )
        resolved = "plain"
    return resolved


def load_module(model_zoo: str, dotted: str):
    """Resolve `pkg.module.fn` relative to the model_zoo directory; returns
    (module, function)."""
    model_zoo = os.path.abspath(model_zoo)
    if model_zoo not in sys.path:
        sys.path.insert(0, model_zoo)
    module_path, fn_name = dotted.rsplit(".", 1)
    module = importlib.import_module(module_path)
    return module, getattr(module, fn_name)


def _call_with_params(fn, params: str):
    """Call fn, passing parsed `--model_params`-style 'k=v;k2=v2' kwargs
    that match its signature."""
    kwargs = {}
    if params:
        for item in params.split(";"):
            if not item.strip():
                continue
            key, _, value = item.partition("=")
            try:
                # Literals only (numbers/strings/tuples/dicts/bools) — this
                # string arrives from job submission, so it must never be
                # able to execute code on the master or workers.
                value = ast.literal_eval(value.strip())
            except (ValueError, SyntaxError):
                pass  # keep as raw string
            kwargs[key.strip()] = value
    sig = inspect.signature(fn)
    accepted = {
        k: v for k, v in kwargs.items() if k in sig.parameters
    }
    return fn(**accepted)


def get_model_spec(
    model_zoo: str,
    model_def: str,
    model_params: str = "",
    dataset_fn: str = "feed",
    loss: str = "loss",
    optimizer: str = "optimizer",
    eval_metrics_fn: str = "eval_metrics_fn",
    custom_data_reader: str = "custom_data_reader",
    callbacks: str = "callbacks",
    prediction_outputs_processor: str = "",
    arena_dtype: str = "",
    store_cache_dtype: str = "",
) -> ModelSpec:
    # --arena_dtype rides into model_params: `_call_with_params` filters
    # kwargs by signature, so zoos without quantized-arena support
    # (mnist, bert, ...) silently ignore it.  An arena_dtype already in
    # model_params wins — the explicit per-model string is the finer
    # knob.  --store_cache_dtype rides the same way as cache_dtype (the
    # tiered zoos' kwarg for the device hot-row cache storage).
    if arena_dtype and "arena_dtype" not in model_params:
        sep = ";" if model_params else ""
        model_params = f"{model_params}{sep}arena_dtype='{arena_dtype}'"
    if store_cache_dtype and "cache_dtype" not in model_params:
        sep = ";" if model_params else ""
        model_params = (
            f"{model_params}{sep}cache_dtype='{store_cache_dtype}'"
        )
    module, model_fn = load_module(model_zoo, model_def)

    def opt(name, required=True):
        fn = getattr(module, name, None)
        if fn is None and required:
            raise ValueError(
                f"model zoo module {module.__name__} lacks required "
                f"function {name}()"
            )
        return fn

    metrics_factory = opt(eval_metrics_fn, required=False)
    reader_factory = opt(custom_data_reader, required=False)
    callbacks_factory = opt(callbacks, required=False)
    processor = None
    if prediction_outputs_processor:
        processor_cls = getattr(module, prediction_outputs_processor, None)
        if processor_cls is None:
            raise ValueError(
                f"--prediction_outputs_processor "
                f"{prediction_outputs_processor!r} not found in "
                f"{module.__name__}"
            )
        processor = _call_with_params(processor_cls, model_params)
    return ModelSpec(
        model=_call_with_params(model_fn, model_params),
        loss=opt(loss),
        optimizer=_call_with_params(opt(optimizer), model_params),
        feed=opt(dataset_fn),
        feed_bulk=opt("feed_bulk", required=False),
        feed_bulk_compact=opt("feed_bulk_compact", required=False),
        feed_bulk_dedup=opt("feed_bulk_dedup", required=False),
        eval_metrics=metrics_factory() if metrics_factory else {},
        custom_data_reader=reader_factory,
        callbacks=callbacks_factory() if callbacks_factory else [],
        param_sharding=getattr(module, "param_sharding", None),
        prediction_outputs_processor=processor,
        module=module,
    )
