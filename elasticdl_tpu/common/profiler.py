"""Profiling/tracing utilities.

The reference had only coarse log-line timing (SURVEY.md §5); here the
baseline is step timing with device synchronisation plus one-call access to
the JAX profiler (Perfetto/XPlane traces TensorBoard can read).
"""

from __future__ import annotations

import contextlib
import time
from collections import deque
from typing import Optional

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)


class StepTimer:
    """Rolling step-rate meter.  `tick()` after each train step; reads are
    O(1).  Use `synchronize=True` at measurement boundaries only (it calls
    block_until_ready, which would serialize the pipeline every step)."""

    def __init__(self, window: int = 100):
        self._times = deque(maxlen=window)
        self._last: Optional[float] = None

    def tick(self, result=None, synchronize: bool = False):
        if synchronize and result is not None:
            import jax

            jax.block_until_ready(result)
        now = time.perf_counter()
        if self._last is not None:
            self._times.append(now - self._last)
        self._last = now

    @property
    def steps_per_sec(self) -> float:
        if not self._times:
            return 0.0
        return len(self._times) / sum(self._times)

    def log(self, prefix: str = ""):
        logger.info("%ssteps/sec=%.2f", prefix, self.steps_per_sec)


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a JAX profiler trace viewable in TensorBoard/Perfetto:

        with profiler.trace("/tmp/trace"):
            state, loss = trainer.train_on_batch(state, batch)
            jax.block_until_ready(loss)
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("Profiler trace written to %s", log_dir)


@contextlib.contextmanager
def annotate(name: str):
    """Name a region so it shows up in profiler timelines."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
