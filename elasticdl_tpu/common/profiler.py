"""Profiling/tracing utilities.

The reference had only coarse log-line timing (SURVEY.md §5); here the
baseline is step timing with device synchronisation plus one-call access to
the JAX profiler (Perfetto/XPlane traces TensorBoard can read).
"""

from __future__ import annotations

import contextlib
import time
from collections import deque
from typing import Optional

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)


class StepTimer:
    """Rolling step-rate meter.  `tick()` after each train step; reads are
    O(1).  Use `synchronize=True` at measurement boundaries only (it calls
    block_until_ready, which would serialize the pipeline every step)."""

    def __init__(self, window: int = 100):
        self._times = deque(maxlen=window)
        self._last: Optional[float] = None

    def tick(self, result=None, synchronize: bool = False):
        if synchronize and result is not None:
            import jax

            jax.block_until_ready(result)
        now = time.perf_counter()
        if self._last is not None:
            self._times.append(now - self._last)
        self._last = now

    @property
    def steps_per_sec(self) -> float:
        if not self._times:
            return 0.0
        return len(self._times) / sum(self._times)

    def log(self, prefix: str = ""):
        logger.info("%ssteps/sec=%.2f", prefix, self.steps_per_sec)


#: The step-phase vocabulary (docs/OBSERVABILITY.md "Phase catalogue").
#: Every phase a worker attributes step time to; the labeled
#: `worker_step_phase_seconds{phase=...}` histogram uses exactly these.
STEP_PHASES = (
    "data_wait", "pack", "h2d_stage", "compute", "report",
    # tiered embedding store (elasticdl_tpu/store): host-tier gathers for
    # cold rows — on the prefetcher thread when overlapped, on the
    # consumer when a deferred row forces a synchronous gather.  Its
    # `share` vs `compute` is the cold-tail overlap measurement
    # bench.py --tiered reports.
    "cold_gather",
)


class PhaseTimer:
    """Attributes each train step's wall time to named phases.

    The worker loop wraps each region in `with timer.phase("compute"):`
    (or calls `add(name, seconds)` for regions timed elsewhere, e.g. on
    the prefetch producer thread) and calls `step_done()` once per
    executed step.  Per-phase seconds feed a labeled registry histogram
    when one is supplied, cumulative totals back the telemetry payload,
    and every `flush_every` steps the accumulated breakdown is emitted as
    ONE `step_phases` span event so the attribution survives into the
    cross-process event log (common/events.py) without a per-step write.

    Thread-safe: `add()` may be called from the prefetch producer thread
    while the consumer loop runs `phase()`/`step_done()`.
    """

    def __init__(self, phases=STEP_PHASES, histogram=None,
                 flush_every: int = 50):
        import threading

        self.phases = tuple(phases)
        self._histogram = histogram   # labeled _HistogramFamily or None
        self._flush_every = max(1, int(flush_every))
        self._lock = threading.Lock()
        self._totals = {p: 0.0 for p in self.phases}      # job lifetime
        self._pending = {p: 0.0 for p in self.phases}     # since last flush
        self._steps = 0
        self._pending_steps = 0

    @contextlib.contextmanager
    def phase(self, name: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - start)

    def add(self, name: str, seconds: float) -> None:
        if name not in self._totals:
            return  # unknown phase: attribution must never raise
        seconds = max(0.0, float(seconds))
        with self._lock:
            self._totals[name] += seconds
            self._pending[name] += seconds
        if self._histogram is not None:
            try:
                self._histogram.labels(phase=name).record(seconds)
            except Exception:
                pass

    def step_done(self) -> None:
        """Count one executed step; flush a `step_phases` span event at
        the flush interval."""
        with self._lock:
            self._steps += 1
            self._pending_steps += 1
            if self._pending_steps < self._flush_every:
                return
            payload = {
                p: round(v, 6) for p, v in self._pending.items()
            }
            steps = self._pending_steps
            for p in self._pending:
                self._pending[p] = 0.0
            self._pending_steps = 0
        from elasticdl_tpu.common import events

        events.emit(events.STEP_PHASES, phases=payload, steps=steps)

    def flush(self) -> None:
        """Force out whatever accumulated since the last flush (end of a
        task/job: partial windows must not be lost)."""
        with self._lock:
            if not self._pending_steps:
                return
            payload = {
                p: round(v, 6) for p, v in self._pending.items()
            }
            steps = self._pending_steps
            for p in self._pending:
                self._pending[p] = 0.0
            self._pending_steps = 0
        from elasticdl_tpu.common import events

        events.emit(events.STEP_PHASES, phases=payload, steps=steps)

    @property
    def steps(self) -> int:
        with self._lock:
            return self._steps

    def snapshot(self) -> dict:
        """{phase: {"total_s", "mean_s", "share"}} over the job so far.
        `share` is the phase's fraction of all attributed time."""
        with self._lock:
            totals = dict(self._totals)
            steps = self._steps
        attributed = sum(totals.values())
        return {
            p: {
                "total_s": t,
                "mean_s": (t / steps) if steps else 0.0,
                "share": (t / attributed) if attributed else 0.0,
            }
            for p, t in totals.items()
        }

    def totals_milli(self) -> dict:
        """{phase: cumulative milliseconds} as ints — the shape the
        worker's int64 telemetry piggyback (report exec_counters) can
        carry."""
        with self._lock:
            return {
                p: int(round(v * 1000.0)) for p, v in self._totals.items()
            }


class LatencyHistogram:
    """Thread-safe log-bucketed latency histogram with quantile reads.

    Serving needs p50/p99 over an unbounded stream without keeping every
    sample; log-spaced buckets give a bounded-error quantile (each bucket
    spans `growth`x, so a reported quantile is within one growth factor of
    truth) at O(1) record cost under a lock — the batcher records from its
    dispatch threads while Health RPCs read concurrently.
    """

    def __init__(self, min_s: float = 1e-4, max_s: float = 60.0,
                 growth: float = 1.25):
        import math
        import threading

        self._min_s = min_s
        self._log_min = math.log(min_s)
        self._log_growth = math.log(growth)
        nbuckets = int(math.ceil(
            (math.log(max_s) - self._log_min) / self._log_growth
        )) + 1
        # bucket i covers [min_s * growth**i, min_s * growth**(i+1));
        # underflow clamps to 0, overflow to the last bucket
        self._uppers = [
            min_s * growth ** (i + 1) for i in range(nbuckets)
        ]
        self._counts = [0] * nbuckets
        self._total = 0
        self._sum_s = 0.0
        self._lock = threading.Lock()

    def record(self, seconds: float) -> None:
        import math

        if seconds < self._min_s:
            idx = 0
        else:
            idx = int((math.log(seconds) - self._log_min)
                      / self._log_growth)
            idx = min(idx, len(self._counts) - 1)
        with self._lock:
            self._counts[idx] += 1
            self._total += 1
            self._sum_s += seconds

    @property
    def count(self) -> int:
        with self._lock:
            return self._total

    def bucket_snapshot(self):
        """(uppers, counts, total, sum_s) copied under ONE lock
        acquisition — the consistent basis for quantiles and for the
        Prometheus histogram exposition (common/metrics.py), which needs
        the raw cumulative buckets, not just the derived quantiles."""
        with self._lock:
            return (
                list(self._uppers), list(self._counts),
                self._total, self._sum_s,
            )

    @staticmethod
    def _quantile_from(uppers, counts, total, q: float) -> float:
        if not total:
            return 0.0
        rank = q * (total - 1)
        seen = 0
        for idx, c in enumerate(counts):
            seen += c
            if seen > rank:
                return uppers[idx]
        return uppers[-1]

    def quantile(self, q: float) -> float:
        """Upper edge of the bucket holding the q-quantile, in seconds.
        Returns 0.0 before any sample."""
        uppers, counts, total, _ = self.bucket_snapshot()
        return self._quantile_from(uppers, counts, total, q)

    def snapshot(self) -> dict:
        """{count, mean_s, p50_s, p99_s} — one consistent read.  All four
        numbers derive from a single locked copy of the buckets; the old
        implementation re-acquired the lock per quantile, so a concurrent
        `record()` could make count/mean and p50/p99 describe different
        populations."""
        uppers, counts, total, sum_s = self.bucket_snapshot()
        return {
            "count": total,
            "mean_s": (sum_s / total) if total else 0.0,
            "p50_s": self._quantile_from(uppers, counts, total, 0.5),
            "p99_s": self._quantile_from(uppers, counts, total, 0.99),
        }


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a JAX profiler trace viewable in TensorBoard/Perfetto:

        with profiler.trace("/tmp/trace"):
            state, loss = trainer.train_on_batch(state, batch)
            jax.block_until_ready(loss)
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("Profiler trace written to %s", log_dir)


@contextlib.contextmanager
def annotate(name: str):
    """Name a region so it shows up in profiler timelines."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
