"""Checkpoint save/restore via Orbax.

Parity: reference python/common/save_utils.py `CheckpointSaver`
(SURVEY.md C9, §3.6): versioned checkpoint directories, keep-max rotation,
restore-on-relaunch.  TPU-native differences: Orbax writes sharded arrays
from the mesh directly (async) — the reference's per-PS-shard serialization
has no equivalent because there are no PS processes; preemption-aware
save-on-signal hooks into the pod manager instead of the PS.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)


def _swap_tree_keys(node, old: str, new: str):
    """Recursively rename dict keys `old` -> `new` through the mixed
    containers a TrainState template is made of (dicts, flax struct
    dataclasses, optax NamedTuple states, lists/tuples).  Raises on a
    collision (a subtree already holding BOTH names) — the shim must
    never silently merge two distinct params."""
    if isinstance(node, dict):
        if old in node and new in node:
            raise ValueError(
                f"cannot rename {old!r} -> {new!r}: both keys present"
            )
        return {
            (new if k == old else k): _swap_tree_keys(v, old, new)
            for k, v in node.items()
        }
    if hasattr(node, "_fields"):          # NamedTuple (optax states)
        return type(node)(
            *(_swap_tree_keys(v, old, new) for v in node)
        )
    if hasattr(node, "__dataclass_fields__"):   # flax struct (TrainState)
        import dataclasses

        return type(node)(
            **{
                f.name: _swap_tree_keys(getattr(node, f.name), old, new)
                for f in dataclasses.fields(node)
            }
        )
    if isinstance(node, (list, tuple)):
        return type(node)(_swap_tree_keys(v, old, new) for v in node)
    return node


def _tree_has_key(node, key: str) -> bool:
    if isinstance(node, dict):
        return key in node or any(
            _tree_has_key(v, key) for v in node.values()
        )
    if hasattr(node, "_fields"):
        return any(_tree_has_key(v, key) for v in node)
    if hasattr(node, "__dataclass_fields__"):
        import dataclasses

        return any(
            _tree_has_key(getattr(node, f.name), key)
            for f in dataclasses.fields(node)
        )
    if isinstance(node, (list, tuple)):
        return any(_tree_has_key(v, key) for v in node)
    return False


class CheckpointSaver:
    def __init__(
        self,
        checkpoint_dir: str,
        keep_max: int = 3,
        async_save: bool = True,
    ):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(checkpoint_dir)
        os.makedirs(self._dir, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep_max,
                enable_async_checkpointing=async_save,
            ),
        )

    def save(self, state, force: bool = False) -> bool:
        import orbax.checkpoint as ocp

        step = int(state.step)
        saved = self._mngr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        if saved:
            logger.info("Checkpoint saved at step %d", step)
        return saved

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def all_steps(self):
        return list(self._mngr.all_steps())

    def restore_step(self, step: int, template: Any) -> Optional[Any]:
        """Restore a SPECIFIC checkpointed step into `template`'s
        shardings (eval-at-version: score the model the master asked
        about, not whatever the leasing worker currently holds)."""
        import jax
        import orbax.checkpoint as ocp

        if step not in self._mngr.all_steps():
            return None
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None)
            )
            if hasattr(x, "shape")
            else x,
            template,
        )
        restored = self._restore_with_shims(step, abstract)
        logger.info("Restored checkpoint step %d (eval-at-version)", step)
        return restored

    def _restore_with_shims(self, step: int, abstract: Any) -> Any:
        """StandardRestore, with a legacy-key migration fallback: round 4
        renamed the GPipe stack param `stack` -> `gpipe_stack` (ADVICE
        r4) — a pre-rename checkpoint restores by renaming the keys in
        the TEMPLATE (everywhere: params AND the optimizer's mirrored
        moment trees), then renaming them back in the restored tree, so
        old pipelined checkpoints load without manual surgery."""
        import orbax.checkpoint as ocp

        try:
            return self._mngr.restore(
                step, args=ocp.args.StandardRestore(abstract)
            )
        except Exception:
            # Retry with the legacy template ONLY when the stored tree
            # really has the old key layout — re-running restore after an
            # unrelated failure (corrupt files, dtype mismatch, transient
            # FS error) would bury the real error under a phantom
            # key-migration failure.
            if not _tree_has_key(abstract, "gpipe_stack"):
                raise
            try:
                stored = self._mngr.item_metadata(step)
                # TreeMetadata wraps the key layout in `.tree`
                stored = getattr(stored, "tree", stored)
            except Exception:
                stored = None
            if stored is not None and not (
                _tree_has_key(stored, "stack")
                and not _tree_has_key(stored, "gpipe_stack")
            ):
                raise
            legacy = _swap_tree_keys(abstract, "gpipe_stack", "stack")
            restored = self._mngr.restore(
                step, args=ocp.args.StandardRestore(legacy)
            )
            logger.info(
                "Restored checkpoint step %d via legacy GPipe key shim "
                "(stack -> gpipe_stack)", step,
            )
            return _swap_tree_keys(restored, "stack", "gpipe_stack")

    def maybe_restore(self, template: Any) -> Optional[Any]:
        """Restore the newest checkpoint into the sharding/structure of
        `template` (an abstract or concrete train state)."""
        import jax
        import orbax.checkpoint as ocp

        step = self._mngr.latest_step()
        if step is None:
            return None
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None)
            )
            if hasattr(x, "shape")
            else x,
            template,
        )
        restored = self._restore_with_shims(step, abstract)
        logger.info("Restored checkpoint step %d", step)
        return restored

    def wait_until_finished(self):
        self._mngr.wait_until_finished()

    def close(self):
        self._mngr.close()
