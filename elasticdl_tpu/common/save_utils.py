"""Checkpoint save/restore via Orbax.

Parity: reference python/common/save_utils.py `CheckpointSaver`
(SURVEY.md C9, §3.6): versioned checkpoint directories, keep-max rotation,
restore-on-relaunch.  TPU-native differences: Orbax writes sharded arrays
from the mesh directly (async) — the reference's per-PS-shard serialization
has no equivalent because there are no PS processes; preemption-aware
save-on-signal hooks into the pod manager instead of the PS.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, FrozenSet, Optional

from elasticdl_tpu.common import events, faults
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)

# ---- step pinning ---------------------------------------------------------
#
# The keep-last-K sweep and the serving hot-reload race: the trainer's
# saver rotates old steps out while a reloader (its OWN CheckpointSaver
# on the same directory) is mid-restore on one of them.  Orbax's
# built-in max_to_keep cannot see the reloader, so rotation is owned
# here instead (max_to_keep=None + an explicit sweep) and gated on a
# PROCESS-WIDE pin registry keyed by the checkpoint directory: the
# reloader pins the step for the duration of verify/restore/swap, and
# the sweep skips pinned steps (they fall on the next sweep after
# unpin).  Refcounted — overlapping pinners (N serving replicas
# reloading the same step) each hold their own pin.

_PIN_LOCK = threading.Lock()
_PINNED: Dict[str, Dict[int, int]] = {}   # abs dir -> step -> refcount


def pin_step(checkpoint_dir: str, step: int) -> None:
    """Protect `step` from the keep-last-K sweep until unpinned."""
    key = os.path.abspath(checkpoint_dir)
    step = int(step)
    with _PIN_LOCK:
        dir_pins = _PINNED.setdefault(key, {})
        dir_pins[step] = dir_pins.get(step, 0) + 1


def unpin_step(checkpoint_dir: str, step: int) -> None:
    key = os.path.abspath(checkpoint_dir)
    step = int(step)
    with _PIN_LOCK:
        dir_pins = _PINNED.get(key)
        if not dir_pins or step not in dir_pins:
            return
        dir_pins[step] -= 1
        if dir_pins[step] <= 0:
            del dir_pins[step]
        if not dir_pins:
            del _PINNED[key]


def pinned_steps(checkpoint_dir: str) -> FrozenSet[int]:
    with _PIN_LOCK:
        return frozenset(_PINNED.get(os.path.abspath(checkpoint_dir), ()))


def _file_digest(path: str) -> Dict[str, Any]:
    sha = hashlib.sha256()
    size = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            sha.update(chunk)
            size += len(chunk)
    return {"sha256": sha.hexdigest(), "size": size}


def _step_files(step_dir: str):
    """Relative paths of every regular file under a step directory, in a
    stable order."""
    out = []
    for root, _dirs, files in os.walk(step_dir):
        for name in files:
            full = os.path.join(root, name)
            out.append(os.path.relpath(full, step_dir))
    return sorted(out)


def _swap_tree_keys(node, old: str, new: str):
    """Recursively rename dict keys `old` -> `new` through the mixed
    containers a TrainState template is made of (dicts, flax struct
    dataclasses, optax NamedTuple states, lists/tuples).  Raises on a
    collision (a subtree already holding BOTH names) — the shim must
    never silently merge two distinct params."""
    if isinstance(node, dict):
        if old in node and new in node:
            raise ValueError(
                f"cannot rename {old!r} -> {new!r}: both keys present"
            )
        return {
            (new if k == old else k): _swap_tree_keys(v, old, new)
            for k, v in node.items()
        }
    if hasattr(node, "_fields"):          # NamedTuple (optax states)
        return type(node)(
            *(_swap_tree_keys(v, old, new) for v in node)
        )
    if hasattr(node, "__dataclass_fields__"):   # flax struct (TrainState)
        import dataclasses

        return type(node)(
            **{
                f.name: _swap_tree_keys(getattr(node, f.name), old, new)
                for f in dataclasses.fields(node)
            }
        )
    if isinstance(node, (list, tuple)):
        return type(node)(_swap_tree_keys(v, old, new) for v in node)
    return node


class ArenaDtypeMismatch(ValueError):
    """A checkpoint's arena storage dtype differs from the configured
    model's and no conversion was requested.  Raised INSTEAD of the jax
    aval/structure crash the raw restore would produce, with the two
    dtypes and the fix in the message."""


def _state_arena_dtype(state) -> str:
    """"int8" when a (possibly abstract) train state carries a
    "quantized" collection, else "float32".  Structure-only."""
    model_state = getattr(state, "model_state", None)
    if isinstance(model_state, dict) and model_state.get("quantized"):
        return "int8"
    return "float32"


def _arena_meta_of(state) -> Dict[str, Any]:
    """Manifest metadata for the arena storage mode: the dtype plus, in
    int8 mode, each quantized plane's path/rows/dim/scale shape — enough
    to synthesize a restore template for dtype conversion without the
    model that wrote the checkpoint."""
    if _state_arena_dtype(state) == "float32":
        return {"arena_dtype": "float32", "planes": {}}
    from elasticdl_tpu.layers.arena import is_quantized_planes

    planes: Dict[str, Any] = {}

    def walk(node, path):
        if is_quantized_planes(node):
            planes["/".join(path)] = {
                "rows": int(node["q8"].shape[0]),
                "dim": int(node["q8"].shape[1]),
                "scale_shape": [int(s) for s in node["scale"].shape],
            }
            return
        for k in node:
            walk(node[k], path + (k,))

    walk(state.model_state["quantized"], ())
    return {"arena_dtype": "int8", "planes": planes}


def _planes_template_from_meta(meta: Dict[str, Any], params: Any):
    """Rebuild the abstract "quantized" collection recorded in a
    manifest: nested {path: {"q8", "scale"}} ShapeDtypeStructs.  Each
    plane reuses the sharding of the params leaf at the same path (the
    carrier has the q8 plane's exact shape), so a sharded restore lands
    the planes where the table lives."""
    import jax
    import jax.numpy as jnp

    quant: Dict[str, Any] = {}
    for dotted, info in meta.get("planes", {}).items():
        keys = dotted.split("/")
        sharding = None
        leaf = params.get("params", {})
        try:
            for k in keys:
                leaf = leaf[k]
            sharding = getattr(leaf, "sharding", None)
        except (KeyError, TypeError):
            leaf = None
        node = quant
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        rows, dim = int(info["rows"]), int(info["dim"])
        node[keys[-1]] = {
            "q8": jax.ShapeDtypeStruct(
                (rows, dim), jnp.int8, sharding=sharding
            ),
            "scale": jax.ShapeDtypeStruct(
                tuple(info.get("scale_shape", (rows, 1))), jnp.float32,
                sharding=sharding,
            ),
        }
    return quant


def _replace_state(state, params, model_state):
    if hasattr(state, "replace"):
        return state.replace(params=params, model_state=model_state)
    out = dict(state)
    out["params"] = params
    out["model_state"] = model_state
    return out


def _tree_has_key(node, key: str) -> bool:
    if isinstance(node, dict):
        return key in node or any(
            _tree_has_key(v, key) for v in node.values()
        )
    if hasattr(node, "_fields"):
        return any(_tree_has_key(v, key) for v in node)
    if hasattr(node, "__dataclass_fields__"):
        import dataclasses

        return any(
            _tree_has_key(getattr(node, f.name), key)
            for f in dataclasses.fields(node)
        )
    if isinstance(node, (list, tuple)):
        return any(_tree_has_key(v, key) for v in node)
    return False


def read_produced_meta(checkpoint_dir: str,
                       step: int) -> Optional[Dict[str, Any]]:
    """Read a manifest's producer freshness stamp without a saver (the
    master's FreshnessTracker watches a directory a trainer writes)."""
    path = os.path.join(
        os.path.abspath(checkpoint_dir), ".manifests", f"{int(step)}.json"
    )
    try:
        with open(path) as f:
            return json.load(f).get("produced")
    except (OSError, ValueError):
        return None


class CheckpointSaver:
    def __init__(
        self,
        checkpoint_dir: str,
        keep_max: int = 3,
        async_save: bool = True,
        clock=time.time,
    ):
        import orbax.checkpoint as ocp

        # injectable for deterministic freshness stamps under fake
        # clocks (docs/OBSERVABILITY.md "Metric history & SLOs")
        self._clock = clock

        self._dir = os.path.abspath(checkpoint_dir)
        os.makedirs(self._dir, exist_ok=True)
        # Per-step checksum manifests live in a side directory (never
        # inside the step dir: Orbax owns that layout) so restores can
        # detect truncated/corrupted checkpoints and fall back.
        self._manifest_dir = os.path.join(self._dir, ".manifests")
        os.makedirs(self._manifest_dir, exist_ok=True)
        self._async_save = bool(async_save)
        # Rotation is owned HERE, not by orbax (max_to_keep=None): the
        # sweep in _refresh_manifests keeps the newest `keep_max`
        # finalized steps, prunes manifests and tiered sidecars in
        # lockstep, and honors the pin registry above so a step a
        # reloader is mid-swap on is never deleted under it.
        self._keep_max = int(keep_max) if keep_max else None
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=None,
                enable_async_checkpointing=async_save,
            ),
        )
        # arena storage metadata per saved step, cached at save() time
        # (manifests are written later, after async finalize, with no
        # access to the state)
        self._arena_meta: Dict[int, Dict[str, Any]] = {}
        # producer freshness stamp per saved step, same cached-at-save
        # pattern — the train-to-serve staleness trace starts here
        self._produced_meta: Dict[int, Dict[str, Any]] = {}
        # tiered embedding store (elasticdl_tpu/store): when attached,
        # save() writes a sidecar (host planes + vocab + cache map) next
        # to each step and restores load it back into the store
        self._tiered_store = None
        self._tiered_meta: Dict[int, Dict[str, Any]] = {}

    def attach_tiered_store(self, store) -> None:
        """Couple a TieredStore to this saver: each save() writes the
        store's sidecar for the step, and each restore re-adopts the
        sidecar matching the restored step."""
        self._tiered_store = store

    def restore_raw(self, step: int):
        """Restore a step WITHOUT a template — the stored tree as orbax
        recorded it (dicts/lists of host arrays).  The tiered<->flat
        migration helpers path-match against this."""
        import orbax.checkpoint as ocp

        return self._mngr.restore(step, args=ocp.args.StandardRestore())

    def _save_tiered_sidecar(self, step: int, state) -> None:
        if self._tiered_store is None:
            return
        from elasticdl_tpu.store import checkpoint as store_ckpt

        try:
            store_ckpt.save_sidecar(self._dir, step,
                                    self._tiered_store, state)
            store = self._tiered_store
            self._tiered_meta[step] = {
                "cache_rows": int(store.cache_rows),
                "vocab_rows": int(store.host.size),
                "host_dtype": store.host.host_dtype,
                "cache_dtype": getattr(store, "cache_dtype", "float32"),
                "planes": {
                    name: int(dim) for name, dim in store.planes.items()
                },
            }
        except Exception:
            logger.exception("tiered sidecar save failed")

    def _load_tiered_sidecar(self, step: int) -> None:
        if self._tiered_store is None:
            return
        from elasticdl_tpu.store import checkpoint as store_ckpt

        if not store_ckpt.has_sidecar(self._dir, step):
            # A flat checkpoint restored into a tiered run: legitimate
            # (migration path) — the store keeps its current (usually
            # fresh) host state and lazily backfills.
            logger.info(
                "checkpoint step %d has no tiered sidecar; store state "
                "not restored", step,
            )
            return
        sidecar = store_ckpt.load_sidecar(self._dir, step)
        # convert=True: when the sidecar's plane dtype differs from the
        # running store's, the device cache VALUES restore through this
        # saver's template (arena_convert handles the int8<->fp32 plane
        # migration on the TrainState), so the residency map is safe to
        # adopt across the dtype change — the strict dtype gate is for
        # callers restoring bookkeeping WITHOUT the values.
        self._tiered_store.load_sidecar_state(
            sidecar.host_state, sidecar.row_of, sidecar.score,
            cache_dtype=sidecar.cache_dtype, convert=True,
        )
        logger.info(
            "tiered store sidecar restored for step %d "
            "(vocab_rows=%d cache_dtype=%s)", step,
            sidecar.meta.get("vocab_rows", -1), sidecar.cache_dtype,
        )

    def save(self, state, force: bool = False) -> bool:
        import orbax.checkpoint as ocp

        try:
            faults.fire(faults.POINT_CHECKPOINT_WRITE)
        except faults.InjectedFault as exc:
            # A failed periodic save is survivable by design: the next
            # crossing saves again, and restores fall back to the last
            # committed step.  Only injected faults take this path — real
            # Orbax errors still propagate.
            logger.warning("checkpoint save skipped (%s)", exc)
            return False
        step = int(state.step)
        if self._async_save:
            import jax

            if jax.default_backend() == "cpu":
                # Orbax's async save snapshots device buffers to host
                # before the background write, but on the CPU backend
                # that snapshot can be a zero-copy VIEW of the live
                # buffer — the next donating train step rewrites it in
                # place and the "step N" checkpoint silently captures
                # step N+1 values (same aliasing family as
                # parallel/collectives.host_snapshot).  Copy eagerly;
                # on accelerators the D2H transfer orbax performs is
                # already an owning copy, so no gate needed there.
                from elasticdl_tpu.parallel.collectives import (
                    host_snapshot,
                )

                state = host_snapshot(state)
        try:
            self._arena_meta[step] = _arena_meta_of(state)
        except Exception:
            logger.exception("arena metadata capture failed")
        self._produced_meta[step] = {
            "model_step": step,
            "produced_unix_s": round(float(self._clock()), 6),
        }
        # Sidecar BEFORE the (async) orbax save: the cache-value read
        # must precede the next donating train step.
        self._save_tiered_sidecar(step, state)
        saved = self._mngr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        if saved:
            logger.info("Checkpoint saved at step %d", step)
            events.emit(events.CHECKPOINT_SAVED, step=step)
        # Manifests cover FINALIZED steps only (async saves commit
        # later); anything committed by now — including earlier async
        # saves — gets its manifest here.
        self._refresh_manifests()
        return saved

    # ---- integrity manifests -------------------------------------------

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self._manifest_dir, f"{step}.json")

    def _step_dir(self, step: int) -> str:
        return os.path.join(self._dir, str(step))

    def _sweep_old_steps(self) -> None:
        """Keep-last-K over FINALIZED steps: delete everything older than
        the newest `keep_max`, except steps pinned by an in-flight
        reloader swap (those rotate out on the first sweep after
        unpin)."""
        if self._keep_max is None:
            return
        steps = sorted(self._mngr.all_steps())
        excess = steps[:-self._keep_max] if self._keep_max else steps
        if not excess:
            return
        pinned = pinned_steps(self._dir)
        for step in excess:
            if step in pinned:
                logger.info(
                    "keep-last-%d sweep deferring step %d (pinned by an "
                    "in-flight reload)", self._keep_max, step,
                )
                continue
            self._mngr.delete(step)

    def _refresh_manifests(self) -> None:
        """Rotate old steps out (keep-last-K, pin-aware), then write
        missing manifests for surviving finalized steps and prune
        manifests + tiered sidecars of rotated-away steps — base dir and
        `.tiered/<step>/` always move in lockstep.  Best-effort:
        integrity metadata must never fail a save."""
        try:
            self._sweep_old_steps()
            steps = set(self._mngr.all_steps())
            for step in steps:
                path = self._manifest_path(step)
                if os.path.exists(path):
                    continue
                self._write_manifest(step)
            for name in os.listdir(self._manifest_dir):
                stem, ext = os.path.splitext(name)
                if ext == ".json" and stem.isdigit() \
                        and int(stem) not in steps:
                    os.remove(os.path.join(self._manifest_dir, name))
            if self._tiered_store is not None:
                from elasticdl_tpu.store import checkpoint as store_ckpt

                store_ckpt.prune_sidecars(self._dir, steps)
        except Exception:
            logger.exception("checkpoint manifest refresh failed")

    def _write_manifest(self, step: int) -> None:
        step_dir = self._step_dir(step)
        if not os.path.isdir(step_dir):
            return
        manifest = {
            "step": step,
            "files": {
                rel: _file_digest(os.path.join(step_dir, rel))
                for rel in _step_files(step_dir)
            },
        }
        # arena storage mode, when this process saved the step (absent
        # for steps written before the quantized arena existed — those
        # are all float32)
        if step in self._arena_meta:
            manifest["arena"] = self._arena_meta[step]
        # producer model_step + wall time (absent for steps written by a
        # pre-freshness trainer); the reloader carries it through the
        # serving swap so every replica knows the age of its model
        if step in self._produced_meta:
            manifest["produced"] = self._produced_meta[step]
        # tiered store layout (cache size, planes, vocab at save time) —
        # what the serving side needs to know BEFORE loading the sidecar
        if step in self._tiered_meta:
            manifest["tiered"] = self._tiered_meta[step]
        path = self._manifest_path(step)
        tmp = path + ".tmp"
        # temp file + os.replace: readers only ever see a complete
        # manifest, even across a crash mid-write
        with open(tmp, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def verify_step(self, step: int) -> bool:
        """Check a step's files against its manifest.  True when intact
        or when no manifest exists (pre-manifest checkpoints stay
        restorable); False on any missing/truncated/altered file."""
        path = self._manifest_path(step)
        if not os.path.exists(path):
            return True
        try:
            with open(path) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return True  # unreadable manifest != corrupt checkpoint
        step_dir = self._step_dir(step)
        for rel, want in manifest.get("files", {}).items():
            full = os.path.join(step_dir, rel)
            if not os.path.isfile(full):
                logger.warning(
                    "checkpoint step %d: missing file %s", step, rel
                )
                return False
            got = _file_digest(full)
            if got["size"] != want.get("size") \
                    or got["sha256"] != want.get("sha256"):
                logger.warning(
                    "checkpoint step %d: checksum mismatch in %s "
                    "(%d bytes vs %d expected)",
                    step, rel, got["size"], want.get("size", -1),
                )
                return False
        return True

    def reload(self) -> None:
        """Re-scan the checkpoint directory for steps written by ANOTHER
        process (serving hot-reload watches a directory a trainer writes
        to; Orbax caches its step listing per manager)."""
        if hasattr(self._mngr, "reload"):
            self._mngr.reload()

    # ---- freshness -----------------------------------------------------

    def produced_meta(self, step: int) -> Optional[Dict[str, Any]]:
        """The {model_step, produced_unix_s} stamp a manifest recorded
        for `step`, or None (pre-freshness checkpoints)."""
        return read_produced_meta(self._dir, step)

    # ---- arena dtype compatibility -------------------------------------

    def _manifest_arena_meta(self, step: int) -> Optional[Dict[str, Any]]:
        try:
            with open(self._manifest_path(step)) as f:
                return json.load(f).get("arena")
        except (OSError, ValueError):
            return None

    def _checkpoint_arena_dtype(self, step: int) -> str:
        """The arena storage mode a checkpointed step was written with:
        from the manifest when recorded, else from the stored tree's
        structure (a "quantized" subtree means int8), else float32 —
        every pre-quantization checkpoint is fp32."""
        meta = self._manifest_arena_meta(step)
        if meta:
            return meta.get("arena_dtype", "float32")
        try:
            stored = self._mngr.item_metadata(step)
            stored = getattr(stored, "tree", stored)
            if stored is not None and _tree_has_key(stored, "quantized"):
                return "int8"
        except Exception:
            pass
        return "float32"

    def _arena_compat(self, step: int, abstract, arena_convert: bool):
        """Reconcile the checkpoint's arena dtype with the template's.

        Same dtype -> (abstract, None).  Different dtype without
        `arena_convert` -> ArenaDtypeMismatch (a clear error instead of
        the jax structure crash the raw restore would hit).  With
        `arena_convert`, returns (source template matching the
        CHECKPOINT's layout, post-restore converter into the CONFIGURED
        layout) — both directions, via layers/arena.py's tree
        converters; the carrier param shares the fp32 table's
        name/shape, so adam moments survive either way."""
        want = _state_arena_dtype(abstract)
        have = self._checkpoint_arena_dtype(step)
        if have == want:
            return abstract, None
        if not arena_convert:
            raise ArenaDtypeMismatch(
                f"checkpoint step {step} stores {have} arena rows but the "
                f"configured model expects {want}: pass "
                "arena_convert=True to migrate on restore, or set "
                f"--arena_dtype {have} to match the checkpoint"
            )
        from elasticdl_tpu.layers.arena import (
            dequantize_arena_tree,
            quantize_arena_tree,
        )

        if have == "float32":  # fp32 checkpoint -> quantized config
            quant_template = abstract.model_state["quantized"]
            source = _replace_state(
                abstract,
                abstract.params,
                {
                    k: v for k, v in abstract.model_state.items()
                    if k != "quantized"
                },
            )

            def convert(restored):
                inner, quant = quantize_arena_tree(
                    restored.params["params"], quant_template
                )
                params = dict(restored.params)
                params["params"] = inner
                model_state = dict(restored.model_state)
                model_state["quantized"] = quant
                logger.info(
                    "checkpoint step %d: quantized fp32 arena rows to "
                    "int8 on restore", step,
                )
                return _replace_state(restored, params, model_state)

            return source, convert

        # quantized checkpoint -> fp32 config (serving export path)
        meta = self._manifest_arena_meta(step)
        if not meta or not meta.get("planes"):
            raise ArenaDtypeMismatch(
                f"checkpoint step {step} stores int8 arena rows but its "
                "manifest records no plane shapes; cannot synthesize the "
                "conversion template — restore with --arena_dtype int8 "
                "instead"
            )
        quant_template = _planes_template_from_meta(meta, abstract.params)
        source = _replace_state(
            abstract,
            abstract.params,
            {**abstract.model_state, "quantized": quant_template},
        )

        def convert(restored):
            inner = dequantize_arena_tree(
                restored.params["params"],
                restored.model_state["quantized"],
            )
            params = dict(restored.params)
            params["params"] = inner
            model_state = {
                k: v for k, v in restored.model_state.items()
                if k != "quantized"
            }
            logger.info(
                "checkpoint step %d: dequantized int8 arena rows to "
                "fp32 on restore", step,
            )
            return _replace_state(restored, params, model_state)

        return source, convert

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def all_steps(self):
        return list(self._mngr.all_steps())

    def restore_step(
        self, step: int, template: Any, arena_convert: bool = False
    ) -> Optional[Any]:
        """Restore a SPECIFIC checkpointed step into `template`'s
        shardings (eval-at-version: score the model the master asked
        about, not whatever the leasing worker currently holds).

        `arena_convert=True` migrates across arena storage dtypes
        (fp32 checkpoint -> int8 config and back); without it a dtype
        mismatch raises `ArenaDtypeMismatch`."""
        import jax
        import orbax.checkpoint as ocp

        if step not in self._mngr.all_steps():
            return None
        if not self.verify_step(step):
            logger.warning(
                "checkpoint step %d failed integrity check; not restoring",
                step,
            )
            return None
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None)
            )
            if hasattr(x, "shape")
            else x,
            template,
        )
        abstract, convert = self._arena_compat(step, abstract, arena_convert)
        restored = self._restore_with_shims(step, abstract)
        if convert is not None:
            restored = convert(restored)
        self._load_tiered_sidecar(step)
        logger.info("Restored checkpoint step %d (eval-at-version)", step)
        events.emit(events.CHECKPOINT_RESTORED, step=step)
        return restored

    def _restore_with_shims(self, step: int, abstract: Any) -> Any:
        """StandardRestore, with a legacy-key migration fallback: round 4
        renamed the GPipe stack param `stack` -> `gpipe_stack` (ADVICE
        r4) — a pre-rename checkpoint restores by renaming the keys in
        the TEMPLATE (everywhere: params AND the optimizer's mirrored
        moment trees), then renaming them back in the restored tree, so
        old pipelined checkpoints load without manual surgery."""
        import orbax.checkpoint as ocp

        try:
            return self._mngr.restore(
                step, args=ocp.args.StandardRestore(abstract)
            )
        except Exception:
            # Retry with the legacy template ONLY when the stored tree
            # really has the old key layout — re-running restore after an
            # unrelated failure (corrupt files, dtype mismatch, transient
            # FS error) would bury the real error under a phantom
            # key-migration failure.
            if not _tree_has_key(abstract, "gpipe_stack"):
                raise
            try:
                stored = self._mngr.item_metadata(step)
                # TreeMetadata wraps the key layout in `.tree`
                stored = getattr(stored, "tree", stored)
            except Exception:
                stored = None
            if stored is not None and not (
                _tree_has_key(stored, "stack")
                and not _tree_has_key(stored, "gpipe_stack")
            ):
                raise
            legacy = _swap_tree_keys(abstract, "gpipe_stack", "stack")
            restored = self._mngr.restore(
                step, args=ocp.args.StandardRestore(legacy)
            )
            logger.info(
                "Restored checkpoint step %d via legacy GPipe key shim "
                "(stack -> gpipe_stack)", step,
            )
            return _swap_tree_keys(restored, "stack", "gpipe_stack")

    def maybe_restore(
        self, template: Any, arena_convert: bool = False
    ) -> Optional[Any]:
        """Restore the newest INTACT checkpoint into the sharding/
        structure of `template` (an abstract or concrete train state).

        A latest step that is truncated/corrupt (manifest mismatch) or
        fails to restore falls back to the previous good step — a torn
        write must cost one checkpoint interval of progress, never the
        job.  When every step fails to restore, the last restore error
        re-raises (callers must not silently train from scratch when
        checkpoints exist but are all broken).

        An arena storage dtype mismatch (checkpoint int8 vs configured
        fp32 or vice versa) raises `ArenaDtypeMismatch` IMMEDIATELY —
        older steps would mismatch the same way, and silently training
        from scratch over a dtype flag is the worst outcome.  Pass
        `arena_convert=True` to migrate instead."""
        import jax

        steps = sorted(self._mngr.all_steps(), reverse=True)
        if not steps:
            return None
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None)
            )
            if hasattr(x, "shape")
            else x,
            template,
        )
        last_exc: Optional[Exception] = None
        for step in steps:
            if not self.verify_step(step):
                logger.warning(
                    "checkpoint step %d corrupt; falling back to the "
                    "previous good step", step,
                )
                continue
            try:
                step_abstract, convert = self._arena_compat(
                    step, abstract, arena_convert
                )
                restored = self._restore_with_shims(step, step_abstract)
                if convert is not None:
                    restored = convert(restored)
            except ArenaDtypeMismatch:
                raise
            except Exception as exc:
                last_exc = exc
                logger.warning(
                    "checkpoint step %d failed to restore (%s); falling "
                    "back to the previous good step", step, exc,
                )
                continue
            self._load_tiered_sidecar(step)
            logger.info("Restored checkpoint step %d", step)
            events.emit(events.CHECKPOINT_RESTORED, step=step)
            return restored
        if last_exc is not None:
            raise last_exc
        return None

    def wait_until_finished(self):
        self._mngr.wait_until_finished()
        # async saves finalized by now become manifest-covered
        self._refresh_manifests()

    def close(self):
        self._mngr.close()
