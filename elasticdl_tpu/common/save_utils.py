"""Checkpoint save/restore via Orbax.

Parity: reference python/common/save_utils.py `CheckpointSaver`
(SURVEY.md C9, §3.6): versioned checkpoint directories, keep-max rotation,
restore-on-relaunch.  TPU-native differences: Orbax writes sharded arrays
from the mesh directly (async) — the reference's per-PS-shard serialization
has no equivalent because there are no PS processes; preemption-aware
save-on-signal hooks into the pod manager instead of the PS.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)


class CheckpointSaver:
    def __init__(
        self,
        checkpoint_dir: str,
        keep_max: int = 3,
        async_save: bool = True,
    ):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(checkpoint_dir)
        os.makedirs(self._dir, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep_max,
                enable_async_checkpointing=async_save,
            ),
        )

    def save(self, state, force: bool = False) -> bool:
        import orbax.checkpoint as ocp

        step = int(state.step)
        saved = self._mngr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        if saved:
            logger.info("Checkpoint saved at step %d", step)
        return saved

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def all_steps(self):
        return list(self._mngr.all_steps())

    def restore_step(self, step: int, template: Any) -> Optional[Any]:
        """Restore a SPECIFIC checkpointed step into `template`'s
        shardings (eval-at-version: score the model the master asked
        about, not whatever the leasing worker currently holds)."""
        import jax
        import orbax.checkpoint as ocp

        if step not in self._mngr.all_steps():
            return None
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None)
            )
            if hasattr(x, "shape")
            else x,
            template,
        )
        restored = self._mngr.restore(
            step, args=ocp.args.StandardRestore(abstract)
        )
        logger.info("Restored checkpoint step %d (eval-at-version)", step)
        return restored

    def maybe_restore(self, template: Any) -> Optional[Any]:
        """Restore the newest checkpoint into the sharding/structure of
        `template` (an abstract or concrete train state)."""
        import jax
        import orbax.checkpoint as ocp

        step = self._mngr.latest_step()
        if step is None:
            return None
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None)
            )
            if hasattr(x, "shape")
            else x,
            template,
        )
        restored = self._mngr.restore(
            step, args=ocp.args.StandardRestore(abstract)
        )
        logger.info("Restored checkpoint step %d", step)
        return restored

    def wait_until_finished(self):
        self._mngr.wait_until_finished()

    def close(self):
        self._mngr.close()
