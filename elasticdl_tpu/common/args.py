"""The shared argparse surface.

Parity: reference python/common/args.py (SURVEY.md C21).  As in the
reference, one flag namespace is shared by client -> master -> worker and
argv is the config wire format: the client re-serializes parsed flags into
the master pod command, the master into worker commands
(`build_arguments_from_parsed_result`).
"""

from __future__ import annotations

import argparse
from itertools import chain


def pos_int(value):
    ivalue = int(value)
    if ivalue <= 0:
        raise argparse.ArgumentTypeError(f"{value} is not a positive integer")
    return ivalue


def non_neg_int(value):
    ivalue = int(value)
    if ivalue < 0:
        raise argparse.ArgumentTypeError(f"{value} is negative")
    return ivalue


def str2bool(value):
    if isinstance(value, bool):
        return value
    if value.lower() in ("yes", "true", "t", "y", "1"):
        return True
    if value.lower() in ("no", "false", "f", "n", "0"):
        return False
    raise argparse.ArgumentTypeError(f"Boolean value expected, got {value}")


def add_common_params(parser: argparse.ArgumentParser):
    parser.add_argument(
        "--job_name", default="elasticdl-job", help="Job / pod-name prefix"
    )
    parser.add_argument("--namespace", default="default")
    parser.add_argument(
        "--distribution_strategy",
        default="AllReduce",
        choices=["Local", "AllReduce", "ParameterServer"],
        help="ParameterServer is accepted for reference-CLI compatibility "
        "and maps onto the sharded-mesh path (no PS pods on TPU).",
    )
    parser.add_argument("--master_addr", default="", help="host:port of master")
    parser.add_argument("--port", type=pos_int, default=50001)
    parser.add_argument("--num_workers", type=pos_int, default=1)
    parser.add_argument("--num_minibatches_per_task", type=pos_int, default=8)
    parser.add_argument("--log_level", default="INFO")
    parser.add_argument("--image_name", default="")
    parser.add_argument("--worker_resource_request", default="cpu=1,memory=4096Mi")
    parser.add_argument("--worker_resource_limit", default="")
    parser.add_argument("--worker_pod_priority", default="")
    parser.add_argument("--restart_policy", default="Never")
    parser.add_argument(
        "--volume", default="",
        help="Pod volume mounts, reference syntax: "
        "'host_path=/a,mount_path=/b' or 'claim_name=pvc,mount_path=/b'; "
        "multiple entries separated by ';'.  Mounted into the master pod "
        "and every worker pod (e.g. the --compilation_cache_dir volume).",
    )
    parser.add_argument("--image_pull_policy", default="IfNotPresent")
    parser.add_argument(
        "--need_tf_config", type=str2bool, default=False, nargs="?", const=True
    )
    parser.add_argument(
        "--use_fake_k8s", type=str2bool, default=False,
        help="Use the in-memory fake cluster instead of the Kubernetes API "
        "(dev/test: exercises the full elastic control plane with no "
        "cluster)",
    )
    parser.add_argument(
        "--use_process_k8s", type=str2bool, default=False,
        help="Run worker pods as local OS subprocesses (single-machine "
        "e2e: the full master+worker entry points, rendezvous and "
        "jax.distributed bootstrap with no Kubernetes — the minikube-CI "
        "equivalent)",
    )
    parser.add_argument(
        "--workers_per_group", type=pos_int, default=1,
        help="Slice-granular failure handling (TPU: one preempted host "
        "stalls the whole slice's ICI collectives).  Workers are "
        "partitioned into groups of this size; when one member truly "
        "fails, the surviving members are proactively restarted "
        "(budget-free) instead of each waiting out its wedge-watchdog "
        "grace.  1 = per-worker granularity (the reference's model).",
    )
    parser.add_argument(
        "--preemption_notice_file", default="",
        help="Path polled for an upcoming-disruption notice (GKE TPU "
        "maintenance event / spot reclaim projected into the pod by a "
        "downward-API volume or node-watcher sidecar).  When the file "
        "appears the worker drains at the next task boundary and "
        "flushes a checkpoint — ahead of the SIGTERM.  'gce-metadata' "
        "polls the instance metadata server instead of a file.",
    )
    parser.add_argument(
        "--telemetry_port", type=non_neg_int, default=0,
        help="HTTP port for /metrics (Prometheus text), /healthz and "
        "/varz on this role (0 = ephemeral).  Workers always bind an "
        "ephemeral port: their argv is the master's re-serialized argv, "
        "so a fixed port would collide on shared hosts.",
    )
    parser.add_argument(
        "--event_log", default="",
        help="Append-only JSONL span-event log (task dispatch/claim/"
        "train/report, checkpoint save/restore, hot reload, elastic "
        "recovery).  The master exports the path to its workers via "
        "ELASTICDL_EVENT_LOG so one file correlates the whole cluster "
        "(docs/OBSERVABILITY.md).",
    )
    parser.add_argument(
        "--straggler_multiple", type=float, default=3.0,
        help="Flag a worker as a straggler when its mean task duration "
        "exceeds this multiple of the fleet-wide median (rolling window "
        "of recent tasks).  Flags surface in Master.snapshot()/varz, "
        "the master_straggler_workers gauge, straggler_detected span "
        "events and `elasticdl top`.  0 disables detection.",
    )
    parser.add_argument(
        "--straggler_min_tasks", type=pos_int, default=3,
        help="Minimum completed tasks per worker (and workers in the "
        "fleet) before straggler detection may flag anyone — avoids "
        "flagging on compile-warmup noise.",
    )
    # ---- policy engine (master/policy.py, docs/ROBUSTNESS.md) --------
    parser.add_argument(
        "--policy_interval", type=float, default=0.0,
        help="Seconds between policy-engine ticks (straggler eviction + "
        "autoscaling).  0 (the default) disables the control loop; the "
        "sensors keep running either way.",
    )
    parser.add_argument(
        "--min_workers", type=pos_int, default=1,
        help="Autoscaling floor: the policy engine never scales the "
        "fleet below this many workers.",
    )
    parser.add_argument(
        "--max_workers", type=int, default=0,
        help="Autoscaling ceiling.  0 means --num_workers (a fixed "
        "fleet unless raised).",
    )
    parser.add_argument(
        "--straggler_dwell_s", type=float, default=30.0,
        help="A straggler flag must persist this long before the policy "
        "engine evicts the worker — transient flags clear on their own.",
    )
    parser.add_argument(
        "--eviction_budget", type=pos_int, default=2,
        help="Lifetime cap on policy-engine evictions; a noisy detector "
        "must not be able to churn the fleet.",
    )
    parser.add_argument(
        "--eviction_cooldown_s", type=float, default=60.0,
        help="Minimum seconds between two policy-engine evictions.",
    )
    parser.add_argument(
        "--backlog_per_worker", type=float, default=4.0,
        help="Scale up when queued tasks per alive worker exceed this "
        "for --backlog_ticks consecutive policy ticks.",
    )
    parser.add_argument(
        "--backlog_ticks", type=pos_int, default=3,
        help="Consecutive over-threshold ticks before a backlog "
        "scale-up (hysteresis).",
    )
    parser.add_argument(
        "--data_wait_share", type=float, default=0.6,
        help="Scale down when the fleet-wide data_wait share of step "
        "time exceeds this for --data_wait_ticks consecutive ticks "
        "(input-starved workers add cost, not throughput).",
    )
    parser.add_argument(
        "--data_wait_ticks", type=pos_int, default=3,
        help="Consecutive over-threshold ticks before a data_wait "
        "scale-down (hysteresis).",
    )
    parser.add_argument(
        "--scale_step", type=pos_int, default=1,
        help="Workers added/removed per policy action, rounded to whole "
        "--workers_per_group slice groups.",
    )
    parser.add_argument(
        "--scale_hold_ticks", type=pos_int, default=2,
        help="Quiet ticks after any scale action before the next one — "
        "the fleet must re-converge before the signals mean anything.",
    )
    parser.add_argument(
        "--wedge_grace_s", type=float, default=20.0,
        help="Seconds a rank may lag a membership-epoch change before its "
        "watchdog assumes it is wedged in a collective with a dead peer "
        "and restarts the process",
    )
    parser.add_argument(
        "--coordinator_port", type=pos_int, default=51001,
        help="Port of the JAX coordination service bound by rank 0; the "
        "rendezvous serves rank 0's address + this port as the "
        "coordinator address",
    )
    parser.add_argument(
        "--rpc_retry_budget_s", type=float, default=0.0,
        help="Max elapsed seconds of backed-off retries any single "
        "control-plane RPC may consume before the worker gives up and "
        "exits with code 45 (charged relaunch).  0 defers to the "
        "ELASTICDL_RPC_MAX_ELAPSED_S env var, default 120 "
        "(docs/ROBUSTNESS.md).",
    )
    parser.add_argument(
        "--compilation_cache_dir", default="",
        help="Persistent XLA-executable cache directory.  A relaunched "
        "worker then LOADS the train-step executable instead of "
        "recompiling it, cutting elastic recovery by the ~20-40s compile "
        "— the AOT mitigation SURVEY.md hard part 1 calls for.  Empty "
        "disables.  Re-serialized into worker pod commands like every "
        "flag; on a real cluster pair it with --volume so the directory "
        "is a mount shared across pod relaunches (e.g. --volume "
        "'claim_name=cache,mount_path=/cache' "
        "--compilation_cache_dir /cache).",
    )
    # ---- serving fleet (master/serving_fleet.py, docs/SERVING.md) ----
    parser.add_argument(
        "--serving_replicas", type=non_neg_int, default=0,
        help="Serving replicas the master places and supervises behind "
        "the job (docs/SERVING.md \"Fleet\").  0 (the default) disables "
        "the serving fleet entirely.",
    )
    parser.add_argument(
        "--serving_probe_interval", type=float, default=0.0,
        help="Seconds between fleet health-probe ticks (probe every "
        "replica's Health RPC, relaunch the dead, sequence rolling "
        "reloads).  0 disables the background loop; tests tick by hand.",
    )
    parser.add_argument(
        "--serving_probe_failures", type=pos_int, default=3,
        help="Consecutive failed health probes before a serving replica "
        "is relaunched (pod-phase death relaunches immediately).",
    )
    parser.add_argument(
        "--serving_step_skew_slo", type=non_neg_int, default=0,
        help="Max allowed cross-replica model_step spread.  A rolling "
        "reload that would exceed it is refused (exported as the "
        "serving_fleet_model_step_skew_steps gauge).  0 disables the "
        "bound.",
    )
    parser.add_argument(
        "--serving_port", type=pos_int, default=50061,
        help="gRPC port each serving replica listens on (the fleet "
        "manager probes {replica-service}:{this port}).",
    )
    # ---- serving autoscaler + backpressure (master/policy.py
    #      ServingPolicyEngine, docs/SERVING.md "Autoscaling &
    #      backpressure") ----
    parser.add_argument(
        "--max_serving_replicas", type=non_neg_int, default=0,
        help="Upper bound the serving policy engine may scale the fleet "
        "to.  0 (the default) disables serving autoscaling entirely; "
        "the fleet stays at --serving_replicas.",
    )
    parser.add_argument(
        "--min_serving_replicas", type=non_neg_int, default=0,
        help="Lower bound the serving policy engine may scale the fleet "
        "down to.  0 defaults to --serving_replicas (the placed size).",
    )
    parser.add_argument(
        "--serving_policy_interval", type=float, default=0.0,
        help="Seconds between serving policy engine ticks (SLO burn / "
        "shed-ratio / batch-fill signals -> at most one scale action).  "
        "0 disables the background loop; tests tick by hand.",
    )
    parser.add_argument(
        "--serving_burn_threshold", type=float, default=1.0,
        help="Fast-window SLO burn rate at or above which a serving "
        "scale-up streak accrues (1.0 = spending exactly the error "
        "budget).",
    )
    parser.add_argument(
        "--serving_shed_threshold", type=float, default=0.02,
        help="Windowed whole-fleet shed ratio at or above which a "
        "serving scale-up streak accrues (capacity exhaustion evidence "
        "even before an SLO burns).",
    )
    parser.add_argument(
        "--serving_fill_low", type=float, default=0.2,
        help="Mean healthy-replica batch fill at or below which a calm "
        "fleet accrues a scale-down streak (paying for replicas the "
        "batcher cannot fill).",
    )
    parser.add_argument(
        "--serving_up_ticks", type=pos_int, default=2,
        help="Consecutive overloaded ticks before the serving policy "
        "engine scales up (hysteresis entry gate).",
    )
    parser.add_argument(
        "--serving_down_ticks", type=pos_int, default=3,
        help="Consecutive calm, underfilled ticks before the serving "
        "policy engine scales down.",
    )
    parser.add_argument(
        "--serving_scale_step", type=pos_int, default=1,
        help="Replicas added or retired per serving scale action.",
    )
    parser.add_argument(
        "--serving_scale_hold_ticks", type=non_neg_int, default=2,
        help="Quiet ticks after any serving scale action before the "
        "next one — the fleet must re-converge (probe, warm, drain) "
        "before the signals mean anything again.",
    )
    parser.add_argument(
        "--serving_shed_window_s", type=float, default=30.0,
        help="Metric-history window the serving policy engine computes "
        "its shed ratio over (a past spike ages out of the evidence).",
    )
    parser.add_argument(
        "--backpressure_threshold", type=float, default=0.25,
        help="serving_pressure (SLO burn rate x fleet shed ratio) above "
        "which the online pipeline slows its stream poll/arm cadence — "
        "train yields to serve until the pressure clears.",
    )
    parser.add_argument(
        "--backpressure_stride", type=pos_int, default=4,
        help="While backpressured, the online pipeline polls/arms only "
        "every this-many-th tick (queued tasks still drain every "
        "tick).",
    )
    # ---- metric history + SLOs (common/history.py, common/slo.py,
    #      docs/OBSERVABILITY.md "Metric history & SLOs") ----
    parser.add_argument(
        "--history_interval", type=float, default=0.0,
        help="Seconds between metric-history samples (ring-buffer "
        "recorder over every /metrics registry; the evidence the SLO "
        "evaluator and `elasticdl slo` read).  0 disables the sampling "
        "thread; tests tick by hand.",
    )
    parser.add_argument(
        "--history_capacity", type=pos_int, default=512,
        help="Samples retained per metric series in the history ring "
        "buffer (oldest evicted first).  Must cover the slowest SLO "
        "window: capacity * --history_interval >= slow_window_s.",
    )
    parser.add_argument(
        "--slo_interval", type=float, default=0.0,
        help="Seconds between SLO evaluator ticks (burn-rate math over "
        "the metric history; emits slo_breach/slo_recovered span "
        "events).  0 disables the thread; tests tick by hand.",
    )
    parser.add_argument(
        "--slo_staleness_p99_s", type=float, default=60.0,
        help="Objective of the staleness_p99 SLO: 99%% of predict "
        "responses must be served from a checkpoint no older than this "
        "many seconds behind the latest produced one.",
    )
    # ---- request tracing + incident flight recorder (common/flight.py,
    #      docs/OBSERVABILITY.md "Request tracing & incident bundles") --
    parser.add_argument(
        "--trace_sample_rate", type=float, default=1.0,
        help="Fraction of routed Predict requests whose predict_span "
        "is recorded end to end (deterministic every-k'th sampling, "
        "k = round(1/rate); 0 disables).  Error, shed, and failover "
        "outcomes always capture regardless of the rate.",
    )
    parser.add_argument(
        "--incident_dir", default="",
        help="Directory the incident flight recorder writes bundles "
        "into on an slo_breach, policy eviction, or terminal reload "
        "refusal (one JSON dir per incident: recent request spans, "
        "decisions, metric-history windows, Master.snapshot(), fault "
        "stats).  Empty disables capture; the forensic rings still "
        "fill.  Render with `elasticdl incident`.",
    )
    parser.add_argument(
        "--incident_ring", type=pos_int, default=256,
        help="Recent predict_span and decision events retained in the "
        "flight recorder's in-memory rings (each; oldest evicted "
        "first).",
    )
    parser.add_argument(
        "--incident_max_bundles", type=pos_int, default=8,
        help="Bundles kept under --incident_dir before the oldest is "
        "rotated out — soak runs cannot fill the disk.",
    )


def add_model_params(parser: argparse.ArgumentParser):
    parser.add_argument(
        "--model_zoo", required=False, default="model_zoo",
        help="Directory containing model definitions",
    )
    parser.add_argument(
        "--model_def", required=False, default="",
        help="module.function returning the model, e.g. "
        "mnist.mnist_functional_api.custom_model",
    )
    parser.add_argument("--model_params", default="", help="free-form kwargs")
    parser.add_argument(
        "--arena_dtype", default="", choices=["", "float32", "int8"],
        help="Embedding arena storage dtype: int8 stores rows as "
        "quantized codes with per-row fp32 scales (docs/PERF.md "
        "'Quantized arena'); empty defers to the model's default "
        "(float32).  Forwarded into model_params for zoos whose "
        "custom_model accepts arena_dtype.",
    )
    parser.add_argument(
        "--store_cache_dtype", default="",
        choices=["", "float32", "int8"],
        help="Tiered-store device hot-row cache storage dtype: int8 "
        "stores cache rows as quantized codes with per-row fp32 scales "
        "(docs/PERF.md §4).  Empty defers to the model's default "
        "(float32).  Forwarded into model_params as cache_dtype for "
        "zoos whose custom_model accepts it; zoos without tiered "
        "support ignore it.",
    )
    parser.add_argument("--dataset_fn", default="feed")
    parser.add_argument("--loss", default="loss")
    parser.add_argument("--optimizer", default="optimizer")
    parser.add_argument("--eval_metrics_fn", default="eval_metrics_fn")
    parser.add_argument("--custom_data_reader", default="custom_data_reader")
    parser.add_argument("--prediction_outputs_processor", default="")
    parser.add_argument("--callbacks", default="callbacks")


def add_train_params(parser: argparse.ArgumentParser):
    parser.add_argument("--minibatch_size", type=pos_int, default=64)
    parser.add_argument(
        "--steps_per_execution", type=pos_int, default=1,
        help="Dispatch this many train steps as ONE compiled program "
        "(lax.scan over a batch stack).  Amortizes per-dispatch "
        "overhead — significant on remote/tunneled TPU runtimes; "
        "losses/metrics are still recorded per step.",
    )
    parser.add_argument("--num_epochs", type=pos_int, default=1)
    parser.add_argument(
        "--grads_to_wait", type=pos_int, default=1,
        help="Accepted for reference-CLI compatibility (the sync-PS "
        "accumulation knob).  Meaningless here: every step is already "
        "bulk-synchronous over the mesh — gradients from all data "
        "shards reduce inside the compiled step.",
    )
    parser.add_argument("--training_data", default="")
    parser.add_argument("--validation_data", default="")
    parser.add_argument("--prediction_data", default="")
    parser.add_argument("--evaluation_steps", type=non_neg_int, default=0)
    parser.add_argument("--evaluation_start_delay_secs", type=non_neg_int, default=0)
    parser.add_argument("--evaluation_throttle_secs", type=non_neg_int, default=0)
    parser.add_argument("--checkpoint_steps", type=non_neg_int, default=0)
    parser.add_argument("--checkpoint_dir", default="")
    parser.add_argument("--keep_checkpoint_max", type=non_neg_int, default=3)
    parser.add_argument("--output", default="", help="final model export dir")
    parser.add_argument(
        "--export_saved_model", type=str2bool, default=False, nargs="?",
        const=True,
        help="Also export a TF SavedModel under <output>/saved_model "
        "(forward pass staged via jax2tf, polymorphic batch dim) — the "
        "serving handoff the reference's SavedModel export provided.  "
        "Mesh-manual models (ring attention / GPipe) do not convert; the "
        "msgpack export is always written regardless.",
    )
    parser.add_argument(
        "--checkpoint_dir_for_init", default="",
        help="checkpoint to warm-start from",
    )
    parser.add_argument(
        "--profile_dir", default="",
        help="capture a JAX profiler trace (Perfetto/XPlane, readable in "
        "TensorBoard) of the first training task into this directory",
    )
    parser.add_argument(
        "--tensorboard_log_dir", default="",
        help="write train-loss/steps-per-sec/eval scalars (workers) and "
        "aggregated eval metrics (master) as TensorBoard event files "
        "under this directory",
    )
    parser.add_argument("--task_fault_tolerance", type=str2bool, default=True)
    parser.add_argument(
        "--relaunch_on_worker_failure", type=non_neg_int, default=3,
        help="max relaunches per failed worker pod",
    )
    parser.add_argument("--use_bf16", type=str2bool, default=True,
                        help="compute in bfloat16 on the MXU where safe")
    parser.add_argument(
        "--compact_wire", type=str2bool, default=False,
        help="ship batches in the zoo's compact device wire format "
        "(feed_bulk_compact, elasticdl_tpu.data.wire) when the zoo "
        "provides one — fewer host->device bytes per example on "
        "bandwidth-limited links",
    )
    parser.add_argument(
        "--wire_format", default="", choices=["", "plain", "compact", "dedup"],
        help="host->device wire format: plain (feed_bulk), compact "
        "(feed_bulk_compact, same as --compact_wire=true), or dedup "
        "(feed_bulk_dedup — host-hashed ids dedup'd per field into "
        "frequency-ranked uniques + a 1-byte inverse plane; fewest "
        "bytes/example on skewed id streams).  Empty defers to "
        "--compact_wire.  SPMD slice-local sharding ignores 'dedup' "
        "(per-rank unique counts diverge -> collective shape mismatch)",
    )
    parser.add_argument("--data_reader_params", default="")
    parser.add_argument("--records_per_task", type=pos_int, default=4096)
    parser.add_argument(
        "--task_lease_timeout_s", type=pos_int, default=900,
        help="re-queue a leased task if not reported within this window",
    )


def add_evaluate_params(parser):
    parser.add_argument("--minibatch_size", type=pos_int, default=64)
    parser.add_argument("--validation_data", default="")
    parser.add_argument("--checkpoint_dir_for_init", default="")
    parser.add_argument("--records_per_task", type=pos_int, default=4096)
    parser.add_argument("--data_reader_params", default="")


def add_predict_params(parser):
    parser.add_argument("--minibatch_size", type=pos_int, default=64)
    parser.add_argument("--prediction_data", default="")
    parser.add_argument("--checkpoint_dir_for_init", default="")
    parser.add_argument("--records_per_task", type=pos_int, default=4096)
    parser.add_argument("--data_reader_params", default="")


def add_serve_params(parser):
    """`elasticdl serve`: online inference from an export or a live
    checkpoint directory (docs/SERVING.md)."""
    parser.add_argument(
        "--export_dir", default="",
        help="directory with params.msgpack + export_meta.json "
        "(from --output of a training job)",
    )
    parser.add_argument(
        "--checkpoint_dir", default="",
        help="serve the newest verified checkpoint and hot-reload as "
        "the trainer writes new steps (alternative to --export_dir)",
    )
    parser.add_argument("--port", type=non_neg_int, default=50061)
    parser.add_argument(
        "--batch_buckets", default="1,4,16,64",
        help="comma-separated batch sizes to precompile; requests are "
        "padded to the nearest bucket",
    )
    parser.add_argument(
        "--max_batch_latency_ms", type=float, default=10.0,
        help="max time a queued request waits for batch-mates",
    )
    parser.add_argument(
        "--max_queue_rows", type=non_neg_int, default=0,
        help="admission-control bound on queued rows "
        "(0 = 4x the largest bucket)",
    )
    parser.add_argument(
        "--reject_oversized", type=str2bool, default=False,
        help="reject requests larger than the largest bucket instead "
        "of splitting them",
    )
    parser.add_argument(
        "--reload_poll_seconds", type=float, default=10.0,
        help="checkpoint-directory poll interval for hot reload",
    )
    parser.add_argument(
        "--telemetry_port", type=non_neg_int, default=0,
        help="HTTP port for /metrics, /healthz and /varz on the serving "
        "replica (0 = ephemeral)",
    )
    parser.add_argument(
        "--event_log", default="",
        help="append-only JSONL span-event log (hot-reload events join "
        "the cluster's trace stream)",
    )
    parser.add_argument(
        "--feature_spec", default="",
        help="serving signature for --checkpoint_dir mode when no "
        "export_meta.json is available: inline JSON "
        '{"name": {"shape": [..], "dtype": ".."}} or a path to an '
        "export_meta.json",
    )


def add_trace_params(parser: argparse.ArgumentParser):
    """`elasticdl trace`: offline event-log analysis (client/trace.py)."""
    parser.add_argument(
        "event_log",
        help="span-event JSONL written by --event_log (a rolled "
        "<path>.1 generation, if present, is read automatically)",
    )
    parser.add_argument(
        "--chrome", default="",
        help="write Chrome trace-event JSON here; open in "
        "https://ui.perfetto.dev or chrome://tracing",
    )
    parser.add_argument(
        "--summary", action="store_true",
        help="print per-worker task-latency quantiles, slowest tasks "
        "and the aggregate step-phase breakdown (default when --chrome "
        "is not given)",
    )
    parser.add_argument(
        "--slowest", type=non_neg_int, default=5,
        help="how many slowest tasks the summary lists",
    )


def add_lineage_params(parser: argparse.ArgumentParser):
    """`elasticdl lineage`: per-window freshness waterfalls from an
    event log (client/lineage.py)."""
    parser.add_argument(
        "event_log",
        help="span-event JSONL written by --event_log (a rolled "
        "<path>.1 generation, if present, is read automatically)",
    )
    parser.add_argument(
        "--slowest", type=non_neg_int, default=3,
        help="how many slowest windows get a full waterfall",
    )
    parser.add_argument(
        "--window", type=int, default=None,
        help="render the waterfall for this one window id only",
    )


def add_incident_params(parser: argparse.ArgumentParser):
    """`elasticdl incident`: postmortem reports from flight-recorder
    bundles (client/incident.py)."""
    parser.add_argument(
        "incident_dir",
        help="directory the master's --incident_dir flight recorder "
        "wrote bundles into",
    )
    parser.add_argument(
        "--bundle", default="",
        help="bundle name (or unambiguous prefix) to render a full "
        "postmortem report for; omitted = list all bundles",
    )
    parser.add_argument(
        "--spans", type=non_neg_int, default=10,
        help="how many of the slowest request spans the report lists",
    )


def parse_master_args(argv=None):
    parser = argparse.ArgumentParser(description="elasticdl-tpu master")
    add_common_params(parser)
    add_model_params(parser)
    add_train_params(parser)
    parser.add_argument("--job_type", default="train",
                        choices=["train", "evaluate", "predict"])
    args, _ = parser.parse_known_args(argv)
    return args


def parse_worker_args(argv=None):
    parser = argparse.ArgumentParser(description="elasticdl-tpu worker")
    add_common_params(parser)
    add_model_params(parser)
    add_train_params(parser)
    parser.add_argument("--worker_id", type=int, default=0)
    parser.add_argument("--job_type", default="train")
    args, _ = parser.parse_known_args(argv)
    return args


def build_arguments_from_parsed_result(args, filter_args=None) -> list:
    """Re-serialize a parsed namespace back into argv (the config wire
    format between client -> master -> worker pods, as in the reference)."""
    items = vars(args).items()
    if filter_args:
        items = [(k, v) for k, v in items if k not in filter_args]
    arguments = []
    for key, value in items:
        if value is None or value == "":
            continue
        arguments.append("--" + key)
        arguments.append(str(value))
    return arguments


def wrap_python_args_with_string(args: list) -> list:
    """Quote values so argv survives a shell boundary in a pod command."""
    return list(chain.from_iterable(
        (a,) if a.startswith("--") else (f"'{a}'",) for a in args
    ))
