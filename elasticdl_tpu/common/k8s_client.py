"""Kubernetes client abstraction + in-memory fake.

Parity: reference python/common/k8s_client.py (SURVEY.md C4): the master
creates/watches/deletes worker pods directly through the Kubernetes API (no
operator/CRD).  The fake records calls and lets tests inject synthetic pod
events — the reference's own test strategy for failure handling
(SURVEY.md §4.3).

The real client is gated: the `kubernetes` package is not installed in this
environment, so `K8sClient` raises with instructions at construction unless
it is.  TPU-specific concern carried in pod specs: workers are provisioned
per TPU *slice* (a preempted host kills the slice's ICI collectives, so the
restart unit is the slice — SURVEY.md §7 hard part 3).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from elasticdl_tpu.common.constants import PodStatus, PodType
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)

# (pod_name, phase, pod_address, exit_code) — address is "" until the
# cluster layer knows the pod's reachable IP (real k8s can emit RUNNING
# before the IP is assigned; workers self-report via keep_alive to close
# that gap); exit_code is the container's exit status when phase is
# terminal (None when unknown), letting the pod manager tell intentional
# self-restarts from crashes.
EventCallback = Callable[[str, str, str, Optional[int]], None]


@dataclass
class PodSpec:
    name: str
    pod_type: str  # "worker" | "master"
    worker_id: int = -1
    image: str = ""
    command: List[str] = field(default_factory=list)
    resources: Dict[str, str] = field(default_factory=dict)
    priority_class: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    # parsed --volume entries (parse_volumes): each a dict with
    # "mount_path" plus one of "host_path" / "claim_name"
    volumes: List[Dict[str, str]] = field(default_factory=list)


def parse_volumes(volume: str) -> List[Dict[str, str]]:
    """Parse the --volume flag (reference syntax, SURVEY.md C21):
    `host_path=/a,mount_path=/b` or `claim_name=pvc,mount_path=/b`;
    multiple volumes separated by `;`.  The shared --compilation_cache_dir
    volume rides this flag like any other mount."""
    out: List[Dict[str, str]] = []
    for part in (volume or "").split(";"):
        part = part.strip()
        if not part:
            continue
        entry: Dict[str, str] = {}
        for kv in part.split(","):
            kv = kv.strip()
            if not kv:
                continue
            if "=" not in kv:
                raise ValueError(
                    f"--volume entry {kv!r} is not key=value "
                    "(expected host_path=/a,mount_path=/b or "
                    "claim_name=pvc,mount_path=/b)"
                )
            key, _, value = kv.partition("=")
            key, value = key.strip(), value.strip()
            if key not in ("host_path", "claim_name", "mount_path"):
                raise ValueError(
                    f"--volume key {key!r} not supported (host_path, "
                    "claim_name, mount_path)"
                )
            if not value:
                raise ValueError(f"--volume key {key!r} has empty value")
            entry[key] = value
        if "host_path" in entry and "claim_name" in entry:
            raise ValueError(
                f"--volume entry {part!r} sets both host_path and "
                "claim_name; pick one source"
            )
        if "mount_path" not in entry or not (
            "host_path" in entry or "claim_name" in entry
        ):
            raise ValueError(
                f"--volume entry {part!r} needs mount_path plus "
                "host_path or claim_name"
            )
        out.append(entry)
    return out


class AbstractK8sClient:
    def create_pod(self, spec: PodSpec) -> None:
        raise NotImplementedError

    def create_service(
        self, name: str, selector: Dict[str, str], port: int
    ) -> None:
        """Expose pods matching `selector` at DNS name `name`:`port` —
        worker pods reach the master via `{job_name}-master:{port}`, which
        only resolves if a Service fronts the master pod."""
        raise NotImplementedError

    def delete_pod(self, name: str) -> None:
        raise NotImplementedError

    def get_pod_phase(self, name: str) -> str:
        raise NotImplementedError

    def start_watch(self, callback: EventCallback) -> None:
        raise NotImplementedError

    def list_pods(self) -> List[Tuple[str, int, str, str]]:
        """Existing pods of this job as (pod_name, worker_id, phase,
        address).  A replacement master pod calls this to ADOPT live
        workers instead of double-launching them (master fault
        tolerance)."""
        return []

    def get_pod_labels(self, name: str) -> Dict[str, str]:
        """Labels stamped on the pod at creation (k8s metadata).  Used by
        a replacement master to recover exact slice-group identity during
        adoption; clients without label storage may return {} (the pod
        manager falls back to packed groups)."""
        return {}

    def master_host(self, job_name: str) -> str:
        """Hostname worker pods use to reach the master.  Real clusters
        resolve the master Service's DNS name; process-backed local
        clusters are loopback."""
        return f"{job_name}-master"


class FakeK8sClient(AbstractK8sClient):
    """In-memory cluster: pods transition Pending -> Running on create;
    tests drive failures/preemptions via `emit`."""

    def __init__(self):
        self._lock = threading.Lock()
        self.pods: Dict[str, PodSpec] = {}
        self.phases: Dict[str, str] = {}
        self.create_calls: List[PodSpec] = []
        self.delete_calls: List[str] = []
        self._callback: Optional[EventCallback] = None

    def create_pod(self, spec: PodSpec) -> None:
        with self._lock:
            self.pods[spec.name] = spec
            self.phases[spec.name] = PodStatus.PENDING
            self.create_calls.append(spec)
        self._emit(spec.name, PodStatus.PENDING)
        with self._lock:
            self.phases[spec.name] = PodStatus.RUNNING
        # Fabricated per-pod address, mirroring pod.status.pod_ip.
        self._emit(spec.name, PodStatus.RUNNING, self._pod_address(spec))

    @staticmethod
    def _pod_address(spec: PodSpec) -> str:
        """One formula for the fabricated pod IP — create_pod events and
        list_pods (master adoption) must agree on it."""
        return f"10.0.0.{spec.worker_id + 1}"

    def create_service(
        self, name: str, selector: Dict[str, str], port: int
    ) -> None:
        with self._lock:
            self.services = getattr(self, "services", {})
            self.services[name] = {"selector": selector, "port": port}

    def delete_pod(self, name: str) -> None:
        with self._lock:
            self.delete_calls.append(name)
            if name not in self.pods:
                return
            self.phases[name] = PodStatus.DELETED
        self._emit(name, PodStatus.DELETED)

    def get_pod_phase(self, name: str) -> str:
        with self._lock:
            return self.phases.get(name, PodStatus.UNKNOWN)

    def get_pod_labels(self, name: str):
        with self._lock:
            spec = self.pods.get(name)
            return dict(spec.labels) if spec is not None else {}

    def list_pods(self):
        with self._lock:
            return [
                (
                    name,
                    spec.worker_id,
                    self.phases.get(name, PodStatus.UNKNOWN),
                    self._pod_address(spec),
                )
                for name, spec in self.pods.items()
                if spec.pod_type == PodType.WORKER
            ]

    def start_watch(self, callback: EventCallback) -> None:
        self._callback = callback

    # ---- test hooks ----------------------------------------------------

    def emit(self, pod_name: str, phase: str, address: str = "",
             exit_code=None):
        """Inject a synthetic pod event (e.g. preemption -> FAILED)."""
        with self._lock:
            self.phases[pod_name] = phase
        self._emit(pod_name, phase, address, exit_code)

    def _emit(self, name: str, phase: str, address: str = "",
              exit_code=None):
        if self._callback is not None:
            self._callback(name, phase, address, exit_code)


class ProcessK8sClient(AbstractK8sClient):
    """Local 'cluster' whose pods are OS subprocesses.

    The e2e equivalent of the reference's minikube CI jobs (SURVEY.md
    §4.4) without Kubernetes: `create_pod` spawns the pod command as a
    child process, a monitor thread maps process exit to pod phases
    (rc==0 -> Succeeded, else Failed), and `delete_pod` terminates the
    child.  Every pod's address is loopback, so the full cluster path —
    master entry point, worker entry point, rendezvous-served coordinator
    address, jax.distributed bootstrap — runs unmodified on one machine."""

    def __init__(self, extra_env: Optional[Dict[str, str]] = None):
        self._lock = threading.Lock()
        self.pods: Dict[str, PodSpec] = {}
        self.procs: Dict[str, "subprocess.Popen"] = {}
        self.phases: Dict[str, str] = {}
        self.create_calls: List[PodSpec] = []
        self._output: Dict[str, List[bytes]] = {}
        self._extra_env = dict(extra_env or {})
        self._callback: Optional[EventCallback] = None
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    def master_host(self, job_name: str) -> str:
        return "127.0.0.1"

    def create_pod(self, spec: PodSpec) -> None:
        import os
        import subprocess

        env = dict(os.environ)
        env.update(self._extra_env)
        with self._lock:
            self.pods[spec.name] = spec
            self.create_calls.append(spec)
            self.phases[spec.name] = PodStatus.PENDING
        self._emit(spec.name, PodStatus.PENDING)
        proc = subprocess.Popen(
            spec.command,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        # Drain continuously: a child that fills an unread 64KB pipe blocks
        # on write() and wedges — indistinguishable from a real hang.
        chunks: List[bytes] = []

        def drain():
            for line in proc.stdout:
                chunks.append(line)

        threading.Thread(target=drain, daemon=True).start()
        with self._lock:
            self.procs[spec.name] = proc
            self._output[spec.name] = chunks
            self.phases[spec.name] = PodStatus.RUNNING
        self._emit(spec.name, PodStatus.RUNNING, "127.0.0.1")

    def delete_pod(self, name: str) -> None:
        with self._lock:
            proc = self.procs.get(name)
            self.phases[name] = PodStatus.DELETED
        if proc is not None and proc.poll() is None:
            proc.terminate()
        # Emit as soon as deletion is INITIATED (real k8s delivers the
        # deletionTimestamp event immediately too): the membership bump
        # then reaches surviving ranks before the condemned process — which
        # handles SIGTERM by finishing its current task — has left, so
        # survivors re-mesh gracefully at the next task boundary instead
        # of wedging in a collective against a vanished peer.
        self._emit(name, PodStatus.DELETED)
        if proc is not None and proc.poll() is None:
            try:
                proc.wait(timeout=15)
            except Exception:
                proc.kill()

    def kill_pod(self, name: str) -> None:
        """Hard preemption (test hook): SIGKILL, then the monitor reports
        the death as FAILED exactly like a spot reclaim."""
        with self._lock:
            proc = self.procs.get(name)
        if proc is not None and proc.poll() is None:
            proc.kill()

    def get_pod_phase(self, name: str) -> str:
        with self._lock:
            return self.phases.get(name, PodStatus.UNKNOWN)

    def get_pod_labels(self, name: str):
        with self._lock:
            spec = self.pods.get(name)
            return dict(spec.labels) if spec is not None else {}

    def list_pods(self):
        with self._lock:
            return [
                (
                    name,
                    spec.worker_id,
                    self.phases.get(name, PodStatus.UNKNOWN),
                    "127.0.0.1",
                )
                for name, spec in self.pods.items()
                if spec.pod_type == PodType.WORKER
            ]

    def start_watch(self, callback: EventCallback) -> None:
        self._callback = callback
        self._monitor = threading.Thread(target=self._watch_loop, daemon=True)
        self._monitor.start()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            procs = list(self.procs.values())
        for proc in procs:
            if proc.poll() is None:
                proc.kill()

    def pod_output(self, name: str) -> str:
        with self._lock:
            chunks = list(self._output.get(name, ()))
        return b"".join(chunks).decode(errors="replace")

    def _watch_loop(self):
        import time as _time

        while not self._stop.is_set():
            with self._lock:
                snapshot = [
                    (name, proc)
                    for name, proc in self.procs.items()
                    if self.phases.get(name) == PodStatus.RUNNING
                ]
            for name, proc in snapshot:
                rc = proc.poll()
                if rc is None:
                    continue
                phase = (
                    PodStatus.SUCCEEDED if rc == 0 else PodStatus.FAILED
                )
                with self._lock:
                    # delete_pod may have won the race; keep its verdict.
                    if self.phases.get(name) != PodStatus.RUNNING:
                        continue
                    self.phases[name] = phase
                self._emit(name, phase, exit_code=rc)
            _time.sleep(0.1)

    def _emit(self, name: str, phase: str, address: str = "",
              exit_code=None):
        if self._callback is not None:
            self._callback(name, phase, address, exit_code)


class K8sClient(AbstractK8sClient):
    """Real Kubernetes client (pod create/watch/delete in a namespace)."""

    def __init__(self, namespace: str = "default", job_name: str = "job"):
        try:
            from kubernetes import client, config, watch  # noqa: F401
        except ImportError as exc:
            raise ImportError(
                "The `kubernetes` package is required for cluster mode; "
                "install it in the job image (local/test modes use "
                "FakeK8sClient)."
            ) from exc
        from kubernetes import client, config, watch

        try:
            config.load_incluster_config()
        except Exception:
            config.load_kube_config()
        self._core = client.CoreV1Api()
        self._watch = watch.Watch()
        self._namespace = namespace
        self._job_name = job_name
        self._callback: Optional[EventCallback] = None
        self._client_mod = client

    def create_pod(self, spec: PodSpec) -> None:
        client = self._client_mod
        volumes, mounts = [], []
        for i, entry in enumerate(spec.volumes):
            vol_name = f"vol-{i}"
            if "claim_name" in entry:
                source = dict(
                    persistent_volume_claim=(
                        client.V1PersistentVolumeClaimVolumeSource(
                            claim_name=entry["claim_name"]
                        )
                    )
                )
            else:
                source = dict(
                    host_path=client.V1HostPathVolumeSource(
                        path=entry["host_path"],
                        type="DirectoryOrCreate",
                    )
                )
            volumes.append(client.V1Volume(name=vol_name, **source))
            mounts.append(
                client.V1VolumeMount(
                    name=vol_name, mount_path=entry["mount_path"]
                )
            )
        container = client.V1Container(
            name="main",
            image=spec.image,
            command=spec.command,
            resources=client.V1ResourceRequirements(
                requests=spec.resources or None
            ),
            volume_mounts=mounts or None,
        )
        pod = client.V1Pod(
            metadata=client.V1ObjectMeta(
                name=spec.name,
                labels={
                    "elasticdl-job": self._job_name,
                    "elasticdl-type": spec.pod_type,
                    "elasticdl-worker-id": str(spec.worker_id),
                    **spec.labels,
                },
            ),
            spec=client.V1PodSpec(
                containers=[container],
                restart_policy="Never",
                priority_class_name=spec.priority_class or None,
                volumes=volumes or None,
            ),
        )
        self._core.create_namespaced_pod(self._namespace, pod)

    def create_service(
        self, name: str, selector: Dict[str, str], port: int
    ) -> None:
        client = self._client_mod
        service = client.V1Service(
            metadata=client.V1ObjectMeta(
                name=name, labels={"elasticdl-job": self._job_name}
            ),
            spec=client.V1ServiceSpec(
                selector=selector,
                ports=[client.V1ServicePort(port=port, target_port=port)],
            ),
        )
        self._core.create_namespaced_service(self._namespace, service)

    def delete_pod(self, name: str) -> None:
        self._core.delete_namespaced_pod(name, self._namespace)

    def get_pod_phase(self, name: str) -> str:
        pod = self._core.read_namespaced_pod(name, self._namespace)
        return pod.status.phase

    def get_pod_labels(self, name: str):
        # served from the last list_pods response when possible: adoption
        # calls list_pods first, then labels per pod — without the cache
        # that is N+1 sequential apiserver round-trips per failover
        cached = getattr(self, "_labels_cache", {}).get(name)
        if cached is not None:
            return dict(cached)
        pod = self._core.read_namespaced_pod(name, self._namespace)
        return dict(pod.metadata.labels or {})

    def list_pods(self):
        pods = self._core.list_namespaced_pod(
            self._namespace,
            label_selector=(
                f"elasticdl-job={self._job_name},elasticdl-type=worker"
            ),
        )
        out = []
        self._labels_cache = {}
        for pod in pods.items:
            try:
                worker_id = int(
                    pod.metadata.labels.get("elasticdl-worker-id", -1)
                )
            except (TypeError, ValueError):
                worker_id = -1
            self._labels_cache[pod.metadata.name] = dict(
                pod.metadata.labels or {}
            )
            out.append(
                (
                    pod.metadata.name,
                    worker_id,
                    pod.status.phase,
                    pod.status.pod_ip or "",
                )
            )
        return out

    def start_watch(self, callback: EventCallback) -> None:
        self._callback = callback
        thread = threading.Thread(target=self._watch_loop, daemon=True)
        thread.start()

    def _watch_loop(self):
        import time as _time

        backoff = 1.0
        while True:
            try:
                for event in self._watch.stream(
                    self._core.list_namespaced_pod,
                    self._namespace,
                    label_selector=f"elasticdl-job={self._job_name}",
                ):
                    backoff = 1.0  # healthy stream: reset
                    pod = event["object"]
                    phase = pod.status.phase
                    if event["type"] == "DELETED":
                        phase = PodStatus.DELETED
                    exit_code = None
                    try:
                        for cs in pod.status.container_statuses or []:
                            if cs.state and cs.state.terminated:
                                exit_code = cs.state.terminated.exit_code
                    except AttributeError:
                        pass
                    self._callback(
                        pod.metadata.name, phase,
                        pod.status.pod_ip or "", exit_code,
                    )
            except Exception as exc:
                logger.warning(
                    "k8s watch reconnecting in %.0fs after: %s", backoff, exc
                )
                _time.sleep(backoff)
                backoff = min(backoff * 2, 60.0)
