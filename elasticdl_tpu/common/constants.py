"""Shared constants.  Parity: reference python/common/constants.py
(SURVEY.md C22)."""


class PodStatus:
    INITIAL = "Initial"
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    DELETED = "Deleted"
    UNKNOWN = "Unknown"


class PodType:
    MASTER = "master"
    WORKER = "worker"
    SERVING = "serving"


class JobStatus:
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


class TaskExecCounterKey:
    FAIL_COUNT = "fail_count"
    RECORDS = "records"


class DistributionStrategy:
    LOCAL = "Local"               # single process, in-process master
    ALLREDUCE = "AllReduce"       # elastic DP over the device mesh (psum)
    PARAMETER_SERVER = "ParameterServer"  # accepted for reference CLI
    # compatibility; maps onto the mesh path (no PS processes on TPU).


class WorkerEnv:
    MASTER_ADDR = "ELASTICDL_MASTER_ADDR"
    WORKER_ID = "ELASTICDL_WORKER_ID"
    # The worker's own reachable address, injected via the k8s downward
    # API (pod IP).  Falls back to source-address discovery toward the
    # master when unset (common/net_utils.py).
    WORKER_ADDR = "ELASTICDL_WORKER_ADDR"


# Interval at which workers self-report liveness (+ their address) to the
# master over keep_alive; the master logs workers silent for several
# multiples of this.
KEEP_ALIVE_INTERVAL_S = 10.0


# Default lease duration before a "doing" task is considered abandoned and
# re-queued even without a pod-failure event (belt-and-braces on top of the
# k8s watch failure detector).
DEFAULT_TASK_LEASE_TIMEOUT_S = 15 * 60

GRPC_MAX_MESSAGE_LENGTH = 32 * 1024 * 1024
