"""Program observatory: a process-wide registry of compiled XLA programs.

Every jitted entry point in the system registers here — either by
wrapping the function with :func:`registered_jit` (the normal path) or
by reporting an already-compiled executable via
:func:`register_compiled` (bench / ad-hoc AOT).  The registry records,
per named program and per distinct aval signature:

- compile wall seconds (``worker_program_compile_seconds{program}``
  histogram, injectable clock so tests replay deterministically);
- compile / retrace counts and the distinct-signature count;
- XLA's own cost model (``cost_analysis()`` flops + bytes accessed,
  version-tolerant: dict on new jax, list-of-dict on old) — the same
  numbers bench.py used to compute privately per run.

Joining per-program cost against the step-rate telemetry the worker
already publishes (``bind_step_rate``) turns the static ledger into
live ``worker_program_bytes_per_sec`` / ``worker_mfu_ratio`` /
``worker_hbm_utilization_ratio`` gauges: the memory-wall numbers the
perf roadmap is navigated by, visible on /varz while training runs
instead of once per bench round.

Retrace detection closes the loop: a program whose distinct-signature
count exceeds its declared budget (serving-engine buckets declare
theirs) within ``storm_window_s`` emits a ``recompile_storm`` span
event and fires the ``on_storm`` hook — wired by the FlightRecorder to
capture an incident bundle with a ``programs.json`` ledger section.

Dispatch contract of :class:`RegisteredProgram`: every call goes
through the plain ``jax.jit`` callable — byte-identical semantics to
the unregistered code (donation, sharding resolution, multi-process
SPMD, the virtual-mesh CPU backend).  Compiles are OBSERVED, not
re-routed: a trace-time hook inside the wrapped function marks the
dispatches that traced, and the wrapper's clock around that dispatch
is the compile wall time.  (An earlier AOT-dispatch design — call the
``lower().compile()`` executable directly — died in testing:
``Compiled.__call__`` hard-aborts the process on the virtual-mesh
remesh path and cannot compile multi-process CPU programs at all.)

AOT executables still exist, but only where they existed before this
layer: explicit :meth:`RegisteredProgram.aot_compile` (the prewarm
path) and :meth:`RegisteredProgram.cost_for` (the bench path) build
one per signature, cache it, record its compile, and harvest
``cost_analysis()`` into the ledger — never dispatching it.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from elasticdl_tpu.common import events
from elasticdl_tpu.common import metrics as metrics_lib

# How long a compile-seconds sample list is kept per program (for the
# ledger's p50/p99; the histogram metric keeps the full distribution).
_COMPILE_SAMPLES_KEPT = 256

# Signature digests shown in events/ledgers are content hashes of the
# aval signature, NOT Python hash() — byte-stable across processes.
_DIGEST_CHARS = 12


def device_peaks() -> Optional[dict]:
    """Datasheet peak numbers for MFU / bandwidth rooflines; None
    off-TPU (the ratio gauges then read 0.0).  Shared with bench.py so
    bench reports and live telemetry divide by the same roofline."""
    try:
        import jax

        kind = getattr(jax.devices()[0], "device_kind", "cpu").lower()
    except Exception:
        return None
    if "v5 lite" in kind or "v5e" in kind:
        return {"bf16_flops": 197e12, "hbm_bytes_per_s": 819e9}
    if "v5p" in kind or "v5" in kind:
        return {"bf16_flops": 459e12, "hbm_bytes_per_s": 2765e9}
    if "v4" in kind:
        return {"bf16_flops": 275e12, "hbm_bytes_per_s": 1228e9}
    return None


def cost_analysis_dict(compiled) -> dict:
    """flops / bytes-accessed from XLA's own cost model (version-
    tolerant: dict on new jax, list-of-dict on old)."""
    try:
        analysis = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    return dict(analysis or {})


def _flops_bytes(cost: dict) -> Tuple[float, float]:
    return (
        float(cost.get("flops", 0.0) or 0.0),
        float(cost.get("bytes accessed", 0.0) or 0.0),
    )


def _quantile(sorted_values: List[float], q: float) -> float:
    if not sorted_values:
        return 0.0
    idx = min(int(q * len(sorted_values)), len(sorted_values) - 1)
    return sorted_values[idx]


def _sharding_key(x) -> Tuple[str, Tuple[int, ...]]:
    s = getattr(x, "sharding", None)
    if s is None:
        return ("", ())
    try:
        ids = tuple(sorted(d.id for d in s.device_set))
    except Exception:
        ids = ()
    return (str(s), ids)


def _leaf_key(x):
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        weak = bool(getattr(getattr(x, "aval", None), "weak_type", False))
        return (tuple(shape), str(dtype), weak, _sharding_key(x))
    return ("py", type(x).__name__)


def signature_of(args) -> tuple:
    """Hashable aval signature of a positional-args tuple: pytree
    structure + per-leaf (shape, dtype, weak_type, sharding).  Two calls
    with the same signature reuse one compiled executable; a new
    signature is a retrace."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (str(treedef), tuple(_leaf_key(leaf) for leaf in leaves))


def signature_digest(sig: tuple) -> str:
    return hashlib.sha1(repr(sig).encode()).hexdigest()[:_DIGEST_CHARS]


def describe_avals(args, limit: int = 8) -> str:
    """Human-readable aval summary ("float32[65536,26], int32[64]")."""
    import jax
    import numpy as np

    leaves = jax.tree_util.tree_leaves(args)
    parts = []
    for leaf in leaves[:limit]:
        dtype = getattr(leaf, "dtype", None)
        if dtype is not None:
            dims = ",".join(str(d) for d in getattr(leaf, "shape", ()))
            parts.append(f"{np.dtype(dtype).name}[{dims}]")
        else:
            parts.append(type(leaf).__name__)
    if len(leaves) > limit:
        parts.append(f"...+{len(leaves) - limit}")
    return ", ".join(parts)


def _has_tracers(args) -> bool:
    import jax

    return any(
        isinstance(leaf, jax.core.Tracer)
        for leaf in jax.tree_util.tree_leaves(args)
    )


def _new_record() -> dict:
    return {
        "signatures": {},
        "compiles": 0,
        "compile_seconds": [],
        "storms": 0,
        "budget": None,
        "latest": None,
    }


class ProgramRegistry:
    """Process-wide ledger of named compiled programs.

    Thread-safe; compiles themselves run outside the lock (they take
    seconds-to-minutes).  The injectable ``clock`` times compiles and
    stamps signature first-seen times for storm detection, so the storm
    tests replay deterministically under a fake clock."""

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        metrics: Optional[metrics_lib.MetricsRegistry] = None,
        storm_window_s: float = 60.0,
        on_storm: Optional[Callable[[dict], None]] = None,
    ):
        self.clock = clock
        self.storm_window_s = float(storm_window_s)
        self._lock = threading.Lock()
        self._programs: Dict[str, dict] = {}
        self._rates: Dict[str, Tuple[Callable[[], float], int]] = {}
        self._on_storm = on_storm
        reg = metrics or metrics_lib.default_registry()
        self._compile_hist = reg.histogram(
            "worker_program_compile_seconds",
            "XLA compile wall seconds per registered program",
            min_value=1e-3, max_value=900.0, labelnames=("program",),
        )
        self._compiles_total = reg.counter(
            "worker_program_compiles_total",
            "XLA compiles (first compile + every retrace) per program",
            labelnames=("program",),
        )
        self._signatures_gauge = reg.gauge(
            "worker_program_signatures_count",
            "distinct aval signatures seen per registered program",
            labelnames=("program",),
        )
        self._storms_total = reg.counter(
            "worker_program_storms_total",
            "recompile storms (signature budget blown within the window)",
            labelnames=("program",),
        )
        reg.gauge_fn(
            "worker_program_bytes_per_sec",
            lambda: self.live()["bytes_per_sec"],
            "cost-model bytes/s across rate-bound programs (cost x rate)",
        )
        reg.gauge_fn(
            "worker_mfu_ratio",
            lambda: self.live()["mfu"],
            "cost-model flops/s over the device datasheet peak (0 off-TPU)",
        )
        reg.gauge_fn(
            "worker_hbm_utilization_ratio",
            lambda: self.live()["hbm_utilization"],
            "cost-model bytes/s over the device HBM roof (0 off-TPU)",
        )

    # -- recording ----------------------------------------------------

    def declare(self, name: str, budget: Optional[int] = None) -> None:
        """Ensure a program record exists; optionally (re)declare its
        signature budget (latest declaration wins)."""
        with self._lock:
            rec = self._programs.setdefault(name, _new_record())
            if budget is not None:
                rec["budget"] = int(budget)

    def set_on_storm(self, hook: Optional[Callable[[dict], None]]) -> None:
        with self._lock:
            self._on_storm = hook

    def note_compile(
        self,
        name: str,
        signature: str,
        seconds: float,
        cost: Optional[dict] = None,
        avals: str = "",
    ) -> None:
        """Record one compile of `name` for aval-signature digest
        `signature`.  Called by RegisteredProgram after every AOT
        compile and by register_compiled for external executables."""
        flops, bytes_ = _flops_bytes(cost or {})
        with self._lock:
            rec = self._programs.setdefault(name, _new_record())
            sig = rec["signatures"].setdefault(
                signature,
                {"compiles": 0, "seconds": 0.0, "flops": 0.0,
                 "bytes": 0.0, "avals": ""},
            )
            sig["compiles"] += 1
            sig["seconds"] = round(sig["seconds"] + seconds, 6)
            if cost:
                # dispatch-path compiles carry no cost model (only the
                # AOT cost/prewarm queries do) — never zero a known cost
                sig["flops"] = flops
                sig["bytes"] = bytes_
            if avals:
                sig["avals"] = avals
            rec["compiles"] += 1
            rec["compile_seconds"].append(round(seconds, 6))
            del rec["compile_seconds"][:-_COMPILE_SAMPLES_KEPT]
            rec["latest"] = signature
            n_sigs = len(rec["signatures"])
        self._compile_hist.labels(program=name).record(max(seconds, 1e-9))
        self._compiles_total.labels(program=name).inc()
        self._signatures_gauge.labels(program=name).set(n_sigs)
        events.emit(
            events.PROGRAM_COMPILED,
            program=name,
            signature=signature,
            seconds=round(seconds, 4),
            flops=flops,
            bytes=bytes_,
            signatures=n_sigs,
        )

    def note_storm(self, name: str, signatures: int, budget: int) -> None:
        """A program blew its signature budget within the window: bump
        the ledger, emit the closed-vocab event, fire the hook (the
        FlightRecorder's immediate pend+flush)."""
        with self._lock:
            rec = self._programs.setdefault(name, _new_record())
            rec["storms"] += 1
            hook = self._on_storm
        record = {
            "program": name,
            "signatures": int(signatures),
            "budget": int(budget),
        }
        self._storms_total.labels(program=name).inc()
        events.emit(events.RECOMPILE_STORM, **record)
        if hook is not None:
            try:
                hook(dict(record))
            except Exception:
                pass

    def bind_step_rate(
        self,
        name: str,
        rate_fn: Callable[[], float],
        steps_per_execution: int = 1,
    ) -> None:
        """Join a program's per-execution cost against a live step rate
        (optimizer steps/sec).  `steps_per_execution` scales fused
        programs whose one execution advances K steps."""
        with self._lock:
            self._rates[name] = (rate_fn, max(int(steps_per_execution), 1))

    # -- views --------------------------------------------------------

    def live(self) -> dict:
        """Live cost x rate attribution across rate-bound programs."""
        with self._lock:
            bound = list(self._rates.items())
            latest: Dict[str, dict] = {}
            for name, _ in bound:
                rec = self._programs.get(name)
                if rec and rec["latest"] is not None:
                    latest[name] = dict(rec["signatures"][rec["latest"]])
        flops_rate = bytes_rate = 0.0
        for name, (rate_fn, spe) in bound:
            cost = latest.get(name)
            if not cost:
                continue
            try:
                rate = float(rate_fn() or 0.0)
            except Exception:
                rate = 0.0
            flops_rate += cost["flops"] * rate / spe
            bytes_rate += cost["bytes"] * rate / spe
        peaks = device_peaks()
        return {
            "flops_per_sec": flops_rate,
            "bytes_per_sec": bytes_rate,
            "mfu": flops_rate / peaks["bf16_flops"] if peaks else 0.0,
            "hbm_utilization": (
                bytes_rate / peaks["hbm_bytes_per_s"] if peaks else 0.0
            ),
        }

    def ledger(self) -> dict:
        """Per-program ledger: compiles, signatures, budget, storms,
        compile-time quantiles, latest-signature cost."""
        with self._lock:
            names = sorted(self._programs)
            records = {name: self._programs[name] for name in names}
            out = {}
            for name in names:
                rec = records[name]
                times = sorted(rec["compile_seconds"])
                latest = (
                    rec["signatures"][rec["latest"]]
                    if rec["latest"] is not None else {}
                )
                out[name] = {
                    "compiles": rec["compiles"],
                    "signatures": len(rec["signatures"]),
                    "budget": rec["budget"],
                    "storms": rec["storms"],
                    "compile_seconds_total": round(sum(times), 6),
                    "compile_seconds_p50": _quantile(times, 0.5),
                    "compile_seconds_p99": _quantile(times, 0.99),
                    "flops_per_execution": latest.get("flops", 0.0),
                    "bytes_per_execution": latest.get("bytes", 0.0),
                    "avals": latest.get("avals", ""),
                }
        return out

    def summary(self) -> dict:
        """The /varz "programs" payload: headline totals + live rates +
        the full ledger (what `elasticdl programs` renders)."""
        led = self.ledger()
        live = self.live()
        return {
            "programs": len(led),
            "compiles_total": sum(p["compiles"] for p in led.values()),
            "signatures_total": sum(p["signatures"] for p in led.values()),
            "storms_total": sum(p["storms"] for p in led.values()),
            "mfu": round(live["mfu"], 6),
            "bytes_per_sec": round(live["bytes_per_sec"], 1),
            "hbm_utilization": round(live["hbm_utilization"], 6),
            "ledger": led,
        }

    def forensics(self) -> dict:
        """The incident-bundle `programs.json` section.  Ledger minus
        live rates and compile wall-time quantiles — both mix in
        wall-clock state, and bundles must be byte-identical across
        same-seed runs (the flight-recorder discipline)."""
        led = self.ledger()
        return {"ledger": {
            name: {
                k: v for k, v in rec.items()
                if not k.startswith("compile_seconds")
            }
            for name, rec in led.items()
        }}


class RegisteredProgram:
    """A jitted callable whose compiles are observed and reported to
    the ProgramRegistry.

    Dispatch is the plain jitted function — unchanged semantics.  The
    wrapped body calls a trace-time hook; a dispatch during which the
    hook fired is a compile, and the wrapper's clock around that
    dispatch is the recorded compile wall time (trace + XLA compile;
    execution is dispatched asynchronously).  Calls under an outer
    trace (tracer arguments) inline without activating the hook slot,
    so nested tracing is not miscounted as a compile.

    Thread-safe: the hook slot is thread-local (jit traces on the
    dispatching thread), and ledger/storm state is lock-guarded.  Under
    concurrent first-calls jax's own jit cache serializes the compile;
    whichever dispatches observe a trace record it."""

    def __init__(
        self,
        name: str,
        fn: Callable,
        registry: ProgramRegistry,
        signature_budget: Optional[int] = None,
        **jit_kwargs,
    ):
        import jax

        self.name = name
        self._registry = registry
        self._budget = signature_budget
        self._tls = threading.local()

        def _observed(*a, **k):
            # trace-time side effect: runs once per trace, never on the
            # executed hot path (the serving engine's compile counter
            # uses the same pattern)
            cell = getattr(self._tls, "cell", None)
            if cell is not None:
                cell.append(1)
            return fn(*a, **k)

        self._jitted = jax.jit(_observed, **jit_kwargs)
        self._lock = threading.Lock()
        self._aot: Dict[tuple, Any] = {}
        self._sig_times: List[float] = []
        self._seen: Dict[tuple, bool] = {}
        self._stormed = False
        registry.declare(name, signature_budget)

    @property
    def signature_count(self) -> int:
        with self._lock:
            return len(self._seen)

    def __call__(self, *args, **kwargs):
        if kwargs or _has_tracers(args):
            # under an outer trace (fused timing loops) or a kwargs
            # call: dispatch without arming the hook slot — an inline
            # nested trace is not an XLA compile
            return self._jitted(*args, **kwargs)
        sig = signature_of(args)
        avals = describe_avals(args)
        clock = self._registry.clock
        tls = self._tls
        prev = getattr(tls, "cell", None)
        cell: List[int] = []
        tls.cell = cell
        start = clock()
        try:
            out = self._jitted(*args)
        finally:
            tls.cell = prev
        if cell:
            self._record(sig, max(clock() - start, 0.0), avals, cost=None)
        return out

    def aot_compile(self, *args):
        """Build (once per signature) the AOT executable — the prewarm
        path (accepts ShapeDtypeStructs like .lower()) — recording the
        compile and harvesting its cost model into the ledger.  The
        executable is cached and returned but never dispatched; the
        call path benefits via the persistent XLA compile cache."""
        return self._aot_for(args)

    def cost_for(self, *args) -> dict:
        """Version-tolerant cost_analysis() dict for this signature,
        AOT-compiling (once, recorded) if no executable is cached —
        the bench path, and the source of the ledger's flops/bytes."""
        compiled = self._aot_for(args)
        if compiled is None:
            return {}
        return cost_analysis_dict(compiled)

    def _aot_for(self, args):
        sig = signature_of(args)
        with self._lock:
            if sig in self._aot:
                return self._aot[sig]
        clock = self._registry.clock
        start = clock()
        try:
            compiled = self._jitted.lower(*args).compile()
        except Exception:
            # multi-process backends cannot AOT-compile; cost queries
            # degrade to {} rather than breaking the caller
            compiled = None
        seconds = max(clock() - start, 0.0)
        with self._lock:
            self._aot[sig] = compiled
        if compiled is not None:
            self._record(
                sig, seconds, describe_avals(args),
                cost=cost_analysis_dict(compiled),
            )
        return compiled

    def _record(self, sig, seconds, avals, cost) -> None:
        clock = self._registry.clock
        now = clock()
        with self._lock:
            new_sig = sig not in self._seen
            if new_sig:
                self._seen[sig] = True
                self._sig_times.append(now)
            window = self._registry.storm_window_s
            recent = [t for t in self._sig_times if now - t <= window]
            storm = (
                new_sig
                and self._budget is not None
                and len(recent) > self._budget
                and not self._stormed
            )
            if storm:
                self._stormed = True
            churn = len(self._sig_times)
        self._registry.note_compile(
            self.name, signature_digest(sig), seconds,
            cost=cost, avals=avals,
        )
        if storm:
            self._registry.note_storm(self.name, churn, self._budget)


_DEFAULT_LOCK = threading.Lock()
_default: Optional[ProgramRegistry] = None


def default_program_registry() -> ProgramRegistry:
    global _default
    with _DEFAULT_LOCK:
        if _default is None:
            _default = ProgramRegistry()
        return _default


def registered_jit(
    name: str,
    fn: Callable,
    registry: Optional[ProgramRegistry] = None,
    signature_budget: Optional[int] = None,
    **jit_kwargs,
) -> RegisteredProgram:
    """The normal registration path: wrap `fn` as a named registered
    program.  Extra kwargs (donate_argnums, out_shardings, ...) pass
    through to jax.jit unchanged."""
    return RegisteredProgram(
        name,
        fn,
        registry or default_program_registry(),
        signature_budget=signature_budget,
        **jit_kwargs,
    )


def register_compiled(
    name: str,
    compiled: Any,
    seconds: float = 0.0,
    registry: Optional[ProgramRegistry] = None,
    signature: str = "external",
    avals: str = "",
):
    """Report an executable compiled outside registered_jit (explicit
    lowered.compile() flows).  Returns the executable unchanged."""
    reg = registry or default_program_registry()
    reg.note_compile(
        name, signature, seconds,
        cost=cost_analysis_dict(compiled), avals=avals,
    )
    return compiled
