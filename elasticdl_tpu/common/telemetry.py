"""Per-role telemetry HTTP server: /metrics, /healthz, /varz.

Every role (master, worker, serving) starts one of these on a background
daemon thread — stdlib `http.server` only, so the exposition surface
works in the stripped container the same as in production.  Endpoints:

* `/metrics` — Prometheus text exposition (format 0.0.4) over the role's
  composed registries (common/metrics.py).
* `/healthz` — `{"status": "ok", "role": ...}` plus whatever the role's
  `healthz_fn` reports; HTTP 200 means "process up and serving".
* `/varz`   — debug JSON: flat metric snapshot + role extras (the
  surface `elasticdl top` scrapes).

Port 0 binds an ephemeral port (logged and available as `.port`) so
tests and multi-process-per-host runs never collide.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Iterable, Optional

from elasticdl_tpu.common import metrics
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class TelemetryServer:
    def __init__(
        self,
        registries: Iterable = (),
        role: str = "",
        port: int = 0,
        host: str = "0.0.0.0",
        varz_fn: Optional[Callable[[], dict]] = None,
        healthz_fn: Optional[Callable[[], dict]] = None,
    ):
        # keep the raw iterable items: callables resolve lazily at each
        # request so registries built after start() still show up
        self._registries = list(registries) or [metrics.default_registry()]
        self._role = role
        self._requested_port = int(port)
        self._host = host
        self._varz_fn = varz_fn
        self._healthz_fn = healthz_fn
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    def add_registry(self, registry) -> None:
        self._registries.append(registry)

    # ---- request surface ------------------------------------------------

    def metrics_text(self) -> str:
        return metrics.render_text(self._registries)

    def healthz_json(self) -> str:
        doc = {"status": "ok", "role": self._role}
        if self._healthz_fn is not None:
            try:
                doc.update(self._healthz_fn() or {})
            except Exception as exc:
                doc["status"] = "degraded"
                doc["error"] = str(exc)
        return json.dumps(doc, sort_keys=True, default=str)

    def varz_json(self) -> str:
        extra = {}
        if self._varz_fn is not None:
            try:
                extra = self._varz_fn() or {}
            except Exception as exc:
                extra = {"varz_error": str(exc)}
        # every role carries the program observatory ledger: the
        # process-wide registry of compiled XLA programs (the surface
        # `elasticdl programs` and the `top` programs line scrape)
        if "programs" not in extra:
            try:
                from elasticdl_tpu.common import programs

                extra["programs"] = (
                    programs.default_program_registry().summary()
                )
            except Exception as exc:
                extra["programs_error"] = str(exc)
        return metrics.varz(self._registries, role=self._role, extra=extra)

    # ---- lifecycle ------------------------------------------------------

    def start(self) -> int:
        if self._httpd is not None:
            return self.port
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        body = outer.metrics_text().encode()
                        ctype = PROMETHEUS_CONTENT_TYPE
                    elif path == "/healthz":
                        body = outer.healthz_json().encode()
                        ctype = "application/json"
                    elif path in ("/varz", "/"):
                        body = outer.varz_json().encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404, "unknown endpoint")
                        return
                except Exception as exc:  # never kill the prober
                    self.send_error(500, str(exc))
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass  # scrapes are periodic; don't spam the job log

        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), _Handler
        )
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"telemetry-{self._role or 'role'}",
            daemon=True,
        )
        self._thread.start()
        logger.info(
            "%s telemetry on port %d (/metrics /healthz /varz)",
            self._role or "process", self.port,
        )
        return self.port

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
