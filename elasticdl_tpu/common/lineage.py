"""Window lineage: end-to-end freshness decomposition per stream window.

PR 10/11 can say THAT train->serve staleness breached an SLO; this
module says WHERE the time went.  Every hop of a window's life emits a
`window_span` event (closed vocabularies in `common/events.py`) carrying
the window id, the phase the hop CLOSES, and an `at_unix_s` stamp drawn
from the hop's injectable clock — which is what keeps the whole lineage
byte-stable under the chaos bench's fake clock:

    ingest (first record event time, stamped at stream seal)
      -> sealed      closes ingest_wait   (StreamReader)
      -> armed       closes arm_wait      (TaskManager.arm_window)
      -> trained     closes train         (per leased task, max wins)
      -> admitted    closes admission     (tiered-store fold, max wins)
      -> produced    closes checkpoint    (CheckpointSaver manifest stamp)
      -> reloaded    closes reload_wait   (first fleet reload >= the step)
      -> served      closes serve_wait    (first Predict >= the step)

`WindowLineage` is a pure consumer tapped on the event stream
(`events.add_observer`, the flight-recorder pattern): it joins the
stamps into per-window decompositions, feeds the
`master_window_phase_seconds{phase=...}` histograms, and keeps a bounded
ring of completed lineage records.  Because every boundary is a stamp on
ONE monotone clock, the seven phase durations sum to the window's
measured end-to-end staleness (served - ingest) exactly — the
reconciliation contract docs/OBSERVABILITY.md documents and bench.py
asserts within 5%.

Replay attribution: a window replayed after a master restart keeps its
FIRST-SEEN ingest/seal stamps; the replay stamp only fills them in when
the original seal was never observed (it carries the journaled
watermark, i.e. the original event time), so replayed windows are
always attributed to their original ingest timestamps.

The module-level helpers (`new_state` / `apply_stamp` / `decompose` /
`from_events`) are the same joining logic run offline by
`elasticdl lineage`, `elasticdl trace`'s window tracks, and
`elasticdl incident`'s postmortem tail — one decomposition, four views.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from elasticdl_tpu.common import events
from elasticdl_tpu.common import metrics as metrics_lib

#: Phase order IS the window's life order: each entry names the segment
#: that ends at the matching boundary stamp.
PHASE_ORDER = (
    "ingest_wait", "arm_wait", "train", "admission", "checkpoint",
    "reload_wait", "serve_wait",
)

#: Boundary stamp that closes each phase, in the same order.
_PHASE_CLOSERS = (
    "sealed_unix_s", "armed_unix_s", "trained_unix_s", "admitted_unix_s",
    "produced_unix_s", "reloaded_unix_s", "served_unix_s",
)


def new_state(window_id: int) -> dict:
    """Empty per-window join state: boundary stamps + attribution flags."""
    return {
        "window_id": int(window_id),
        "ingest_unix_s": None,
        "sealed_unix_s": None,
        "armed_unix_s": None,
        "trained_unix_s": None,
        "admitted_unix_s": None,
        "produced_unix_s": None,
        "reloaded_unix_s": None,
        "served_unix_s": None,
        "step": 0,              # max model step a trained stamp carried
        "produced_step": None,  # checkpoint step that covered the window
        "tasks_trained": 0,
        "records": 0,
        "replayed": False,
        "rearmed": False,
        "dropped": False,
    }


def apply_stamp(state: dict, record: dict) -> None:
    """Fold one `window_span` event into the join state.  First stamp
    wins every boundary except trained/admitted (per-task, last task
    wins) — which is exactly what pins replayed windows to their
    original ingest/arm times."""
    reason = record.get("reason")
    at = record.get("at_unix_s")
    at = float(at) if at is not None else None
    if reason == "sealed":
        if state["sealed_unix_s"] is None:
            state["sealed_unix_s"] = at
            ingest = record.get("ingest_unix_s", at)
            state["ingest_unix_s"] = (
                float(ingest) if ingest is not None else at
            )
            state["records"] = int(record.get("records", 0))
    elif reason == "replayed":
        state["replayed"] = True
        if state["ingest_unix_s"] is None:
            # Original seal never observed: the replay stamp carries the
            # journaled watermark = the original event time.
            ingest = record.get("ingest_unix_s")
            if ingest is not None:
                state["ingest_unix_s"] = float(ingest)
                state["sealed_unix_s"] = float(ingest)
    elif reason in ("armed", "rearmed"):
        if reason == "rearmed":
            state["rearmed"] = True
        if state["armed_unix_s"] is None:
            state["armed_unix_s"] = at
    elif reason == "trained":
        if at is not None:
            prev = state["trained_unix_s"]
            state["trained_unix_s"] = at if prev is None else max(prev, at)
        state["step"] = max(state["step"], int(record.get("step", 0)))
        state["tasks_trained"] += 1
    elif reason == "admitted":
        if at is not None:
            prev = state["admitted_unix_s"]
            state["admitted_unix_s"] = (
                at if prev is None else max(prev, at)
            )
    elif reason == "produced":
        if state["produced_unix_s"] is None:
            state["produced_unix_s"] = at
            state["produced_step"] = int(record.get("step", 0))
    elif reason == "reloaded":
        if state["reloaded_unix_s"] is None:
            state["reloaded_unix_s"] = at
    elif reason == "served":
        if state["served_unix_s"] is None:
            state["served_unix_s"] = at
    elif reason == "dropped":
        state["dropped"] = True


def decompose(state: dict, now: Optional[float] = None) -> dict:
    """Phase durations for one window.  Complete windows carry all seven
    phases and `e2e_s` = served - ingest (== the phase sum, same monotone
    clock).  Open windows carry the closed phases plus the CURRENT
    blocked phase's elapsed wait against `now` (defaults to the last
    stamp seen) — so a mid-incident postmortem can still name the phase
    the fleet is stuck in."""
    phases: Dict[str, float] = {}
    prev = state["ingest_unix_s"]
    blocked = None
    for phase, closer in zip(PHASE_ORDER, _PHASE_CLOSERS):
        at = state[closer]
        if prev is None:
            break
        if at is None:
            blocked = phase
            if now is not None and now > prev:
                phases[phase] = round(now - prev, 6)
            break
        phases[phase] = round(max(0.0, at - prev), 6)
        prev = at
    complete = state["served_unix_s"] is not None and (
        state["ingest_unix_s"] is not None
    )
    out = {
        "window_id": state["window_id"],
        "phases": phases,
        "complete": complete,
        "blocked_phase": blocked,
        "replayed": state["replayed"],
        "rearmed": state["rearmed"],
        "dropped": state["dropped"],
        "tasks": state["tasks_trained"],
        "records": state["records"],
        "step": state["produced_step"],
    }
    if state["ingest_unix_s"] is not None:
        # present even for open windows: replay-attribution checks need
        # the original ingest stamp before the window completes
        out["ingest_unix_s"] = round(state["ingest_unix_s"], 6)
    if complete:
        out["served_unix_s"] = round(state["served_unix_s"], 6)
        out["e2e_s"] = round(
            max(0.0, state["served_unix_s"] - state["ingest_unix_s"]), 6
        )
    else:
        out["e2e_s"] = round(sum(phases.values()), 6)
    return out


def from_events(evts: List[dict]) -> Dict[int, dict]:
    """Offline join: fold an event log's `window_span` (and the buffer's
    `stream_window_dropped`) records into per-window states, keyed by
    window id — what `elasticdl lineage` / `trace` / `incident` render."""
    states: Dict[int, dict] = {}
    for record in evts:
        event = record.get("event")
        if event == events.WINDOW_SPAN:
            wid = record.get("window_id")
            if wid is None:
                continue
            wid = int(wid)
            state = states.get(wid)
            if state is None:
                state = states[wid] = new_state(wid)
            apply_stamp(state, record)
        elif event == events.STREAM_WINDOW_DROPPED:
            wid = record.get("window")
            if wid is None:
                continue
            wid = int(wid)
            state = states.get(wid)
            if state is None:
                state = states[wid] = new_state(wid)
            state["dropped"] = True
    return states


def dominant_phase(decomps: List[dict]) -> Optional[str]:
    """The phase holding the most total seconds across the given
    decompositions — the postmortem's one-line attribution."""
    totals = {p: 0.0 for p in PHASE_ORDER}
    for d in decomps:
        for phase, seconds in d.get("phases", {}).items():
            if phase in totals:
                totals[phase] += float(seconds)
    best = max(PHASE_ORDER, key=lambda p: totals[p])
    return best if totals[best] > 0.0 else None


def _p99(values: List[float]) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    idx = min(len(ordered) - 1, int(0.99 * len(ordered)))
    return round(ordered[idx], 6)


class WindowLineage:
    """Live lineage aggregator: an event-stream tap (install/close, the
    flight-recorder pattern) joining `window_span` stamps into completed
    lineage records, per-phase histograms, and the join queries the
    pipeline uses to fan broadcast hops (checkpoint / reload / first
    serve) out into per-window stamps."""

    def __init__(
        self,
        clock: Callable[[], float] = time.time,
        capacity: int = 256,
        registry: Optional[metrics_lib.MetricsRegistry] = None,
    ):
        self._clock = clock
        self._lock = threading.Lock()
        self._open: Dict[int, dict] = {}
        self._completed: deque = deque(maxlen=int(capacity))
        self._traced_total = 0
        self._replayed_total = 0
        self._dropped_total = 0
        self.registry = registry or metrics_lib.MetricsRegistry()
        self._phase_hist = self.registry.histogram(
            "master_window_phase_seconds",
            "one window-lineage phase duration, labeled by phase "
            "(the staleness decomposition)",
            max_value=3600.0,
            labelnames=("phase",),
        )
        self._e2e_hist = self.registry.histogram(
            "master_window_e2e_seconds",
            "stream ingest to first post-reload serve, per window",
            max_value=3600.0,
        )
        self._traced = self.registry.counter(
            "master_lineage_windows_total",
            "windows whose lineage completed (first serve observed)",
        )
        self._installed = False

    # ---- event tap ------------------------------------------------------

    def install(self) -> None:
        if not self._installed:
            events.add_observer(self.observe)
            self._installed = True

    def close(self) -> None:
        if self._installed:
            events.remove_observer(self.observe)
            self._installed = False

    def observe(self, record: dict) -> None:
        """Event-stream tap; must never raise (events.emit contract)."""
        event = record.get("event")
        if event == events.WINDOW_SPAN:
            wid = record.get("window_id")
            if wid is None:
                return
            self._stamp(int(wid), record)
        elif event == events.STREAM_WINDOW_DROPPED:
            wid = record.get("window")
            if wid is None:
                return
            with self._lock:
                state = self._open.get(int(wid))
                if state is not None:
                    state["dropped"] = True
                    self._finalize_dropped_locked(int(wid), state)

    def _stamp(self, wid: int, record: dict) -> None:
        with self._lock:
            state = self._open.get(wid)
            if state is None:
                state = self._open[wid] = new_state(wid)
            apply_stamp(state, record)
            if record.get("reason") == "dropped":
                self._finalize_dropped_locked(wid, state)
            elif state["served_unix_s"] is not None:
                self._finalize_locked(wid, state)

    def _finalize_dropped_locked(self, wid: int, state: dict) -> None:
        """A dropped/forfeited window ends its life incomplete: its
        partial decomposition joins the ring flagged `dropped` (no
        histogram samples — it never reached serving)."""
        self._completed.append(decompose(state))
        self._dropped_total += 1
        del self._open[wid]

    def _finalize_locked(self, wid: int, state: dict) -> None:
        decomp = decompose(state)
        self._completed.append(decomp)
        self._traced_total += 1
        if decomp["replayed"]:
            self._replayed_total += 1
        del self._open[wid]
        self._traced.inc()
        for phase, seconds in decomp["phases"].items():
            self._phase_hist.labels(phase=phase).record(float(seconds))
        self._e2e_hist.record(float(decomp["e2e_s"]))

    # ---- pipeline join queries ------------------------------------------
    # The checkpoint / reload / first-serve hops are fleet-level facts;
    # the pipeline asks which open windows each one covers and emits one
    # per-window stamp for each, so the on-disk event stream stays fully
    # per-window (trace/lineage can replay it with no extra state).

    def windows_awaiting_checkpoint(self, step: int) -> List[int]:
        with self._lock:
            return sorted(
                wid for wid, s in self._open.items()
                if s["trained_unix_s"] is not None
                and s["produced_unix_s"] is None
                and s["step"] <= int(step)
            )

    def windows_awaiting_reload(self, step: int) -> List[int]:
        with self._lock:
            return sorted(
                wid for wid, s in self._open.items()
                if s["produced_unix_s"] is not None
                and s["reloaded_unix_s"] is None
                and s["produced_step"] is not None
                and s["produced_step"] <= int(step)
            )

    def windows_awaiting_serve(self, model_step: int) -> List[int]:
        with self._lock:
            return sorted(
                wid for wid, s in self._open.items()
                if s["reloaded_unix_s"] is not None
                and s["served_unix_s"] is None
                and s["produced_step"] is not None
                and s["produced_step"] <= int(model_step)
            )

    def discard(self, window_id: int) -> None:
        """Forget a forfeited window's open state (its `dropped` stamp
        already flagged the loss on the stream)."""
        with self._lock:
            self._open.pop(int(window_id), None)

    # ---- reads ----------------------------------------------------------

    def records(self) -> List[dict]:
        """Completed lineage records, oldest first — every field comes
        off the injectable clock, so under a fake clock this list is
        byte-stable across same-seed chaos replays (bench.py folds it
        into the canonical trace)."""
        with self._lock:
            return [dict(d) for d in self._completed]

    def open_decompositions(self) -> List[dict]:
        """In-flight windows with their current blocked phase charged up
        to now — the mid-incident view."""
        now = self._clock()
        with self._lock:
            states = [dict(s) for s in self._open.values()]
        return [decompose(s, now=now) for s in states]

    def snapshot(self) -> dict:
        with self._lock:
            completed = list(self._completed)
            open_count = len(self._open)
            traced = self._traced_total
            replayed = self._replayed_total
            dropped = self._dropped_total
        phase_values: Dict[str, List[float]] = {p: [] for p in PHASE_ORDER}
        for d in completed:
            for phase, seconds in d["phases"].items():
                phase_values[phase].append(float(seconds))
        decomps = completed or self.open_decompositions()
        return {
            "windows_traced": traced,
            "windows_open": open_count,
            "replayed": replayed,
            "dropped": dropped,
            "e2e_p99_s": _p99(
                [d["e2e_s"] for d in completed if d["complete"]]
            ),
            "dominant_phase": dominant_phase(decomps),
            "phase_p99_s": {
                p: _p99(v) for p, v in phase_values.items() if v
            },
        }
