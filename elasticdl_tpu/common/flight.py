"""Incident flight recorder: bounded rings of recent forensic evidence,
snapshotted into self-contained bundles when something goes wrong.

The observability stack records continuously (metrics, span events,
`MetricHistory` windows) but until now a breach captured nothing: by the
time an operator looked, the stalled-window requests and the decisions
that preceded them had rotated out of every buffer.  The
`FlightRecorder` closes that gap the way an aircraft recorder does —
always listening, dumping state at the moment of the incident:

- It taps the in-process span-event stream (`events.add_observer`) and
  keeps bounded rings of recent `predict_span` records, `window_span`
  lineage stamps, and decision-class events (policy decisions, fleet
  reloads/refusals, replica relaunches, SLO transitions).
- Triggers — an `slo_breach`, a policy eviction, a `reload_refused` —
  queue a capture; `flush()` (called from the SLO evaluator's
  `on_breach` hook, from `Master.stop()`, or by hand in tests) writes
  each queued capture as one incident bundle: a directory of JSON files
  (manifest + rings + `MetricHistory` windows + `Master.snapshot()` +
  fault-injection stats), rotation-capped so soak runs cannot fill the
  disk.
- `elasticdl incident` (client/incident.py) lists bundles and renders a
  postmortem report from one.

Trigger detection is event-driven but capture is deferred to `flush()`
on purpose: decision events are emitted under their component's lock
(the fleet manager records inside `_maybe_reload_locked`), and a
synchronous capture would re-enter that lock through
`Master.snapshot()`.  The SLO evaluator's `on_breach` hook runs outside
its lock, so the breach path flushes immediately — the acceptance
scenario (a staleness burn) captures its bundle in the same tick the
breach is decided, deterministically.

Determinism: bundle names come from a per-recorder sequence counter
(never wall time), every JSON file is written `sort_keys=True`, and the
process-specific `ts`/`pid` fields are stripped from each record — a
same-seed chaos run produces byte-identical bundles (the same
discipline as the clock-free `decisions` lists).
"""

from __future__ import annotations

import json
import os
import shutil
import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from elasticdl_tpu.common import events
from elasticdl_tpu.common import faults
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)

MANIFEST_NAME = "manifest.json"
BUNDLE_FORMAT = 1

#: Record fields that vary run-to-run (emit wall time, process id) and
#: are stripped from everything a bundle persists — forensics keys on
#: request ids, ticks, and phase durations, not on when the log line
#: happened to be written.
VOLATILE_KEYS = frozenset({"ts", "pid"})

#: Decision-class events the recorder rings alongside request spans.
DECISION_EVENTS = frozenset({
    events.POLICY_DECISION,
    events.STRAGGLER_DETECTED,
    events.SERVING_REPLICA_RELAUNCHED,
    events.FLEET_RELOAD_STEP,
    events.FLEET_RELOAD_REFUSED,
    events.SLO_BREACH,
    events.SLO_RECOVERED,
    events.SERVING_SCALE,
    # recompile_storm carries only deterministic fields (program,
    # signature count, budget) — unlike program_compiled, whose wall
    # seconds would break byte-stable bundles, so that one stays out.
    events.RECOMPILE_STORM,
})


def _stable(value):
    """Recursive copy with VOLATILE_KEYS dropped from every dict."""
    if isinstance(value, dict):
        return {
            k: _stable(v) for k, v in value.items()
            if k not in VOLATILE_KEYS
        }
    if isinstance(value, (list, tuple)):
        return [_stable(v) for v in value]
    return value


def _write_json(path: str, payload) -> None:
    with open(path, "w") as fh:
        json.dump(payload, fh, sort_keys=True, indent=2, default=str)
        fh.write("\n")


class FlightRecorder:
    """Bounded forensic rings + SLO/eviction/refusal-triggered bundles.

    `install()` taps the event stream; `close()` removes the tap.  The
    recorder is safe to construct without an incident_dir (rings still
    fill; captures are skipped) so wiring it is never the thing that
    breaks a master."""

    def __init__(
        self,
        incident_dir: Optional[str] = None,
        ring_capacity: int = 256,
        max_bundles: int = 8,
        snapshot_fn: Optional[Callable[[], dict]] = None,
        history=None,
        program_registry=None,
    ):
        self._dir = incident_dir or None
        self._max_bundles = max(1, int(max_bundles))
        self._snapshot_fn = snapshot_fn
        self._history = history
        self._program_registry = program_registry
        if program_registry is not None:
            # registry storm hooks run with no locks held (the
            # dispatching thread, after the ledger lock is released),
            # so an immediate pend+flush is a safe point — same
            # contract as the SLO evaluator's on_breach.
            program_registry.set_on_storm(self.storm)
        capacity = max(1, int(ring_capacity))
        self._spans: deque = deque(maxlen=capacity)
        self._decisions: deque = deque(maxlen=capacity)
        self._lineage: deque = deque(maxlen=capacity)
        # RLock: capture emits INCIDENT_CAPTURED, which re-enters
        # observe() on this same thread through the event tap.
        self._lock = threading.RLock()
        self._pending: List[Tuple[str, tuple, dict]] = []
        self._armed_out: set = set()  # keys already captured, not re-armed
        self._seq = 0
        self._captured: List[str] = []

    # ---- event tap ------------------------------------------------------

    def install(self) -> "FlightRecorder":
        events.add_observer(self.observe)
        return self

    def close(self) -> None:
        events.remove_observer(self.observe)

    def observe(self, record: dict) -> None:
        """Event-stream tap: ring the record, queue trigger captures.
        Must never raise (it runs inside events.emit)."""
        event = record.get("event")
        with self._lock:
            if event == events.PREDICT_SPAN:
                self._spans.append(dict(record))
            elif event == events.WINDOW_SPAN:
                # the train-path lineage ring: a staleness postmortem
                # needs the window stamps that preceded the breach
                self._lineage.append(dict(record))
            elif event in DECISION_EVENTS:
                self._decisions.append(dict(record))
            if event == events.SLO_BREACH:
                self._pend_locked(
                    "slo_breach", ("slo_breach", record.get("slo")), record
                )
            elif event == events.SLO_RECOVERED:
                # the breach cleared: re-arm so the next one captures
                self._armed_out.discard(
                    ("slo_breach", record.get("slo"))
                )
            elif (event == events.POLICY_DECISION
                    and record.get("action") == "evict"):
                self._pend_locked(
                    "policy_eviction",
                    ("policy_eviction", record.get("worker_id")),
                    record,
                )
            elif event == events.FLEET_RELOAD_REFUSED:
                self._pend_locked(
                    "reload_refused",
                    ("reload_refused", record.get("pending_step")),
                    record,
                )
            elif event == events.STREAM_WINDOW_DROPPED:
                # a silently lost training window is an incident, not a
                # log line: bundle the rings around the drop
                self._pend_locked(
                    "window_dropped",
                    ("window_dropped", record.get("window")),
                    record,
                )
            elif event == events.RECOMPILE_STORM:
                # one bundle per storming program: the per-program key
                # plus _armed_out dedupe means a storm that keeps
                # retracing does not spam the incident dir
                self._pend_locked(
                    "recompile_storm",
                    ("recompile_storm", record.get("program")),
                    record,
                )

    def _pend_locked(self, trigger: str, key: tuple,
                     evidence: dict) -> None:
        assert trigger in events.INCIDENT_TRIGGERS, trigger
        if key in self._armed_out:
            return
        if any(k == key for _, k, _ in self._pending):
            return
        self._armed_out.add(key)
        self._pending.append((trigger, key, dict(evidence)))

    # ---- capture --------------------------------------------------------

    def breach(self, decision: dict) -> List[str]:
        """SloEvaluator `on_breach` wiring: queue (deduped against the
        tap's copy of the same breach) and capture immediately — the
        hook runs outside the evaluator lock, so this is a safe point."""
        with self._lock:
            self._pend_locked(
                "slo_breach", ("slo_breach", decision.get("slo")), decision
            )
        return self.flush()

    def storm(self, record: dict) -> List[str]:
        """ProgramRegistry `on_storm` wiring: queue (deduped against
        the tap's copy of the same storm event) and capture in the same
        tick — the hook runs with no registry locks held."""
        with self._lock:
            self._pend_locked(
                "recompile_storm",
                ("recompile_storm", record.get("program")),
                record,
            )
        return self.flush()

    def flush(self) -> List[str]:
        """Write one bundle per queued trigger; returns bundle paths.
        Call from a context that holds no component locks."""
        with self._lock:
            pending, self._pending = self._pending, []
        return [
            path
            for trigger, _key, evidence in pending
            for path in [self.capture(trigger, evidence)]
            if path is not None
        ]

    def capture(self, trigger: str,
                evidence: Optional[dict] = None) -> Optional[str]:
        """Snapshot rings + history + master state into one bundle dir.
        Returns the path, or None when no incident_dir is configured or
        the write failed (capture must never take the serving path
        down with it)."""
        assert trigger in events.INCIDENT_TRIGGERS, trigger
        if self._dir is None:
            return None
        with self._lock:
            self._seq += 1
            seq = self._seq
            spans = [_stable(r) for r in self._spans]
            decisions = [_stable(r) for r in self._decisions]
            lineage = [_stable(r) for r in self._lineage]
        name = f"incident-{seq:04d}-{trigger}"
        path = os.path.join(self._dir, name)
        try:
            sections: Dict[str, object] = {
                "spans": spans,
                "decisions": decisions,
                "lineage": lineage,
                "faults": _stable(faults.stats()),
            }
            if self._history is not None:
                sections["history"] = _stable(self._history.snapshot())
            if self._program_registry is not None:
                sections["programs"] = _stable(
                    self._program_registry.forensics()
                )
            if self._snapshot_fn is not None:
                sections["master"] = _stable(self._snapshot_fn())
            os.makedirs(path, exist_ok=True)
            files = []
            for section in sorted(sections):
                filename = f"{section}.json"
                _write_json(
                    os.path.join(path, filename), sections[section]
                )
                files.append(filename)
            _write_json(os.path.join(path, MANIFEST_NAME), {
                "format": BUNDLE_FORMAT,
                "bundle": name,
                "seq": seq,
                "trigger": trigger,
                "evidence": _stable(evidence or {}),
                "counts": {
                    "spans": len(spans),
                    "decisions": len(decisions),
                    "lineage": len(lineage),
                },
                "files": files,
            })
        except Exception:
            logger.exception("incident capture failed: %s", name)
            return None
        with self._lock:
            self._captured.append(name)
        self._rotate()
        events.emit(
            events.INCIDENT_CAPTURED, trigger=trigger, bundle=name
        )
        logger.warning("incident bundle captured: %s", path)
        return path

    def _rotate(self) -> None:
        """Keep at most max_bundles on disk, oldest-first eviction (the
        seq-prefixed names sort in capture order)."""
        try:
            bundles = sorted(
                entry for entry in os.listdir(self._dir)
                if entry.startswith("incident-")
                and os.path.isdir(os.path.join(self._dir, entry))
            )
            for stale in bundles[:-self._max_bundles]:
                shutil.rmtree(
                    os.path.join(self._dir, stale), ignore_errors=True
                )
        except OSError:
            pass

    # ---- reads ----------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "incident_dir": self._dir,
                "spans_buffered": len(self._spans),
                "decisions_buffered": len(self._decisions),
                "lineage_buffered": len(self._lineage),
                "pending": len(self._pending),
                "captured": list(self._captured),
            }


# ---- bundle reads (the `elasticdl incident` CLI) -----------------------

def list_bundles(incident_dir: str) -> List[dict]:
    """Manifests of every bundle under `incident_dir`, capture order;
    each dict gains a `path` key.  Unreadable entries are skipped."""
    out: List[dict] = []
    try:
        entries = sorted(os.listdir(incident_dir))
    except OSError:
        return []
    for entry in entries:
        path = os.path.join(incident_dir, entry)
        manifest_path = os.path.join(path, MANIFEST_NAME)
        if not os.path.isfile(manifest_path):
            continue
        try:
            with open(manifest_path) as fh:
                manifest = json.load(fh)
        except (OSError, ValueError):
            continue
        manifest["path"] = path
        out.append(manifest)
    return out


def load_bundle(path: str) -> dict:
    """One bundle as {section: payload}, manifest under "manifest"."""
    out: Dict[str, object] = {}
    manifest_path = os.path.join(path, MANIFEST_NAME)
    with open(manifest_path) as fh:
        out["manifest"] = json.load(fh)
    for filename in out["manifest"].get("files", []):
        section = filename[:-len(".json")]
        try:
            with open(os.path.join(path, filename)) as fh:
                out[section] = json.load(fh)
        except (OSError, ValueError):
            continue
    return out
