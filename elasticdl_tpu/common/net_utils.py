"""Self-address discovery for worker pods.

Parity role: the reference's AllReduce workers were addressed by the
Horovod rendezvous via host:port entries the master collected from k8s
(SURVEY.md C6/§3.4).  Here every worker must be reachable as a
`jax.distributed` peer, and rank 0's address doubles as the coordination
service address, so a worker needs to know the address other hosts can
dial it on — NOT `localhost`.

Resolution order: explicit env (k8s downward-API pod IP) > the source
address the kernel picks to reach the master (a UDP connect performs no
handshake, so this works without any listener) > hostname lookup.
"""

from __future__ import annotations

import os
import socket

from elasticdl_tpu.common.constants import WorkerEnv


def get_reachable_address(master_addr: str = "") -> str:
    explicit = os.environ.get(WorkerEnv.WORKER_ADDR) or os.environ.get(
        "POD_IP"
    )
    if explicit:
        return explicit
    host = (master_addr or "").rsplit(":", 1)[0] or "8.8.8.8"
    try:
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            sock.connect((host, 9))
            return sock.getsockname()[0]
        finally:
            sock.close()
    except OSError:
        pass
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"
