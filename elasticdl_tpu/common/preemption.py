"""Save-on-preemption: checkpoint before the pod dies.

Parity: reference §3.6/SURVEY.md §5 — the reference checkpointed PS state
on signal.  On preemptible TPU VMs the kernel delivers SIGTERM with a
grace window before the VM is reclaimed; the hook flushes one final
(synchronous) checkpoint so the replacement topology restores from the
last step instead of the last periodic save.  Elastic recovery then
proceeds through the normal epoch-bump path — the task queue re-leases
whatever this worker held.
"""

from __future__ import annotations

import signal
import sys
from typing import Callable, Iterable

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)


def install_preemption_hook(
    save_fn: Callable[[], None],
    signals: Iterable[int] = (signal.SIGTERM,),
    exit_after: bool = True,
    exit_code: int = 143,
) -> Callable[[int, object], None]:
    """Register `save_fn` to run on preemption signals.

    exit_after=False is for tests (the handler returns instead of
    exiting).  Returns the handler so tests can invoke it directly.
    """

    def handler(signum, frame):
        logger.warning(
            "Preemption signal %d: flushing final checkpoint", signum
        )
        try:
            save_fn()
        except Exception as exc:  # best effort — never mask the shutdown
            logger.error("Preemption checkpoint failed: %s", exc)
        if exit_after:
            sys.exit(exit_code)

    for sig in signals:
        signal.signal(sig, handler)
    return handler


# ---- maintenance-event / preemption-notice awareness -------------------
#
# GKE TPU node pools surface upcoming disruption BEFORE the kill: GCE
# maintenance events and spot/preemption notices are published on the
# instance metadata server, and cluster tooling commonly projects them
# into a file in the pod (downward API / a node-watcher sidecar).  The
# reference had nothing equivalent (k8s pod-phase watch only, SURVEY §5);
# for TPU slices SURVEY §7's C4 mapping calls for acting on the notice —
# draining at a task boundary and flushing a checkpoint while the grace
# window is still all ours, instead of racing the SIGTERM delivery.


def file_notice_checker(path: str) -> Callable[[], bool]:
    """Notice = the file exists AND is non-empty.  A downward-API
    projection creates the file at pod start with the (empty) label
    value — existence alone would read as an immediate notice and
    drain-loop the job; content appears only when the node watcher
    writes the event (e.g. TERMINATE_ON_MAINTENANCE)."""
    import os

    def check() -> bool:
        try:
            return os.path.getsize(path) > 0
        except OSError:
            return False

    return check


def gce_metadata_checker(
    kind: str = "preempted",
    timeout_s: float = 1.0,
) -> Callable[[], bool]:
    """Poll the GCE metadata server for a disruption notice.

    kind: "preempted" (spot/preemptible reclaim) or "maintenance-event"
    (host maintenance; value != NONE means a migration is imminent).
    Unreachable metadata (non-GCE hosts, tests) reads as no-notice.
    """
    import urllib.request

    url = (
        "http://metadata.google.internal/computeMetadata/v1/instance/"
        + ("preempted" if kind == "preempted" else "maintenance-event")
    )

    def check() -> bool:
        try:
            req = urllib.request.Request(
                url, headers={"Metadata-Flavor": "Google"}
            )
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                value = resp.read().decode().strip().upper()
            if kind == "preempted":
                return value == "TRUE"
            return value not in ("", "NONE")
        except Exception:
            return False

    return check


def any_notice_checker(*checkers) -> Callable[[], bool]:
    """Notice = ANY source fires.  The GKE wiring watches BOTH the spot
    reclaim ('preempted') and scheduled host maintenance
    ('maintenance-event') endpoints — a non-spot TPU VM only ever sees
    the latter."""

    def check() -> bool:
        return any(c() for c in checkers)

    return check


class MaintenanceNoticeWatcher:
    """Daemon thread polling a notice source; fires `on_notice` ONCE when
    the notice appears.  `on_notice` is the same drain hook the SIGTERM
    path uses (stop at the next task boundary + flush checkpoint), so the
    notice simply starts recovery earlier than the kill would."""

    def __init__(
        self,
        check: Callable[[], bool],
        on_notice: Callable[[], None],
        poll_s: float = 5.0,
    ):
        self._check = check
        self._on_notice = on_notice
        self._poll_s = poll_s
        self._fired = False
        self._stop = False
        self._thread = None

    def start(self) -> "MaintenanceNoticeWatcher":
        import threading

        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop = True

    @property
    def fired(self) -> bool:
        return self._fired

    def _run(self) -> None:
        import time

        while not self._stop and not self._fired:
            try:
                notice = self._check()
            except Exception:
                notice = False
            if notice:
                logger.warning(
                    "Maintenance/preemption notice observed: draining at "
                    "the next task boundary and flushing checkpoint "
                    "(ahead of the kill)"
                )
                try:
                    self._on_notice()
                except Exception as exc:
                    logger.error("Notice drain hook failed: %s", exc)
                # published AFTER the drain hook: observers of `fired`
                # may rely on the drain having actually happened
                self._fired = True
                return
            time.sleep(self._poll_s)
