"""Save-on-preemption: checkpoint before the pod dies.

Parity: reference §3.6/SURVEY.md §5 — the reference checkpointed PS state
on signal.  On preemptible TPU VMs the kernel delivers SIGTERM with a
grace window before the VM is reclaimed; the hook flushes one final
(synchronous) checkpoint so the replacement topology restores from the
last step instead of the last periodic save.  Elastic recovery then
proceeds through the normal epoch-bump path — the task queue re-leases
whatever this worker held.
"""

from __future__ import annotations

import signal
import sys
from typing import Callable, Iterable

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)


def install_preemption_hook(
    save_fn: Callable[[], None],
    signals: Iterable[int] = (signal.SIGTERM,),
    exit_after: bool = True,
    exit_code: int = 143,
) -> Callable[[int, object], None]:
    """Register `save_fn` to run on preemption signals.

    exit_after=False is for tests (the handler returns instead of
    exiting).  Returns the handler so tests can invoke it directly.
    """

    def handler(signum, frame):
        logger.warning(
            "Preemption signal %d: flushing final checkpoint", signum
        )
        try:
            save_fn()
        except Exception as exc:  # best effort — never mask the shutdown
            logger.error("Preemption checkpoint failed: %s", exc)
        if exit_after:
            sys.exit(exit_code)

    for sig in signals:
        signal.signal(sig, handler)
    return handler
