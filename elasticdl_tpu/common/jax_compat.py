"""Version-guarded shims over drifting jax APIs.

The codebase targets the current jax surface (`jax.shard_map`,
`jax.lax.pcast`, `jax.distributed.is_initialized`); older runtimes (the
0.4.x line this container ships) spell those `jax.experimental.shard_map`
/ no-pcast / no-is_initialized.  Every call site goes through this module
so the drift is handled in exactly one place and a future jax bump is a
one-file deletion, not a hunt.

Rules for this module:
- feature-detect (`hasattr`), never version-parse — patch releases have
  backported/removed these symbols independently of the version string;
- the fallback must be semantically equivalent for OUR call sites, not
  fully general (documented per shim below).
"""

from __future__ import annotations

import jax

_HAS_NATIVE_SHARD_MAP = hasattr(jax, "shard_map")
_HAS_PCAST = hasattr(jax.lax, "pcast")
_HAS_PVARY = hasattr(jax.lax, "pvary")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` with a fallback to the pre-0.6 experimental API.

    `check_vma` maps onto the legacy `check_rep`: both gate the static
    audit of per-shard output typing.  The legacy checker predates the
    vma type system and rejects valid carries that mix invariant and
    varying operands (exactly the pattern our ring/pipeline scan bodies
    use), so on the legacy path the audit is disabled outright — the
    in/out specs still pin the sharding contract, which is what our
    callers rely on.
    """
    if _HAS_NATIVE_SHARD_MAP:
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def pcast_to_varying(x, axes):
    """Mark `x` as shard-varying over `axes` inside a shard_map body.

    On jax builds with the vma type system this is `lax.pcast(...,
    to="varying")` (or its `lax.pvary` predecessor).  Pre-vma builds have
    no varying/invariant distinction in the type system at all, so the
    identity is the correct (and only) lowering.
    """
    if _HAS_PCAST:
        return jax.lax.pcast(x, axes, to="varying")
    if _HAS_PVARY:
        return jax.lax.pvary(x, axes)
    return x


def distributed_is_initialized() -> bool:
    """`jax.distributed.is_initialized()` with a fallback that inspects
    the distributed client singleton (the exact state the public API
    reads on builds that have it)."""
    if hasattr(jax.distributed, "is_initialized"):
        return jax.distributed.is_initialized()
    try:
        from jax._src import distributed as _distributed

        return getattr(_distributed.global_state, "client", None) is not None
    except Exception:
        return False
