"""TensorBoard scalar summaries (optional — gated on TensorFlow being
importable, matching the reference's optional TensorBoard service,
SURVEY.md §5)."""

from __future__ import annotations

from typing import Dict, Optional

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)


class SummaryWriter:
    """Thin tf.summary wrapper; a no-op when TF is unavailable or no
    log_dir is configured."""

    def __init__(self, log_dir: Optional[str] = None):
        self._writer = None
        if not log_dir:
            return
        try:
            import tensorflow as tf

            self._writer = tf.summary.create_file_writer(log_dir)
        except ImportError:
            logger.warning(
                "TensorFlow unavailable; summaries to %s disabled", log_dir
            )

    def scalars(self, values: Dict[str, float], step: int):
        if self._writer is None:
            return
        import tensorflow as tf

        with self._writer.as_default():
            for name, value in values.items():
                tf.summary.scalar(name, value, step=step)

    def flush(self):
        if self._writer is not None:
            self._writer.flush()

    def close(self):
        if self._writer is not None:
            self._writer.close()
