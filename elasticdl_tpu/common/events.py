"""Cross-process task tracing: append-only JSONL span events.

The control plane already carries the correlation keys — task_id and
worker_id ride every GetTask/ReportTaskResult RPC — so tracing one task
across processes needs no new wire format, only a shared log.  Each
participating process appends one JSON object per line to the SAME file
(O_APPEND; events are far under PIPE_BUF so concurrent appends from
master + worker processes do not interleave):

    {"ts": ..., "role": "master", "pid": ..., "event": "task_dispatched",
     "task_id": 7, "worker_id": 0}

A task's life is then the chain `task_dispatched -> task_claimed ->
task_trained -> task_reported` filtered by task_id; checkpoint, serving
hot-reload, and elastic-recovery events share the stream so an operator
can line a latency spike up against the recovery that caused it.

The log path propagates to subprocess workers through the environment
(`ELASTICDL_EVENT_LOG`), the same wire `common/faults.py` uses for chaos
schedules.  Unconfigured processes pay one None-check per emit.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional

ENV_EVENT_LOG = "ELASTICDL_EVENT_LOG"

# Span-event vocabulary (docs/OBSERVABILITY.md "Span schema").
TASK_DISPATCHED = "task_dispatched"    # master leased the task
TASK_CLAIMED = "task_claimed"          # worker received it
TASK_TRAINED = "task_trained"          # worker finished the shard
TASK_REPORTED = "task_reported"        # master recorded the result
CHECKPOINT_SAVED = "checkpoint_saved"
CHECKPOINT_RESTORED = "checkpoint_restored"
SERVING_RELOADED = "serving_reloaded"
RECOVERY_STARTED = "recovery_started"  # worker loss opened an outage
RECOVERY_DONE = "recovery_done"        # first post-restore progress
STEP_PHASES = "step_phases"            # worker phase-time breakdown flush
STRAGGLER_DETECTED = "straggler_detected"  # master flagged a slow worker
POLICY_DECISION = "policy_decision"    # master policy engine acted
SERVING_REPLICA_RELAUNCHED = "serving_replica_relaunched"  # fleet replaced
FLEET_RELOAD_STEP = "fleet_reload_step"        # one replica hot-swapped
FLEET_RELOAD_REFUSED = "fleet_reload_refused"  # skew SLO blocked a reload
SLO_BREACH = "slo_breach"          # burn rate crossed an alert threshold
SLO_RECOVERED = "slo_recovered"    # burn rate back inside the budget
PREDICT_SPAN = "predict_span"      # one routed serve request, all phases
INCIDENT_CAPTURED = "incident_captured"  # flight recorder wrote a bundle
STORE_GROWN = "store_grown"        # tiered store lazily grew vocab rows
STORE_TIER_SWAPPED = "store_tier_swapped"  # serving adopted tier metadata
STREAM_WINDOW_SEALED = "stream_window_sealed"  # a stream window filled
STREAM_WINDOW_ARMED = "stream_window_armed"    # window became queue tasks
STREAM_WINDOW_DROPPED = "stream_window_dropped"  # bounded buffer lost one
STREAM_WINDOW_RELEASED = "stream_window_released"  # ledger acked trained
STREAM_WINDOW_RESTORED = "stream_window_restored"  # un-acked replayed
STORE_SHARD_HANDOFF = "store_shard_handoff"  # row range moved to successor
SERVING_SCALE = "serving_scale"    # serving policy engine scaled the fleet
WINDOW_SPAN = "window_span"        # one window-lineage phase stamp
PROGRAM_COMPILED = "program_compiled"  # a registered XLA program compiled
RECOMPILE_STORM = "recompile_storm"    # a program blew its signature budget

#: Every event name this stream may carry.  `emit()` callers must pass
#: one of these constants — scripts/check_metric_names.py rejects string
#: literals so the vocabulary (and docs/OBSERVABILITY.md) stays the
#: single source of truth.
VOCABULARY = frozenset({
    TASK_DISPATCHED, TASK_CLAIMED, TASK_TRAINED, TASK_REPORTED,
    CHECKPOINT_SAVED, CHECKPOINT_RESTORED, SERVING_RELOADED,
    RECOVERY_STARTED, RECOVERY_DONE, STEP_PHASES, STRAGGLER_DETECTED,
    POLICY_DECISION, SERVING_REPLICA_RELAUNCHED, FLEET_RELOAD_STEP,
    FLEET_RELOAD_REFUSED, SLO_BREACH, SLO_RECOVERED, PREDICT_SPAN,
    INCIDENT_CAPTURED, STORE_GROWN, STORE_TIER_SWAPPED,
    STREAM_WINDOW_SEALED, STREAM_WINDOW_ARMED, STREAM_WINDOW_DROPPED,
    STREAM_WINDOW_RELEASED, STREAM_WINDOW_RESTORED, STORE_SHARD_HANDOFF,
    SERVING_SCALE, WINDOW_SPAN, PROGRAM_COMPILED, RECOMPILE_STORM,
})

#: Closed vocabularies for the `action` / `reason` fields every
#: POLICY_DECISION event must carry (enforced at emit time by
#: master/policy.py and statically by scripts/check_metric_names.py):
#: a decision an operator cannot grep for by exact name is a decision
#: that never reached the dashboards.
POLICY_ACTIONS = frozenset({"evict", "scale_up", "scale_down"})
POLICY_REASONS = frozenset({
    "straggler", "backlog", "data_wait", "stream_lag",
})

#: Closed vocabularies for the `action` / `reason` fields every
#: SERVING_SCALE event must carry (enforced at emit time by
#: master/policy.py's ServingPolicyEngine and statically by graftlint
#: GL-METRIC rule 4, same contract as POLICY_DECISION).  `scale_aborted`
#: records an action the fleet.scale fault point aborted — the engine
#: retries it next tick with its streaks frozen.
SERVING_SCALE_ACTIONS = frozenset({
    "scale_up", "scale_down", "scale_aborted",
})
SERVING_SCALE_REASONS = frozenset({
    "burn_rate", "shed_ratio", "batch_fill", "idle", "reload_guard",
    "fault",
})

#: Closed vocabularies for the serve-path PREDICT_SPAN event
#: (docs/OBSERVABILITY.md "Request tracing & incident bundles").
#: `phase` names one timed hop inside a request; the span's
#: `phases_s` dict may only carry these keys, and the
#: `serving_request_phase_seconds{phase=...}` histogram label draws
#: from the same set.  `reason` is the routing outcome stamped on the
#: span: "sampled" for the normal sampled-in path, the rest are the
#: always-captured error/shed/failover outcomes that bypass
#: `--trace_sample_rate`.
SPAN_PHASES = frozenset({
    "route", "queue_wait", "batch_form", "pad", "compute",
    "unpack", "respond",
})
SPAN_REASONS = frozenset({
    "sampled", "error", "shed", "failover", "invalid", "internal",
})

#: Closed vocabularies for the train-path WINDOW_SPAN event — the
#: lineage twin of PREDICT_SPAN (docs/OBSERVABILITY.md "Window
#: lineage").  Each emit stamps the hop that CLOSES one named phase of
#: a window's ingest->first-serve life; `common/lineage.py` joins the
#: stamps into the staleness decomposition and the
#: `master_window_phase_seconds{phase=...}` histogram draws its label
#: from the same set.  `reason` names the hop outcome: "sealed" /
#: "replayed" for the two ingest stamps, "armed" / "rearmed" for the
#: arm (first arm vs ledger replay after a master restart), "trained" /
#: "admitted" per task, "produced" / "reloaded" / "served" for the
#: checkpoint->fleet->first-predict tail, "dropped" when the window is
#: forfeited.  Enforced statically by graftlint GL-METRIC rule 6.
WINDOW_PHASES = frozenset({
    "ingest_wait", "arm_wait", "train", "admission", "checkpoint",
    "reload_wait", "serve_wait",
})
WINDOW_REASONS = frozenset({
    "sealed", "replayed", "armed", "rearmed", "trained", "admitted",
    "produced", "reloaded", "served", "dropped",
})

#: Triggers the incident flight recorder (common/flight.py) captures
#: on; the `reason` field of every INCIDENT_CAPTURED event and bundle
#: manifest draws from this set.
INCIDENT_TRIGGERS = frozenset({
    "slo_breach", "policy_eviction", "reload_refused", "manual",
    "tier1_failure", "window_dropped", "recompile_storm",
})

_lock = threading.Lock()
_fh = None
_path: Optional[str] = None
_role = ""
_worker_id: Optional[int] = None
_max_bytes: Optional[int] = None
# In-process taps (common/flight.py's incident ring): each observer is
# called with every emitted record, whether or not a log file is
# configured.  Observers must be cheap and must never raise.
_observers: List = []


def add_observer(fn) -> None:
    """Register an in-process tap on the event stream.  `fn(record)` is
    called for every emit, including when no log file is configured."""
    with _lock:
        if fn not in _observers:
            _observers.append(fn)


def remove_observer(fn) -> None:
    with _lock:
        if fn in _observers:
            _observers.remove(fn)


def rotated_path(path: str) -> str:
    """Where `configure(max_bytes=...)` rolls a full log to."""
    return path + ".1"


def configure(path: Optional[str], role: str = "",
              worker_id: Optional[int] = None,
              export_env: bool = False,
              max_bytes: Optional[int] = None) -> None:
    """Point this process's event stream at `path` (None disables).
    `export_env=True` additionally publishes the path to the environment
    so subprocess workers launched later inherit it.  `max_bytes` caps
    the file: on crossing the cap the log rolls to `<path>.1` (one
    generation — long soaks can't grow the JSONL unboundedly)."""
    global _fh, _path, _role, _worker_id, _max_bytes
    with _lock:
        if _fh is not None:
            try:
                _fh.close()
            except Exception:
                pass
            _fh = None
        _path = path or None
        _role = role
        _worker_id = worker_id
        _max_bytes = int(max_bytes) if max_bytes else None
        if _path:
            directory = os.path.dirname(_path)
            if directory:
                os.makedirs(directory, exist_ok=True)
            _fh = open(_path, "a", buffering=1)
    if export_env and path:
        os.environ[ENV_EVENT_LOG] = path


def _maybe_rotate_locked() -> None:
    """Roll `<path>` to `<path>.1` when past the size cap.  Caller holds
    `_lock`.  Best-effort: rotation failure must never break emit."""
    global _fh
    if _max_bytes is None or _fh is None or _path is None:
        return
    try:
        if _fh.tell() < _max_bytes:
            return
        _fh.close()
        os.replace(_path, rotated_path(_path))
        _fh = open(_path, "a", buffering=1)
    except Exception:
        try:
            if _fh is None or _fh.closed:
                _fh = open(_path, "a", buffering=1)
        except Exception:
            _fh = None


def configure_from_env(role: str = "",
                       worker_id: Optional[int] = None) -> bool:
    """Subprocess wire: enable tracing when the parent exported a log
    path.  Returns True when tracing is on."""
    path = os.environ.get(ENV_EVENT_LOG, "")
    if path:
        configure(path, role=role, worker_id=worker_id)
    return bool(path)


def enabled() -> bool:
    return _fh is not None


def emit(event: str, **fields) -> None:
    """Append one span event and feed any in-process observers.  No-op
    unless configured or observed; never raises — tracing must not be
    able to fail the training loop."""
    fh = _fh
    observers = _observers
    if fh is None and not observers:
        return
    record = {
        "ts": time.time(),
        "role": _role,
        "pid": os.getpid(),
        "event": event,
    }
    if _worker_id is not None and "worker_id" not in fields:
        record["worker_id"] = _worker_id
    record.update(fields)
    for observer in list(observers):
        try:
            observer(record)
        except Exception:
            pass
    if fh is None:
        return
    try:
        line = json.dumps(record, sort_keys=True, default=str)
        with _lock:
            if _fh is not None:
                _fh.write(line + "\n")
                _maybe_rotate_locked()
    except Exception:
        pass


def _read_one(path: str) -> List[dict]:
    out: List[dict] = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        return []
    return out


def read_events(path: str) -> List[dict]:
    """Parse an event log; malformed lines (torn writes from a killed
    process) are skipped, not fatal.  A rolled generation (`<path>.1`,
    from `configure(max_bytes=...)`) is read first so the combined list
    stays in emit order."""
    return _read_one(rotated_path(path)) + _read_one(path)


def task_chain(events: List[dict], task_id: int) -> List[str]:
    """The ordered event names recorded for one task — the correlated
    span chain the e2e test (and an operator) inspects."""
    return [
        e["event"] for e in sorted(
            (e for e in events if e.get("task_id") == task_id),
            key=lambda e: e.get("ts", 0.0),
        )
    ]
