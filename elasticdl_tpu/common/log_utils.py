"""Pod-name–tagged logging.  Parity: reference python/common/log_utils.py
(SURVEY.md C22)."""

import logging
import os
import sys

_FORMAT = (
    "[%(asctime)s] [%(levelname)s] "
    "[{pod}] [%(name)s:%(lineno)d] %(message)s"
)


def get_logger(name: str, level: int = logging.INFO) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        pod = os.environ.get("HOSTNAME", "local")
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT.format(pod=pod)))
        logger.addHandler(handler)
        logger.propagate = False
    logger.setLevel(level)
    return logger
