"""Virtual CPU device-mesh environment setup.

Multi-chip code paths (DP psum, sharded embeddings, ring attention) are
exercised without TPUs by forcing jax onto a virtual n-device CPU mesh —
the CI strategy SURVEY.md §4 prescribes. This helper is the single place
that builds that environment; tests/conftest.py and the driver's
`dryrun_multichip` re-exec both use it so the flag-patching logic cannot
drift.

Stdlib-only: must be importable before jax (env vars have to be set
before the backend initialises).
"""

from __future__ import annotations

import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def cpu_mesh_env(n_devices: int, base: dict | None = None) -> dict:
    """Return a copy of `base` (default os.environ) patched for an
    n-device virtual CPU mesh.

    Always *overrides* any existing device-count flag rather than keeping
    a stale (possibly smaller) value — a smaller inherited count would
    otherwise leave the child short of devices.
    """
    import os

    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(rf"{_COUNT_FLAG}=\d+\s*", "", env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (flags + f" {_COUNT_FLAG}={n_devices}").strip()
    # Persistent XLA-executable cache shared by every process in the
    # harness (the in-process suite AND the OS-process cluster drills):
    # workers re-spawned by elasticity tests compile the same tiny
    # programs over and over — a disk cache turns all but the first
    # compile into a read.  Keyed by HLO + compile options, so identical
    # programs from different ranks share safely.  Per-user path: a
    # world-shared /tmp dir would hit permission failures (and symlink
    # hazards) the moment a second user runs the suite on the same host.
    cache_dir = default_cache_dir()
    if cache_dir:
        env.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir)
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
    return env


def _secure_cache_dir(path: str) -> "str | None":
    """Create the per-user cache dir 0o700 and verify we own it (ADVICE
    r3: the predictable /tmp path is squattable — another local user
    could pre-create it, or plant a symlink, before our first run).
    Returns None (caller skips the persistent cache) when the path can't
    be made safe; the cache is an accelerator, never a requirement."""
    import os

    try:
        os.makedirs(path, mode=0o700, exist_ok=True)
        st = os.lstat(path)
        import stat as _stat

        if not _stat.S_ISDIR(st.st_mode):
            return None  # symlink or file squatting the name
        if hasattr(os, "getuid") and st.st_uid != os.getuid():
            return None  # someone else's directory
        if st.st_mode & 0o077:
            os.chmod(path, 0o700)
        return path
    except OSError:
        return None


def default_cache_dir() -> "str | None":
    """Per-user persistent XLA-executable cache path (created 0o700 and
    ownership-verified), or None when it cannot be made safe."""
    import getpass
    import os
    import tempfile

    try:
        user = getpass.getuser()
    except Exception:
        user = str(os.getuid()) if hasattr(os, "getuid") else "anon"
    return _secure_cache_dir(
        os.path.join(tempfile.gettempdir(), f"elasticdl_tpu_xla_cache_{user}")
    )


def enable_persistent_compile_cache() -> None:
    """One-call opt-in for entry points (bench, CLI tools): point an
    already-imported jax at the per-user persistent executable cache.
    Executables are keyed by HLO + topology + platform, so TPU and
    virtual-CPU programs share the directory safely."""
    cache = default_cache_dir()
    if cache:
        import os

        os.environ.setdefault(
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5"
        )
        apply_compilation_cache_config(cache)


def apply_cpu_mesh_env(n_devices: int) -> None:
    """Patch os.environ in place (for conftest-style early setup)."""
    import os

    os.environ.update(cpu_mesh_env(n_devices))


def apply_compilation_cache_config(cache_dir: "str | None" = None) -> None:
    """Late-apply the persistent-cache env vars to an already-imported jax.

    jax reads JAX_COMPILATION_CACHE_DIR once, at import; on hosts whose
    sitecustomize imports jax at interpreter start (this machine's does,
    to register the TPU plugin), env vars set afterwards by a conftest or
    a parent process are silently ignored.  Call this after jax import in
    any entry point that wants the shared executable cache.

    `cache_dir` (the --compilation_cache_dir flag) overrides the env var:
    an explicit flag is the job's configuration; the env var is harness
    ambience."""
    import os

    if cache_dir:
        os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    cache = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if not cache:
        return
    import jax

    if jax.config.jax_compilation_cache_dir != cache:
        jax.config.update("jax_compilation_cache_dir", cache)
    min_secs = os.environ.get("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS")
    if min_secs is not None:
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", float(min_secs)
        )
