"""Virtual CPU device-mesh environment setup.

Multi-chip code paths (DP psum, sharded embeddings, ring attention) are
exercised without TPUs by forcing jax onto a virtual n-device CPU mesh —
the CI strategy SURVEY.md §4 prescribes. This helper is the single place
that builds that environment; tests/conftest.py and the driver's
`dryrun_multichip` re-exec both use it so the flag-patching logic cannot
drift.

Stdlib-only: must be importable before jax (env vars have to be set
before the backend initialises).
"""

from __future__ import annotations

import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def cpu_mesh_env(n_devices: int, base: dict | None = None) -> dict:
    """Return a copy of `base` (default os.environ) patched for an
    n-device virtual CPU mesh.

    Always *overrides* any existing device-count flag rather than keeping
    a stale (possibly smaller) value — a smaller inherited count would
    otherwise leave the child short of devices.
    """
    import os

    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(rf"{_COUNT_FLAG}=\d+\s*", "", env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (flags + f" {_COUNT_FLAG}={n_devices}").strip()
    return env


def apply_cpu_mesh_env(n_devices: int) -> None:
    """Patch os.environ in place (for conftest-style early setup)."""
    import os

    os.environ.update(cpu_mesh_env(n_devices))
