"""Unified metrics vocabulary: labeled Counters / Gauges / Histograms in
thread-safe registries with Prometheus text exposition.

Before this module every subsystem kept private numbers (batcher counters,
engine compile counts, resilience retry Counters, RecoveryClock histories)
that only surfaced through bespoke snapshot dicts.  Here the registry IS
the storage: instrumented code registers a metric once and increments it;
the Health RPC, `Master.snapshot()`, `/metrics` exposition, and
`elasticdl top` all read the same objects.

Two scopes compose:

* `default_registry()` — one per process, for process-wide series
  (RPC retries, fault injections, wire bytes, worker step counters).
* per-component `MetricsRegistry()` instances — components that can be
  instantiated many times in one process (batcher, engine, task manager)
  keep instance-scoped values; the role's telemetry server composes the
  relevant registries into one exposition surface.

Naming contract (enforced by scripts/check_metric_names.py): every
metric is `subsystem_name_unit`, lower_snake_case, with the subsystem in
`KNOWN_SUBSYSTEMS` and the unit suffix in `ALLOWED_UNIT_SUFFIXES`.
"""

from __future__ import annotations

import json
import re
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from elasticdl_tpu.common.profiler import LatencyHistogram

_NAME_RE = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)+$")
_LABEL_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# First `_`-separated token of every metric name.
KNOWN_SUBSYSTEMS = frozenset(
    {"master", "worker", "serving", "data", "rpc", "faults", "process",
     "store", "traffic"}
)

# Trailing unit token(s).  `_total` marks counters (Prometheus convention),
# `_seconds`/`_bytes` mark measured quantities (histogram or gauge),
# the rest are dimensionless gauge units kept explicit so a reader never
# has to guess what a number means.
ALLOWED_UNIT_SUFFIXES = (
    "_total",
    "_seconds",
    "_bytes",
    "_ratio",
    "_per_sec",
    "_count",
    "_rows",
    "_step",
    "_steps",  # a step-distance (e.g. cross-replica skew), not a position
    "_epoch",
    "_info",
)

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"


def validate_metric_name(name: str) -> Optional[str]:
    """Returns an error string when `name` violates the naming contract,
    None when it is valid.  Shared with scripts/check_metric_names.py."""
    if not _NAME_RE.match(name):
        return f"{name!r} is not lower_snake_case with >= 2 tokens"
    subsystem = name.split("_", 1)[0]
    if subsystem not in KNOWN_SUBSYSTEMS:
        return (
            f"{name!r} does not start with a known subsystem "
            f"({', '.join(sorted(KNOWN_SUBSYSTEMS))})"
        )
    if not name.endswith(ALLOWED_UNIT_SUFFIXES):
        return (
            f"{name!r} does not end with a unit suffix "
            f"({', '.join(ALLOWED_UNIT_SUFFIXES)})"
        )
    suffix = max(
        (s for s in ALLOWED_UNIT_SUFFIXES if name.endswith(s)), key=len
    )
    if not name[len(subsystem):-len(suffix)].strip("_"):
        return (
            f"{name!r} is only a subsystem and a unit — a metric also "
            "needs a name between them (subsystem_name_unit)"
        )
    return None


def _check_labels(labelnames: Sequence[str]) -> Tuple[str, ...]:
    names = tuple(labelnames)
    for label in names:
        if not _LABEL_RE.match(label):
            raise ValueError(f"invalid label name {label!r}")
    return names


class _Child:
    """One (metric, label-values) series: a float cell under a lock."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counters only go up (inc {amount})")
        with self._lock:
            self._value += amount

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def add(self, amount: float) -> None:
        with self._lock:
            self._value += amount

    def value(self) -> float:
        with self._lock:
            return self._value


class _Family:
    """A named metric family: unlabeled (one implicit child) or labeled
    (children created on first use of each label-value combination)."""

    def __init__(self, name: str, kind: str, help: str,
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = _check_labels(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _Child] = {}
        if not self.labelnames:
            self._children[()] = _Child()

    # ---- child access ---------------------------------------------------

    def labels(self, **labelvalues) -> _Child:
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: got labels {sorted(labelvalues)}, "
                f"declared {list(self.labelnames)}"
            )
        key = tuple(str(labelvalues[ln]) for ln in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = _Child()
            return child

    def _default_child(self) -> _Child:
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {list(self.labelnames)}; "
                "use .labels(...)"
            )
        return self._children[()]

    # unlabeled convenience surface
    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def set(self, value: float) -> None:
        self._default_child().set(value)

    def value(self, **labelvalues) -> float:
        if self.labelnames:
            if labelvalues:
                return self.labels(**labelvalues).value()
            # no labels given on a labeled family: the family total
            return sum(self.child_values().values())
        return self._default_child().value()

    def child_values(self) -> Dict[Tuple[str, ...], float]:
        with self._lock:
            return {key: c.value() for key, c in self._children.items()}

    def reset(self) -> None:
        """Testing escape hatch: drop all recorded values."""
        with self._lock:
            for child in self._children.values():
                child.set(0.0)
            if self.labelnames:
                self._children.clear()

    def samples(self) -> List[Tuple[Tuple[Tuple[str, str], ...], float]]:
        out = []
        for key, value in sorted(self.child_values().items()):
            out.append((tuple(zip(self.labelnames, key)), value))
        return out


class _GaugeFnFamily:
    """A gauge whose value is read from a callable at collection time —
    the component's existing state stays authoritative (queue depths,
    alive-worker counts) with zero double bookkeeping."""

    kind = GAUGE
    labelnames: Tuple[str, ...] = ()

    def __init__(self, name: str, fn: Callable[[], float], help: str):
        self.name = name
        self.help = help
        self._fn = fn

    def value(self) -> float:
        try:
            return float(self._fn())
        except Exception:
            return 0.0

    def samples(self):
        return [((), self.value())]

    def reset(self) -> None:
        pass


class _HistogramFamily:
    """Log-bucketed histogram family reusing LatencyHistogram's bucket
    scheme (bounded-error quantiles, O(1) observe under a lock).

    Unlabeled (the default) it is a drop-in for a bare LatencyHistogram.
    With `labelnames`, each label-value combination gets its own child
    histogram created on first `.labels(...)` — the shape
    `worker_step_phase_seconds{phase="compute"}` needs."""

    kind = HISTOGRAM

    def __init__(self, name: str, help: str, min_value: float = 1e-4,
                 max_value: float = 60.0, growth: float = 1.25,
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = _check_labels(labelnames)
        self._hist_args = dict(
            min_s=min_value, max_s=max_value, growth=growth
        )
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], LatencyHistogram] = {}
        if not self.labelnames:
            self._children[()] = LatencyHistogram(**self._hist_args)

    def labels(self, **labelvalues) -> LatencyHistogram:
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: got labels {sorted(labelvalues)}, "
                f"declared {list(self.labelnames)}"
            )
        key = tuple(str(labelvalues[ln]) for ln in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = LatencyHistogram(
                    **self._hist_args
                )
            return child

    def child_items(self):
        """[(label-value tuple, child histogram)] in sorted label order —
        the per-series iteration exposition needs."""
        with self._lock:
            return sorted(self._children.items())

    def _default_child(self) -> LatencyHistogram:
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {list(self.labelnames)}; "
                "use .labels(...)"
            )
        return self._children[()]

    def observe(self, value: float) -> None:
        self._default_child().record(value)

    # LatencyHistogram-compatible surface so a registry histogram is a
    # drop-in where a bare LatencyHistogram used to live
    def record(self, value: float) -> None:
        self._default_child().record(value)

    def snapshot(self) -> dict:
        return self._default_child().snapshot()

    def quantile(self, q: float) -> float:
        return self._default_child().quantile(q)

    @property
    def count(self) -> int:
        with self._lock:
            children = list(self._children.values())
        return sum(c.count for c in children)

    def mean(self) -> float:
        snap = self._default_child().snapshot()
        return snap["mean_s"]

    def bucket_snapshot(self):
        return self._default_child().bucket_snapshot()

    def reset(self) -> None:  # pragma: no cover - symmetry with _Family
        pass


class MetricsRegistry:
    """Thread-safe get-or-create registry of metric families."""

    def __init__(self, strict_names: bool = True):
        self._strict = strict_names
        self._lock = threading.Lock()
        self._families: Dict[str, object] = {}

    def _register(self, name: str, factory):
        if self._strict:
            err = validate_metric_name(name)
            if err is not None:
                raise ValueError(f"bad metric name: {err}")
        with self._lock:
            existing = self._families.get(name)
            if existing is None:
                existing = self._families[name] = factory()
            return existing

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> _Family:
        fam = self._register(
            name, lambda: _Family(name, COUNTER, help, labelnames)
        )
        if getattr(fam, "kind", None) != COUNTER:
            raise ValueError(f"{name} already registered as {fam.kind}")
        return fam

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> _Family:
        fam = self._register(
            name, lambda: _Family(name, GAUGE, help, labelnames)
        )
        if getattr(fam, "kind", None) != GAUGE:
            raise ValueError(f"{name} already registered as {fam.kind}")
        return fam

    def gauge_fn(self, name: str, fn: Callable[[], float],
                 help: str = "") -> _GaugeFnFamily:
        fam = self._register(name, lambda: _GaugeFnFamily(name, fn, help))
        if not isinstance(fam, _GaugeFnFamily):
            raise ValueError(f"{name} already registered as {fam.kind}")
        # Latest registrant wins: a re-created component (get-or-create
        # registries outlive job-scoped objects) must not leave the
        # gauge reading a dead instance.
        fam._fn = fn
        return fam

    def histogram(self, name: str, help: str = "", min_value: float = 1e-4,
                  max_value: float = 60.0, growth: float = 1.25,
                  labelnames: Sequence[str] = ()) -> _HistogramFamily:
        fam = self._register(
            name,
            lambda: _HistogramFamily(name, help, min_value, max_value,
                                     growth, labelnames),
        )
        if not isinstance(fam, _HistogramFamily):
            raise ValueError(f"{name} already registered as {fam.kind}")
        return fam

    # ---- reads ----------------------------------------------------------

    def families(self) -> List[object]:
        with self._lock:
            return list(self._families.values())

    def value(self, name: str, **labelvalues) -> float:
        with self._lock:
            fam = self._families.get(name)
        if fam is None:
            return 0.0
        if isinstance(fam, _HistogramFamily):
            return float(fam.count)
        if labelvalues:
            return fam.labels(**labelvalues).value()
        return fam.value()

    def snapshot(self) -> Dict[str, float]:
        """Flat {series: value} view for varz / Master.snapshot / bench.
        Histograms contribute `<name>_count`, `<name>_sum`, and bounded-
        error p50/p99 series."""
        out: Dict[str, float] = {}
        for fam in self.families():
            if isinstance(fam, _HistogramFamily):
                for key, hist in fam.child_items():
                    labelpairs = tuple(zip(fam.labelnames, key))
                    uppers, counts, total, sum_v = hist.bucket_snapshot()
                    out[_series_key(f"{fam.name}_count", labelpairs)] = \
                        float(total)
                    out[_series_key(f"{fam.name}_sum", labelpairs)] = \
                        float(sum_v)
                    out[_series_key(f"{fam.name}_p50", labelpairs)] = \
                        hist._quantile_from(uppers, counts, total, 0.5)
                    out[_series_key(f"{fam.name}_p99", labelpairs)] = \
                        hist._quantile_from(uppers, counts, total, 0.99)
                continue
            for labelpairs, value in fam.samples():
                out[_series_key(fam.name, labelpairs)] = value
        return out


def _series_key(name: str, labelpairs) -> str:
    if not labelpairs:
        return name
    inner = ",".join(f'{ln}="{lv}"' for ln, lv in labelpairs)
    return f"{name}{{{inner}}}"


def _escape_label_value(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


_default_registry = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry for singleton subsystems."""
    return _default_registry


def _flatten(registries) -> List[MetricsRegistry]:
    """Accepts registries and zero-arg callables returning registries (or
    lists of registries) — late binding for components built after the
    telemetry server starts."""
    out: List[MetricsRegistry] = []
    for item in registries:
        if callable(item) and not isinstance(item, MetricsRegistry):
            item = item()
        if item is None:
            continue
        if isinstance(item, MetricsRegistry):
            out.append(item)
        else:
            out.extend(r for r in item if isinstance(r, MetricsRegistry))
    return out


def render_text(registries: Iterable) -> str:
    """Prometheus text exposition (format 0.0.4) over one or more
    registries.  When several registries define the same family name the
    samples concatenate; an identical (name, labels) series from a later
    registry replaces the earlier one (one process = one truth)."""
    families: Dict[str, List[object]] = {}
    for registry in _flatten(registries):
        for fam in registry.families():
            families.setdefault(fam.name, []).append(fam)

    lines: List[str] = []
    for name in sorted(families):
        group = families[name]
        head = group[0]
        help_text = next((f.help for f in group if f.help), "")
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {head.kind}")
        if head.kind == HISTOGRAM:
            for fam in group:
                for key, hist in fam.child_items():
                    labelpairs = tuple(zip(fam.labelnames, key))
                    inner = ",".join(
                        f'{ln}="{_escape_label_value(str(lv))}"'
                        for ln, lv in labelpairs
                    )
                    sep = "," if inner else ""
                    uppers, counts, total, sum_v = hist.bucket_snapshot()
                    cumulative = 0
                    for upper, count in zip(uppers, counts):
                        cumulative += count
                        lines.append(
                            f'{name}_bucket{{{inner}{sep}'
                            f'le="{upper:.6g}"}} {cumulative}'
                        )
                    lines.append(
                        f'{name}_bucket{{{inner}{sep}le="+Inf"}} {total}'
                    )
                    if inner:
                        lines.append(
                            f"{name}_sum{{{inner}}} {sum_v:.9g}"
                        )
                        lines.append(f"{name}_count{{{inner}}} {total}")
                    else:
                        lines.append(f"{name}_sum {sum_v:.9g}")
                        lines.append(f"{name}_count {total}")
            continue
        seen: Dict[str, str] = {}
        for fam in group:
            for labelpairs, value in fam.samples():
                if labelpairs:
                    inner = ",".join(
                        f'{ln}="{_escape_label_value(str(lv))}"'
                        for ln, lv in labelpairs
                    )
                    series = f"{name}{{{inner}}}"
                else:
                    series = name
                seen[series] = f"{series} {value:.9g}"
        lines.extend(seen[k] for k in sorted(seen))
    return "\n".join(lines) + "\n"


def varz(registries: Iterable, role: str = "",
         extra: Optional[dict] = None) -> str:
    """Debug JSON snapshot served at /varz: flat metric series plus
    whatever structured extras the role wants to expose."""
    import os

    merged: Dict[str, float] = {}
    for registry in _flatten(registries):
        merged.update(registry.snapshot())
    doc = {
        "role": role,
        "pid": os.getpid(),
        "time_unix_s": time.time(),
        "metrics": merged,
    }
    if extra:
        doc.update(extra)
    return json.dumps(doc, sort_keys=True, default=str)
