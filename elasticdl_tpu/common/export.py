"""Final model export.

Parity: reference ModelHandler.get_model_to_export + SavedModel export
(SURVEY.md C9/C14).  The reference rewrote `elasticdl.Embedding` layers
back to `keras.Embedding` before export; here the sharded tables are
ordinary arrays in the param tree, so export is a gather-to-host plus
serialization — no layer rewrite needed.

Format: `params.msgpack` (flax serialization of {params, model_state}) +
`export_meta.json` (module/model info for reloading).  Re-load with
`load_exported` into a freshly constructed zoo model.
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import numpy as np
from flax import serialization

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)


def export_model(state, spec, output_dir: str) -> str:
    os.makedirs(output_dir, exist_ok=True)
    host_tree = {
        "params": jax.tree.map(np.asarray, state.params),
        "model_state": jax.tree.map(np.asarray, state.model_state),
    }
    path = os.path.join(output_dir, "params.msgpack")
    with open(path, "wb") as f:
        f.write(serialization.to_bytes(host_tree))
    meta = {
        "step": int(state.step),
        "module": getattr(spec.module, "__name__", None),
        "model_class": type(spec.model).__name__,
        "framework": "elasticdl-tpu",
    }
    with open(os.path.join(output_dir, "export_meta.json"), "w") as f:
        json.dump(meta, f, indent=2)
    return path


def load_exported(output_dir: str, template: Any):
    """Restore exported variables into `template` (a {params, model_state}
    dict with matching structure, e.g. from model.init)."""
    with open(os.path.join(output_dir, "params.msgpack"), "rb") as f:
        return serialization.from_bytes(template, f.read())
