"""Final model export.

Parity: reference ModelHandler.get_model_to_export + SavedModel export
(SURVEY.md C9/C14).  The reference rewrote `elasticdl.Embedding` layers
back to `keras.Embedding` before export; here the sharded tables are
ordinary arrays in the param tree, so export is a gather-to-host plus
serialization — no layer rewrite needed.

Formats:
- `params.msgpack` (flax serialization of {params, model_state}) +
  `export_meta.json` (module/model info) — always written; re-load with
  `load_exported` into a freshly constructed zoo model.
- `saved_model/` — optional TF SavedModel (`--export_saved_model`): the
  model's forward pass staged through jax2tf with a polymorphic batch
  dimension, params embedded as tf.Variables, serving signature named
  after the feature keys.  This is the serving handoff the reference's
  SavedModel export provided; any TF Serving stack consumes it with no
  JAX at inference time.
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import numpy as np
from flax import serialization

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)

# Feature-dict key used when a model's feed yields a single array instead
# of a dict (MNIST); the serving protocol and export meta both use it so
# single-input and dict-input models share one wire shape.
SINGLE_FEATURE_KEY = "features"


def feature_meta(sample_features: Any) -> dict:
    """Per-feature serving signature: {name: {shape: per-row dims, dtype}}.
    The batch dimension is dropped — it is the serving system's to choose."""

    def leaf(v):
        v = np.asarray(v)
        return {
            "shape": [int(d) for d in v.shape[1:]],
            "dtype": str(v.dtype),
        }

    if isinstance(sample_features, dict):
        return {str(k): leaf(v) for k, v in sample_features.items()}
    return {SINGLE_FEATURE_KEY: leaf(sample_features)}


def read_export_meta(output_dir: str) -> dict:
    with open(os.path.join(output_dir, "export_meta.json")) as f:
        return json.load(f)


def export_model(
    state,
    spec,
    output_dir: str,
    saved_model: bool = False,
    sample_features: Any = None,
) -> str:
    os.makedirs(output_dir, exist_ok=True)
    # owning copies: np.asarray views would alias device buffers that a
    # later donating train step reuses (parallel/collectives.host_snapshot)
    from elasticdl_tpu.parallel.collectives import host_snapshot

    host_tree = {
        "params": host_snapshot(state.params),
        "model_state": host_snapshot(state.model_state),
    }
    path = os.path.join(output_dir, "params.msgpack")
    with open(path, "wb") as f:
        f.write(serialization.to_bytes(host_tree))
    meta = {
        "step": int(state.step),
        "module": getattr(spec.module, "__name__", None),
        "model_class": type(spec.model).__name__,
        "framework": "elasticdl-tpu",
    }
    if sample_features is not None:
        # the export's serving signature: feature keys + per-row
        # shape/dtype.  Serving (serving/engine.py) loads against these
        # and load_exported cross-checks them against the consumer's
        # model, so a zoo-definition drift fails loudly at load, not as
        # a shape error deep inside jit.
        meta["features"] = feature_meta(sample_features)

    def write_meta():
        with open(os.path.join(output_dir, "export_meta.json"), "w") as f:
            json.dump(meta, f, indent=2)

    # meta is ALWAYS written (module contract) — before the SavedModel
    # attempt, so a raise/crash mid-export still leaves a loadable
    # msgpack + meta pair; re-written below with the SavedModel outcome.
    write_meta()
    if saved_model:
        if sample_features is None:
            # raise so export_for_task re-queues to a worker that HAS
            # processed a batch — a silent skip would let the job report
            # success with <output>/saved_model never written (the same
            # discipline worker.export_for_task applies to missing state)
            raise RuntimeError(
                "SavedModel export requested but this worker captured no "
                "sample features (no batch ever reached it); re-queueing"
            )
        try:
            export_saved_model(
                state, spec, os.path.join(output_dir, "saved_model"),
                sample_features,
            )
            meta["saved_model"] = "ok"
        except ImportError as exc:
            # no TensorFlow in the image: a documented, non-retryable
            # deployment condition — record it in the export metadata
            # (ADVICE r3: a log line alone let the job read as fully
            # successful) and keep the msgpack export
            meta["saved_model"] = f"unavailable: {exc}"
            logger.error(
                "SavedModel export unavailable (%s); wrote params.msgpack "
                "only", exc,
            )
        except Exception as exc:
            # Conversion/disk failures: the msgpack export above is still
            # valid, so don't kill a finished training job — but surface
            # the miss durably in export_meta.json, not only in a log
            # record, so the job's final artifacts say what's missing.
            meta["saved_model"] = f"failed: {exc}"
            logger.error(
                "SavedModel export failed (%s); wrote params.msgpack "
                "only", exc,
            )
        write_meta()
    return path


def export_saved_model(
    state, spec, output_dir: str, sample_features: Any
) -> str:
    """Stage the model's forward pass into a TF SavedModel via jax2tf.

    sample_features: one host batch of features (any batch size) — used
    only for structure/shape/dtype of the serving signature; the batch
    dimension is exported polymorphic.
    """
    import tensorflow as tf
    from jax.experimental import jax2tf

    from elasticdl_tpu.parallel import mesh as mesh_lib

    model = spec.model
    variables = {
        **jax.tree.map(np.asarray, state.params),
        **jax.tree.map(np.asarray, state.model_state),
    }
    from elasticdl_tpu.worker.trainer import model_has_train_kwarg

    has_train = model_has_train_kwarg(model)

    def apply_fn(variables, features):
        kwargs = {"train": False} if has_train else {}
        # export mode: mesh-manual ops (ring attention, GPipe schedule,
        # Pallas flash kernel) switch to their single-device lax
        # formulations — shard_map/custom-calls cannot stage through
        # jax2tf, and the param tree is identical by design
        with mesh_lib.export_mode():
            return model.apply(variables, features, **kwargs)

    def poly_spec(x):
        nd = np.ndim(x)
        inner = (", " + ", ".join(["_"] * (nd - 1))) if nd > 1 else ""
        return f"(b{inner})"

    tf_fn = jax2tf.convert(
        apply_fn,
        polymorphic_shapes=[None, jax.tree.map(poly_spec, sample_features)],
        with_gradient=False,
        # Multi-platform lowering: a model trained on TPU must serve on
        # CPU/GPU TF Serving hosts — single-platform native serialization
        # embeds the training platform and refuses to load elsewhere
        # (observed: module exported under the TPU session failed to load
        # on CPU with "platform CPU is not among the platforms required").
        native_serialization_platforms=("cpu", "cuda", "tpu"),
    )
    module = tf.Module()
    module.v = tf.nest.map_structure(tf.Variable, variables)

    def leaf_spec(value, name):
        value = np.asarray(value)
        return tf.TensorSpec(
            (None,) + value.shape[1:], value.dtype, name=name
        )

    if isinstance(sample_features, dict):
        signature = {
            k: leaf_spec(v, k) for k, v in sample_features.items()
        }
    else:
        signature = leaf_spec(sample_features, "features")

    @tf.function(autograph=False)
    def serve(features):
        return tf_fn(module.v, features)

    concrete = serve.get_concrete_function(signature)
    tf.saved_model.save(
        module, output_dir, signatures={"serving_default": concrete}
    )
    logger.info("Exported TF SavedModel to %s", output_dir)
    return output_dir


def load_exported(
    output_dir: str,
    template: Any,
    expected_features: Any = None,
    check_only: bool = False,
):
    """Restore exported variables into `template` (a {params, model_state}
    dict with matching structure, e.g. from model.init).

    `expected_features`: the consumer model's input signature — a sample
    feature batch/dict, or an iterable of feature-key names.  When given
    AND the export recorded its own signature, the key sets are
    cross-checked and a mismatch raises ValueError naming both sides —
    catching a zoo model whose feed was edited since the export, which
    otherwise surfaces as an inscrutable shape error inside jit (or,
    worse, silently mis-keyed features).  Exports from before signatures
    were recorded skip the check.
    """
    if expected_features is not None:
        meta = {}
        try:
            meta = read_export_meta(output_dir)
        except (OSError, json.JSONDecodeError):
            pass  # meta missing/corrupt: msgpack load below still governs
        exported = meta.get("features")
        if exported is not None:
            if isinstance(expected_features, dict):
                expected_keys = {str(k) for k in expected_features}
            elif isinstance(
                expected_features, (list, tuple, set, frozenset)
            ):
                expected_keys = {str(k) for k in expected_features}
            else:  # a single sample array (MNIST-style feed)
                expected_keys = {SINGLE_FEATURE_KEY}
            if set(exported) != expected_keys:
                raise ValueError(
                    f"export at {output_dir} was written for feature keys "
                    f"{sorted(exported)} but the model expects "
                    f"{sorted(expected_keys)}; the model definition has "
                    "drifted since export — re-export the model or load "
                    "it with the matching zoo definition"
                )
    if check_only:
        return None
    with open(os.path.join(output_dir, "params.msgpack"), "rb") as f:
        return serialization.from_bytes(template, f.read())
