"""GPipe-style pipeline parallelism over the mesh `pipe` axis.

Net-new TPU capability relative to the reference (SURVEY.md §2: upstream
ships data parallelism plus sharded embeddings ONLY — no pipeline
parallelism).  Design is TPU-first rather than a port of any GPU pipeline
runtime:

- The layer stack is ONE stacked pytree (leading `num_layers` axis) whose
  leaves are sharded over `pipe`, so stage s holds layers
  [s*L/P, (s+1)*L/P) in HBM — no per-stage processes, no RPC.
- Scheduling is a single `lax.scan` over M + P - 1 ticks inside
  `shard_map`: every tick each stage applies its local layers to its
  current microbatch and hands the activation to the next stage with
  `jax.lax.ppermute` (a neighbor hop over ICI).  XLA compiles the whole
  schedule into one fused loop; there is no host-side orchestration per
  microbatch.
- Backward is just `jax.grad` through the scan: `ppermute` transposes to
  the reverse rotation, so the backward pipeline runs in the opposite
  direction automatically — no hand-written 1F1B state machine.

The classic GPipe bubble (P - 1 idle ticks out of M + P - 1) is the cost;
choose num_microbatches >= 4 * stages to keep it under ~20%.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common.jax_compat import pcast_to_varying, shard_map
from elasticdl_tpu.parallel.mesh import DATA_AXIS, PIPE_AXIS


def _sequential(apply_fn: Callable, stacked_params: Any, x):
    """Reference semantics: layers applied in order (pipe axis of size 1)."""

    def body(h, p):
        return apply_fn(p, h), None

    return lax.scan(body, x, stacked_params)[0]


def _pipeline_local(
    stacked_local_params: Any,
    x: jnp.ndarray,
    *,
    apply_fn: Callable,
    stages: int,
    num_microbatches: int,
    data_axis: str,
    pipe_axis: str,
    remat: bool,
):
    """Runs INSIDE shard_map.  x: (B_local, ...) activations for this data
    shard (replicated over `pipe`); stacked_local_params: this stage's
    (L/P, ...) slice of the layer stack."""
    mstages, batch = stages, x.shape[0]
    mcount = num_microbatches
    stage = lax.axis_index(pipe_axis)
    micro = x.reshape((mcount, batch // mcount) + x.shape[1:])

    def apply_stage(h):
        def body(h2, p):
            return apply_fn(p, h2), None

        return lax.scan(body, h, stacked_local_params)[0]

    if remat:
        apply_stage = jax.checkpoint(apply_stage)

    def varying(v):
        return pcast_to_varying(v, (data_axis, pipe_axis))

    mb_shape = micro.shape[1:]
    state0 = varying(jnp.zeros(mb_shape, x.dtype))
    out0 = varying(jnp.zeros(micro.shape, x.dtype))
    # forward rotation only: stage 0 never receives, it feeds fresh
    # microbatches, so the hop P-1 -> 0 is omitted (no wrap traffic)
    perm = [(i, i + 1) for i in range(mstages - 1)]

    def tick(carry, t):
        state, out_buf = carry
        recv = lax.ppermute(state, pipe_axis, perm) if perm else state
        feed = lax.dynamic_index_in_dim(
            micro, jnp.minimum(t, mcount - 1), axis=0, keepdims=False
        )
        h_in = jnp.where(stage == 0, feed, recv)
        h_out = apply_stage(h_in)
        # the last stage's output at tick t is microbatch t-(P-1); ticks
        # before the pipeline fills write garbage to slot 0, which tick
        # t = P-1 then overwrites with the real microbatch 0
        slot = jnp.clip(t - (mstages - 1), 0, mcount - 1)
        out_buf = lax.dynamic_update_index_in_dim(out_buf, h_out, slot, 0)
        return (h_out, out_buf), None

    (_, out_buf), _ = lax.scan(
        tick, (state0, out0), jnp.arange(mcount + mstages - 1)
    )
    out = out_buf.reshape(x.shape)
    # only the last stage holds real outputs; psum both broadcasts them to
    # every pipe shard (making the result pipe-invariant, as the unmapped
    # out_spec requires) and zeroes nothing real (other stages contribute 0)
    out = jnp.where(stage == mstages - 1, out, jnp.zeros_like(out))
    return lax.psum(out, pipe_axis)


def gpipe_spmd(
    apply_fn: Callable,
    stacked_params: Any,
    x: jnp.ndarray,
    mesh,
    num_microbatches: int = 8,
    data_axis: str = DATA_AXIS,
    pipe_axis: str = PIPE_AXIS,
    remat: bool = False,
):
    """Apply a stacked layer pytree to x as a pipeline over mesh[`pipe`].

    apply_fn: (one_layer_params, h) -> h, shape-preserving (transformer
              block contract).
    stacked_params: pytree whose leaves have leading dim num_layers,
              sharded P(pipe) on that dim (pipeline_param_sharding).
    x:        (B, ...) activations, batch sharded P(data).

    Degenerates to a plain sequential scan when the pipe axis is 1 — so a
    model configured for pipelining trains identically (same param tree,
    same numerics) on a mesh without a pipe dimension; checkpoints move
    between the two meshes unchanged (the cross-mesh restore story,
    tests/test_remesh.py).
    """
    stages = mesh.shape[pipe_axis]
    num_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    from elasticdl_tpu.parallel.mesh import in_export_mode

    if stages == 1 or in_export_mode():
        # pipe=1 — or serving export, where shard_map cannot stage
        # through jax2tf: the sequential scan is the same computation on
        # the same stacked param tree.
        return _sequential(apply_fn, stacked_params, x)
    if num_layers % stages:
        raise ValueError(
            f"num_layers={num_layers} not divisible by pipe={stages}"
        )
    local_batch = x.shape[0] // mesh.shape[data_axis]
    if local_batch % num_microbatches:
        raise ValueError(
            f"per-data-shard batch {local_batch} not divisible by "
            f"num_microbatches={num_microbatches}"
        )
    fn = functools.partial(
        _pipeline_local,
        apply_fn=apply_fn,
        stages=stages,
        num_microbatches=num_microbatches,
        data_axis=data_axis,
        pipe_axis=pipe_axis,
        remat=remat,
    )
    param_spec = jax.tree.map(lambda _: P(pipe_axis), stacked_params)
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(param_spec, P(data_axis)),
        out_specs=P(data_axis),
    )(stacked_params, x)
