"""Ring attention: sequence/context parallelism over the mesh `seq` axis.

Net-new TPU capability relative to the reference (SURVEY.md §5 records the
reference has NO sequence parallelism; long-context is first-class here).
Design follows the blockwise ring-attention recipe (Liu et al.; see
PAPERS.md): Q stays resident per shard, K/V blocks rotate around the ring
via `jax.lax.ppermute` over ICI, and attention accumulates with the online
(flash) softmax — running max `m`, normaliser `l`, unnormalised output `o`
rescaled as blocks arrive.  Peak memory per chip is O(L_local^2) instead of
O(L^2), and the N-step rotation overlaps compute with neighbor transfers.

Everything is expressed with static-shape `lax.scan` + collectives so XLA
compiles one fused loop; no data-dependent Python control flow.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common.jax_compat import pcast_to_varying, shard_map
from elasticdl_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS

_NEG_INF = -1e30


def _ring_attention_local(
    q, k, v, *, ring_size: int, axis_name: str, causal: bool, scale: float,
    varying_axes: tuple,
):
    """Runs INSIDE shard_map.  q/k/v: (B, L_local, H, D) local blocks."""
    batch, q_len, heads, dim = q.shape
    k_len = k.shape[1]
    my_block = jax.lax.axis_index(axis_name)
    q_pos = my_block * q_len + jnp.arange(q_len)          # global positions

    perm = [(j, (j + 1) % ring_size) for j in range(ring_size)]

    # accumulators: (B, H, Lq) softmax stats, (B, H, Lq, D) output.
    # pcast-to-varying marks them as shard-varying so the scan carry
    # types match the per-shard loop outputs.
    def _varying(x):
        return pcast_to_varying(x, varying_axes)

    m0 = _varying(jnp.full((batch, heads, q_len), _NEG_INF, jnp.float32))
    l0 = _varying(jnp.zeros((batch, heads, q_len), jnp.float32))
    o0 = _varying(jnp.zeros((batch, heads, q_len, dim), jnp.float32))

    def step(carry, step_idx):
        o, m, l, k_cur, v_cur = carry
        # the block currently held arrived from shard (my - step) mod n
        src_block = (my_block - step_idx) % ring_size
        k_pos = src_block * k_len + jnp.arange(k_len)
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_cur,
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]        # (Lq, Lk)
            logits = jnp.where(mask[None, None], logits, _NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        # guard fully-masked rows (m_new == -inf): keep weights at zero
        p = jnp.exp(logits - m_new[..., None])
        p = jnp.where(jnp.isfinite(logits), p, 0.0)
        correction = jnp.exp(m - m_new)
        l_new = l * correction + p.sum(axis=-1)
        o_new = o * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_cur,
            preferred_element_type=jnp.float32,
        )
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    (o, m, l, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(ring_size)
    )
    out = o / jnp.maximum(l, 1e-30)[..., None]             # (B, H, Lq, D)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)       # (B, Lq, H, D)


def ring_self_attention(
    q, k, v, mesh, causal: bool = False, scale: Optional[float] = None,
    data_axis: str = DATA_AXIS, seq_axis: str = SEQ_AXIS,
):
    """Sequence-parallel attention over `mesh`'s seq axis.

    q/k/v: (B, L, H, D) GLOBAL arrays (sharded or shardable as
    P(data, seq, None, None)); returns same shape/sharding.
    Degenerates to one local flash-style pass when the seq axis is 1.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    from elasticdl_tpu.parallel.mesh import in_export_mode

    if in_export_mode():
        # Serving export: jax2tf cannot stage shard_map/Pallas; the plain
        # lax formulation is numerically the same computation.
        return full_attention_reference(q, k, v, causal=causal, scale=scale)
    ring_size = mesh.shape[seq_axis]
    spec = P(data_axis, seq_axis, None, None)
    if ring_size == 1:
        # Sequence axis unsharded: every K/V block is local, so skip the
        # ring machinery and run the Pallas flash kernel (same online
        # softmax, tiled in VMEM — ops/flash_attention.py).  Still under
        # shard_map over the SAME specs: each data shard runs the kernel
        # on its local batch, so inputs stay batch-sharded and the output
        # keeps the documented sharding (a bare call would force full
        # replication under jit).  Tile-shape constraints (L % 128,
        # D <= 128) take the fused-lax ring body with ring size 1 instead
        # — dispatched on an EXPLICIT shape check: a blanket
        # `except ValueError` here once swallowed a shard_map vma error
        # and silently downgraded every single-chip run (bench included)
        # to the O(L^2) path (round-5 on-chip profile finding).
        # check_vma=False: the kernel types its outputs' vma from its
        # inputs for real TPU lowering, but interpret mode (CPU tests)
        # re-evaluates the kernel body where the block-slicing internals
        # mix varying and invariant operands and fail the audit; the
        # wrapper's in/out specs still pin the sharding contract.
        from elasticdl_tpu.ops.flash_attention import (
            flash_attention,
            flash_shapes_ok,
        )

        if flash_shapes_ok(q.shape, k.shape):
            return shard_map(
                functools.partial(
                    flash_attention, causal=causal, scale=scale
                ),
                mesh=mesh,
                in_specs=(spec, spec, spec),
                out_specs=spec,
                check_vma=False,
            )(q, k, v)
    fn = functools.partial(
        _ring_attention_local,
        ring_size=ring_size,
        axis_name=seq_axis,
        causal=causal,
        scale=scale,
        varying_axes=(data_axis, seq_axis),
    )
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def full_attention_reference(q, k, v, causal: bool = False,
                             scale: Optional[float] = None):
    """O(L^2) single-device attention — the numerical reference ring
    attention is validated against in tests."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        q_len, k_len = q.shape[1], k.shape[1]
        mask = jnp.arange(q_len)[:, None] >= jnp.arange(k_len)[None, :]
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bhqd", weights, v, preferred_element_type=jnp.float32
    )
    return out.transpose(0, 2, 1, 3).astype(q.dtype)
