"""Pallas TPU flash-attention kernel (single-shard fast path).

The framework's attention stack has two tiers (SURVEY.md §5 long-context —
net-new capability vs the reference, which has no attention ops at all):

- cross-chip: `ops/ring_attention.py` rotates K/V blocks over ICI with
  online-softmax accumulation (sequence scales with chips);
- on-chip (this module): a hand-written Pallas kernel computes the local
  attention with the same online softmax, tiled for the MXU/VMEM instead
  of materialising the (L, L) score matrix in HBM.  Used by
  `ring_self_attention` when the mesh's `seq` axis is 1 (every block is
  local) and directly by models.

Kernel shape (round 5 — second generation): inputs stay in the model's
native (B, L, H, D) layout viewed as (B, L, H*D) — a FREE reshape — and
the grid runs over (B, Lq/BLOCK_Q) with a static per-head loop inside
each program slicing D-wide column chunks.  The first-generation kernel
merged to (B*H, L, D) via transposes that cost ~23 ms/step of pure
layout copies in the BERT bench (docs/BERT_PROFILE.md) and ran more,
smaller grid programs; this layout measures ~19% faster solo AND deletes
the transposes.  Each program holds one Q tile resident in VMEM and
streams K/V tiles, carrying the running max `m`, normaliser `l` and
unnormalised accumulator in f32.  Causal masking prunes whole K tiles
above the diagonal.  The FORWARD is O(L) in HBM (nothing (L, L)-shaped
is ever materialised; only the log-sum-exp is saved).  Backward is a
`jax.custom_vjp` that recomputes probabilities from the saved
log-sum-exp in plain jnp on the (B, L, H, D) layout — XLA fuses it, but
its einsum operands are O(L^2), so truly long-context TRAINING belongs
to the ring tier (sequence sharded over chips), where per-chip lengths
stay modest.

Off-TPU the kernel runs in Pallas interpret mode (tests exercise the SAME
kernel code path on CPU).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int, causal: bool,
    scale: float, q_len: int, k_len: int, block_q: int, heads: int,
    dim: int,
):
    qi = pl.program_id(1)
    # operands stay in the INPUT dtype (bf16 in mixed-precision training)
    # so the MXU runs at full rate — f32 upcasts before the dots would
    # quarter the matmul rate on v5e; accumulation is f32 via
    # preferred_element_type, softmax math is f32.
    q_all = q_ref[0]                                    # (BLOCK_Q, H*D)
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0
    )
    num_kb = k_len // block_k
    if causal:
        # K tiles strictly above this Q tile's diagonal are all-masked:
        # stop the stream early instead of computing and zeroing them.
        last_kb = jnp.minimum(
            (qi + 1) * block_q + block_k - 1, k_len
        ) // block_k
        num_iters = jnp.minimum(num_kb, last_kb)
    else:
        num_iters = num_kb

    # STATIC head loop (Mosaic has no dynamic_slice on values): each head
    # is a D-wide column chunk of the (BLOCK_Q, H*D) tile; the compiler
    # reuses one set of scratch buffers across the unrolled iterations.
    for h in range(heads):
        lo = h * dim
        q = q_all[:, lo:lo + dim]                       # (BLOCK_Q, D)

        def body(kb, carry, lo=lo, q=q):
            o, m, l = carry
            k = k_ref[0, pl.ds(kb * block_k, block_k), lo:lo + dim]
            v = v_ref[0, pl.ds(kb * block_k, block_k), lo:lo + dim]
            logits = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                   # (BLOCK_Q, BLOCK_K)
            if causal:
                k_pos = kb * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (1, block_k), 1
                )
                logits = jnp.where(q_pos >= k_pos, logits, _NEG_INF)
            m_new = jnp.maximum(m, logits.max(axis=-1, keepdims=True))
            p = jnp.exp(logits - m_new)
            if causal:
                # rows fully masked in this tile contribute nothing
                p = jnp.where(logits > _NEG_INF / 2, p, 0.0)
            correction = jnp.exp(m - m_new)
            l_new = l * correction + p.sum(axis=-1, keepdims=True)
            o_new = o * correction + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return o_new, m_new, l_new

        o0 = jnp.zeros((block_q, dim), jnp.float32)
        m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((block_q, 1), jnp.float32)
        o, m, l = jax.lax.fori_loop(0, num_iters, body, (o0, m0, l0))
        l_safe = jnp.maximum(l, 1e-30)
        o_ref[0, :, lo:lo + dim] = (o / l_safe).astype(o_ref.dtype)
        # lse block is (BLOCK_Q, H): per-head column write; H as the
        # block's last dim equals the array's, satisfying the TPU
        # lowering's last-two-dims rule for any head count
        lse_ref[0, :, h:h + 1] = m + jnp.log(l_safe)


def _pallas_forward(q3, k3, v3, causal: bool, scale: float, block_q: int,
                    block_k: int, heads: int, dim: int, interpret: bool):
    """q3/k3/v3: (B, L, H*D) -> (out (B, L, H*D), lse (B, L, H))."""
    batch, q_len, hd = q3.shape
    k_len = k3.shape[1]
    grid = (batch, q_len // block_q)
    kernel = functools.partial(
        _fwd_kernel,
        block_k=block_k,
        causal=causal,
        scale=scale,
        q_len=q_len,
        k_len=k_len,
        block_q=block_q,
        heads=heads,
        dim=dim,
    )

    # Outputs inherit the inputs' varying-axes type (vma): inside a
    # shard_map with the varying-axis audit on, an untyped out_shape is a
    # ValueError — which round 4's blanket except silently converted into
    # the O(L^2) fallback on every single-chip run (round-5 profile
    # finding).  Older jax without vma typing skips the annotation.
    def out_struct(shape, dtype):
        try:
            vma = frozenset().union(
                *(jax.typeof(x).vma for x in (q3, k3, v3))
            )
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        except (AttributeError, TypeError):
            return jax.ShapeDtypeStruct(shape, dtype)

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, hd), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, k_len, hd), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, k_len, hd), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, hd), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, heads), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            out_struct((batch, q_len, hd), q3.dtype),
            out_struct((batch, q_len, heads), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, scale):
    return _flash_fwd(q, k, v, causal, scale)[0]


def _pick_block(length: int) -> int:
    # 256-512-sized tiles measured 1.6-2x the 128-tile rate on v5e
    # (docs/BERT_PROFILE.md): per-grid-program overhead dominates these
    # small-matmul kernels, so fewer/larger programs win.  Blocks must
    # divide the length (the grid streams whole tiles).
    for cand in (512, 256, 128):
        if length >= cand and length % cand == 0:
            return cand
    return length


def _flash_fwd(q, k, v, causal, scale):
    batch, q_len, heads, dim = q.shape
    k_len = k.shape[1]
    hd = heads * dim
    # measured optimum at BERT-base shapes: Q tiles of 256 with K
    # streamed in 512s (10.3 TFLOPs solo vs 9.9 at 512/512)
    block_q = 256 if q_len % 256 == 0 else _pick_block(q_len)
    block_k = _pick_block(k_len)
    out3, lse = _pallas_forward(
        q.reshape(batch, q_len, hd),
        k.reshape(batch, k_len, hd),
        v.reshape(batch, k_len, hd),
        causal, scale, block_q, block_k, heads, dim, _use_interpret(),
    )
    out = out3.reshape(batch, q_len, heads, dim)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, residuals, g):
    """Flash backward by recompute: probabilities are rebuilt from the
    saved log-sum-exp, so nothing O(L^2) was ever saved.  Expressed in
    jnp on the (B, L, H, D) layout — XLA fuses the whole thing (the
    O(L^2) intermediate lives only inside the fused computation) and
    folds the bhqk<->blhd layout changes into the matmuls instead of
    materialising transposes."""
    q, k, v, out, lse = residuals            # lse: (B, Lq, H)
    # matmul operands in the input dtype (MXU full rate), f32 accumulate;
    # softmax/correction math in f32
    g = g.astype(q.dtype)
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        q_len, k_len = q.shape[1], k.shape[1]
        mask = jnp.arange(q_len)[:, None] >= jnp.arange(k_len)[None, :]
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    p = jnp.exp(logits - lse.transpose(0, 2, 1)[..., None])
    pc = p.astype(q.dtype)
    dv = jnp.einsum(
        "bhqk,bqhd->bkhd", pc, g, preferred_element_type=jnp.float32
    )
    dp = jnp.einsum(
        "bqhd,bkhd->bhqk", g, v, preferred_element_type=jnp.float32
    )
    delta = (
        (g.astype(jnp.float32) * out.astype(jnp.float32))
        .sum(-1)                              # (B, Lq, H)
        .transpose(0, 2, 1)[..., None]        # (B, H, Lq, 1)
    )
    ds = (p * (dp - delta) * scale).astype(q.dtype)
    dq = jnp.einsum(
        "bhqk,bkhd->bqhd", ds, k, preferred_element_type=jnp.float32
    )
    dk = jnp.einsum(
        "bhqk,bqhd->bkhd", ds, q, preferred_element_type=jnp.float32
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


# Per-program K/V VMEM residency ceiling: the (B, L, H*D)-layout kernel
# holds a WHOLE (k_len, H*D) K and V block per program, and the BINDING
# limit is the 16 MB *scoped* VMEM window.  The boundary is EMPIRICAL,
# not a clean K/V-bytes formula — the scope also charges the Q/out
# block pipeline and f32 scratch: measured on v5e, BERT-base at L=2048
# (k_len*H*D = 1.57M elements) overflows the scope by 8 KB while
# L=1024 (0.79M) compiles with room.  1.25M keeps L=1024-class shapes
# on the kernel with margin below the measured failure; beyond it
# callers fall back to the fused-lax ring body, and truly long context
# belongs to the ring tier (sequence sharded over chips) regardless.
# Re-derive by measurement, not arithmetic, if the scope or kernel
# layout changes.
_MAX_KV_BLOCK_ELEMENTS = 5 * 256 * 1024  # 1.25M


def flash_shapes_ok(q_shape, k_shape) -> bool:
    """Whether (B, L, H, D) q/k shapes satisfy the kernel's constraints:
    tile shapes (L multiple of 128 or a sub-128 multiple of 8, D <= 128)
    AND per-program K/V VMEM residency (k_len * H * D within
    _MAX_KV_BLOCK_ELEMENTS).  Callers dispatch on THIS instead of
    catching ValueError from `flash_attention` — a blanket except around
    a traced call swallowed an unrelated shard_map vma error for a full
    round and silently downgraded the bench to the O(L^2) reference path
    (round-5 profile finding)."""
    def bad(length):
        return (length >= 128 and length % 128 != 0) or (
            length < 128 and length % 8 != 0
        )

    heads, dim = q_shape[2], q_shape[3]
    return not (
        bad(q_shape[1])
        or bad(k_shape[1])
        or dim > 128
        or k_shape[1] * heads * dim > _MAX_KV_BLOCK_ELEMENTS
    )


def flash_attention(
    q, k, v, causal: bool = False, scale: Optional[float] = None
):
    """Single-device flash attention; q/k/v: (B, L, H, D) -> (B, L, H, D).

    Differentiable (custom VJP with flash recompute).  Sequence lengths
    must be multiples of the 128 tile (or shorter than it) — pad upstream
    if not; head dim <= 128.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    from elasticdl_tpu.parallel.mesh import in_export_mode

    if in_export_mode():
        # Serving export: Pallas custom calls don't stage through jax2tf;
        # the O(L^2) lax reference computes the same function.  Lazy
        # import — ring_attention imports this module.
        from elasticdl_tpu.ops.ring_attention import (
            full_attention_reference,
        )

        return full_attention_reference(q, k, v, causal=causal, scale=scale)
    # The SAME predicate callers dispatch on (an un-tileable k_len would
    # silently DROP tail keys — the kernel streams whole tiles); a
    # separate inline copy here could drift from flash_shapes_ok and
    # reintroduce the uncaught-ValueError-in-shard_map failure mode.
    if not flash_shapes_ok(q.shape, k.shape) or k.shape != v.shape:
        raise ValueError(
            f"flash_attention needs L a multiple of 128 (or a sub-128 "
            f"multiple of 8) for BOTH q and k/v, k.shape == v.shape, "
            f"D <= 128, and Lk*H*D <= {_MAX_KV_BLOCK_ELEMENTS} (the "
            f"per-program K/V VMEM residency ceiling); got "
            f"Lq={q.shape[1]}, Lk={k.shape[1]}, H={q.shape[2]}, "
            f"D={q.shape[3]}"
        )
    return _flash(q, k, v, causal, scale)
