"""Group-synchronized task assignment for SPMD training.

In cluster mode all worker processes execute ONE collective train step over
a global mesh (worker/spmd.py), so every rank must consume the identical
task sequence.  The reference never faced this problem — its PS workers
trained independently on disjoint shards and the PS merged their gradients
(SURVEY.md §3.3) — but under SPMD the *assignment itself* is the thing to
synchronize: the first rank to ask for (epoch, seq) triggers a real lease
from the TaskManager on behalf of the group; every other rank gets the
cached identical answer.  Failure semantics are unchanged from the
reference's task-lease design (C3): the group holds the lease, an epoch
bump (membership change) recovers all in-flight group leases and starts a
fresh assignment sequence.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.proto import elasticdl_pb2 as pb

logger = get_logger(__name__)

# Group lease owner ids live far above real worker ids; one id per epoch so
# recover_tasks() on an epoch bump can blacklist the stale owner without
# touching the new epoch's leases.
SPMD_GROUP_BASE = 1 << 20


class SpmdAssigner:
    def __init__(self, task_manager, rendezvous_server=None):
        self._tm = task_manager
        self._rendezvous = rendezvous_server
        self._lock = threading.Lock()
        self._epoch = 0
        # seq -> SpmdTaskResponse, valid for the current epoch only
        self._assignments: Dict[int, pb.SpmdTaskResponse] = {}

    def _current_epoch(self) -> int:
        if self._rendezvous is None:
            return 0
        return self._rendezvous.rendezvous_id

    def _group_id(self, epoch: int) -> int:
        return SPMD_GROUP_BASE + epoch

    def get(self, req: pb.GetSpmdTaskRequest) -> pb.SpmdTaskResponse:
        epoch = self._current_epoch()
        with self._lock:
            if epoch != self._epoch:
                # Membership changed since the last assignment: re-queue
                # everything the old group holds and start a new sequence.
                recovered = self._tm.recover_tasks(self._group_id(self._epoch))
                if recovered:
                    logger.info(
                        "SPMD epoch %d -> %d: recovered %d group leases",
                        self._epoch, epoch, recovered,
                    )
                self._assignments.clear()
                self._epoch = epoch
            if req.rendezvous_id != epoch:
                return pb.SpmdTaskResponse(epoch_stale=True)
            cached = self._assignments.get(req.seq)
            if cached is not None:
                return cached
            task = self._tm.get(self._group_id(epoch))
            if task is not None:
                resp = pb.SpmdTaskResponse(task=task)
                self._assignments[req.seq] = resp
                return resp
            if self._tm.finished:
                resp = pb.SpmdTaskResponse(
                    task=pb.Task(task_id=-1, type=pb.WAIT), job_finished=True
                )
                self._assignments[req.seq] = resp
                return resp
            # Nothing leasable right now but the job isn't over (epoch
            # rollover, eval injection pending).  NOT cached: ranks retry
            # the same seq and the first to land after a task appears
            # creates the shared assignment.  Task completion flows through
            # the ordinary report_task_result RPC (rank 0 reports; the
            # TaskManager matches leases by task_id).
            return pb.SpmdTaskResponse(task=pb.Task(task_id=-1, type=pb.WAIT))
