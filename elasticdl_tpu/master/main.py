"""Master process: job brain.

Parity: reference python/master/main.py (SURVEY.md C2, call stack §3.2):
build shards -> task manager -> gRPC servicer -> pod manager (cluster mode)
-> evaluation service -> wait for completion -> final eval/save -> exit.
"""

from __future__ import annotations

import threading
import time
from concurrent import futures
from typing import Optional

from elasticdl_tpu.common import args as args_lib
from elasticdl_tpu.common.constants import GRPC_MAX_MESSAGE_LENGTH
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.data.reader import create_data_reader
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_manager import (
    TaskManager,
    create_shards_from_ranges,
)

logger = get_logger(__name__)


class Master:
    """Owns the control plane of one job."""

    def __init__(self, args, data_reader=None, validation_reader=None):
        self.args = args
        self.job_type = getattr(args, "job_type", "train")
        self._reader = data_reader
        self._val_reader = validation_reader
        if self._reader is None and args.training_data:
            self._reader = create_data_reader(args.training_data)
        if self._val_reader is None and args.validation_data:
            self._val_reader = create_data_reader(args.validation_data)

        training_shards = (
            create_shards_from_ranges(
                self._reader.create_shards(), args.records_per_task
            )
            if self._reader and self.job_type == "train"
            else []
        )
        evaluation_shards = (
            create_shards_from_ranges(
                self._val_reader.create_shards(), args.records_per_task
            )
            if self._val_reader
            else []
        )
        prediction_shards = []
        if getattr(args, "prediction_data", "") and self.job_type == "predict":
            pred_reader = create_data_reader(args.prediction_data)
            prediction_shards = create_shards_from_ranges(
                pred_reader.create_shards(), args.records_per_task
            )
        if not (training_shards or evaluation_shards or prediction_shards):
            raise ValueError(
                f"job type {self.job_type!r} has no input data "
                "(--training_data / --validation_data / --prediction_data)"
            )
        self.task_manager = TaskManager(
            training_shards=training_shards,
            evaluation_shards=evaluation_shards,
            prediction_shards=prediction_shards,
            num_epochs=args.num_epochs,
            lease_timeout_s=args.task_lease_timeout_s,
            shuffle_shards=True,
            shuffle_seed=0,
        )
        # evaluate-only jobs: the eval round IS the job — inject upfront.
        if self.job_type == "evaluate" and evaluation_shards:
            self.task_manager.create_evaluation_tasks(model_version=0)
        self.evaluation_service = EvaluationService(
            self.task_manager,
            evaluation_steps=args.evaluation_steps,
            start_delay_secs=args.evaluation_start_delay_secs,
            throttle_secs=args.evaluation_throttle_secs,
        )
        self.rendezvous_server = None  # attached in elastic mode (M5)
        self.pod_manager = None
        self.servicer = MasterServicer(
            self.task_manager,
            evaluation_service=self.evaluation_service,
            rendezvous_server=self.rendezvous_server,
        )
        self._grpc_server = None
        self._done = threading.Event()
        self.task_manager.add_all_done_callback(self._on_all_done)
        # Final evaluation over the validation set: injected atomically by
        # the task manager the moment the queue first drains (no window in
        # which workers can observe job_finished before the eval round).
        self._final_eval_done = False
        self._evaluation_shards = evaluation_shards
        if evaluation_shards and self.job_type == "train":
            self.task_manager.add_pre_finish_provider(self._final_eval_tasks)

    # ---- lifecycle -----------------------------------------------------

    def start_grpc(self, port: Optional[int] = None) -> int:
        import grpc

        from elasticdl_tpu.proto.service import add_master_servicer_to_server

        options = [
            ("grpc.max_send_message_length", GRPC_MAX_MESSAGE_LENGTH),
            ("grpc.max_receive_message_length", GRPC_MAX_MESSAGE_LENGTH),
        ]
        self._grpc_server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=64), options=options
        )
        add_master_servicer_to_server(self.servicer, self._grpc_server)
        bind = f"[::]:{port if port is not None else self.args.port}"
        actual = self._grpc_server.add_insecure_port(bind)
        self._grpc_server.start()
        logger.info("Master gRPC serving on %s", actual)
        self.task_manager.start_lease_reaper()
        return actual

    def _final_eval_tasks(self):
        """Pre-finish provider (runs under the task-manager lock): the
        final evaluation round, exactly once."""
        if self._final_eval_done:
            return []
        self._final_eval_done = True
        version = self.servicer.max_model_version
        logger.info(
            "Final evaluation: %d tasks at version %d",
            len(self._evaluation_shards), version,
        )
        from elasticdl_tpu.proto import elasticdl_pb2 as pb

        return [
            (shard, pb.EVALUATION, version)
            for shard in self._evaluation_shards
        ]

    def _on_all_done(self):
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        deadline = None if timeout is None else time.time() + timeout
        while True:
            remaining = None if deadline is None else deadline - time.time()
            if remaining is not None and remaining <= 0:
                return False
            if self._done.wait(timeout=0.2 if remaining is None else min(0.2, remaining)):
                if self.task_manager.finished:
                    return True

    def stop(self):
        if self._grpc_server is not None:
            self._grpc_server.stop(grace=1)


def main(argv=None):
    args = args_lib.parse_master_args(argv)
    master = Master(args)
    master.start_grpc()
    master.wait()
    logger.info("Job complete: %s", master.task_manager.snapshot())
    metrics = master.evaluation_service.latest_metrics()
    if metrics:
        logger.info("Final metrics: %s", metrics)
    # Linger so workers polling get_task observe job_finished and exit
    # cleanly instead of hitting a torn-down server mid-RPC.
    time.sleep(5.0)
    master.stop()


if __name__ == "__main__":
    main()
