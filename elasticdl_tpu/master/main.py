"""Master process: job brain.

Parity: reference python/master/main.py (SURVEY.md C2, call stack §3.2):
build shards -> task manager -> gRPC servicer -> pod manager (cluster mode)
-> evaluation service -> wait for completion -> final eval/save -> exit.
"""

from __future__ import annotations

import threading
import time
from concurrent import futures
from typing import Optional

from elasticdl_tpu.common import args as args_lib
from elasticdl_tpu.common.constants import GRPC_MAX_MESSAGE_LENGTH
from elasticdl_tpu.common.k8s_client import parse_volumes
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.data.reader import create_data_reader
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_manager import (
    TaskManager,
    create_shards_from_ranges,
)
from elasticdl_tpu.proto import elasticdl_pb2 as pb

logger = get_logger(__name__)


class Master:
    """Owns the control plane of one job.

    Cluster-elastic mode (SURVEY.md §3.2) engages when a k8s client is
    passed: the master constructs the RendezvousServer (membership epochs)
    and PodManager (create/watch/relaunch worker pods), generates worker
    pod commands by re-serializing its own args (argv is the config wire
    format, as in the reference), and injects a SAVE_MODEL task at job end
    so a worker exports the final model.  With `k8s_client=None` the
    master is control-plane-only (Local mode, unit tests).
    """

    def __init__(
        self, args, data_reader=None, validation_reader=None, k8s_client=None
    ):
        self.args = args
        self.job_type = getattr(args, "job_type", "train")
        self._reader = data_reader
        self._val_reader = validation_reader
        if self._reader is None and args.training_data:
            self._reader = create_data_reader(args.training_data)
        if self._val_reader is None and args.validation_data:
            self._val_reader = create_data_reader(args.validation_data)

        training_shards = (
            create_shards_from_ranges(
                self._reader.create_shards(), args.records_per_task
            )
            if self._reader and self.job_type == "train"
            else []
        )
        evaluation_shards = (
            create_shards_from_ranges(
                self._val_reader.create_shards(), args.records_per_task
            )
            if self._val_reader
            else []
        )
        prediction_shards = []
        if getattr(args, "prediction_data", "") and self.job_type == "predict":
            pred_reader = create_data_reader(args.prediction_data)
            prediction_shards = create_shards_from_ranges(
                pred_reader.create_shards(), args.records_per_task
            )
        if not (training_shards or evaluation_shards or prediction_shards):
            raise ValueError(
                f"job type {self.job_type!r} has no input data "
                "(--training_data / --validation_data / --prediction_data)"
            )
        persist_path = None
        restore_cutoff = None
        if getattr(args, "checkpoint_dir", "") and self.job_type == "train":
            import os

            # Master fault tolerance: completed-shard journal lives next
            # to the model checkpoints; a relaunched master pod resumes
            # the epoch instead of retraining it.  The journal is only
            # trusted up to the newest MODEL checkpoint's STEP — a shard
            # completed at a later model version has gradients the
            # restored params never saw, so it must re-run; with no model
            # checkpoint at all the journal is orphaned and discarded
            # (resuming the task queue without resuming the model would
            # silently drop that data from training).
            persist_path = os.path.join(
                args.checkpoint_dir, "task_state.json"
            )
            restore_cutoff = _latest_model_checkpoint_step(
                args.checkpoint_dir
            )
            if restore_cutoff is None and os.path.exists(persist_path):
                logger.warning(
                    "Discarding orphaned task journal %s (no model "
                    "checkpoint to pair it with)", persist_path,
                )
                try:
                    os.remove(persist_path)
                except OSError:
                    pass
        self.task_manager = TaskManager(
            training_shards=training_shards,
            evaluation_shards=evaluation_shards,
            prediction_shards=prediction_shards,
            num_epochs=args.num_epochs,
            lease_timeout_s=args.task_lease_timeout_s,
            shuffle_shards=True,
            shuffle_seed=0,
            persist_path=persist_path,
            restore_cutoff_step=restore_cutoff,
            straggler_multiple=getattr(args, "straggler_multiple", 3.0),
            straggler_min_tasks=getattr(args, "straggler_min_tasks", 3),
        )
        # evaluate-only jobs: the eval round IS the job — inject upfront.
        if self.job_type == "evaluate" and evaluation_shards:
            self.task_manager.create_evaluation_tasks(model_version=0)
        eval_summary = None
        if getattr(args, "tensorboard_log_dir", ""):
            import os

            from elasticdl_tpu.common.summary import SummaryWriter

            eval_summary = SummaryWriter(
                os.path.join(args.tensorboard_log_dir, "master")
            )
        self.evaluation_service = EvaluationService(
            self.task_manager,
            evaluation_steps=args.evaluation_steps,
            start_delay_secs=args.evaluation_start_delay_secs,
            throttle_secs=args.evaluation_throttle_secs,
            summary_writer=eval_summary,
            eval_metrics=self._load_eval_metrics(args),
        )
        self.rendezvous_server = None
        self.pod_manager = None
        self.recovery_clock = None
        self.policy_engine = None
        self.serving_fleet = None
        self.serving_policy = None
        self.freshness = None
        self.metric_history = None
        self.slo_evaluator = None
        self.flight_recorder = None
        self._k8s = k8s_client
        if k8s_client is not None:
            from elasticdl_tpu.master.pod_manager import PodManager
            from elasticdl_tpu.master.recovery import RecoveryClock
            from elasticdl_tpu.master.rendezvous_server import RendezvousServer

            self.recovery_clock = RecoveryClock()
            self.rendezvous_server = RendezvousServer(
                coordinator_port=getattr(args, "coordinator_port", 51001)
            )
            self.pod_manager = PodManager(
                k8s_client,
                task_manager=self.task_manager,
                rendezvous_server=self.rendezvous_server,
                job_name=args.job_name,
                num_workers=args.num_workers,
                image=getattr(args, "image_name", ""),
                worker_command=self._worker_command,
                relaunch_on_worker_failure=getattr(
                    args, "relaunch_on_worker_failure", 3
                ),
                worker_resources=_parse_resources(
                    getattr(args, "worker_resource_request", "")
                ),
                priority_class=getattr(args, "worker_pod_priority", ""),
                on_job_abort=self._on_job_abort,
                recovery_clock=self.recovery_clock,
                volumes=parse_volumes(getattr(args, "volume", "")),
                workers_per_group=getattr(args, "workers_per_group", 1),
            )
        self.servicer = MasterServicer(
            self.task_manager,
            evaluation_service=self.evaluation_service,
            rendezvous_server=self.rendezvous_server,
            recovery_clock=self.recovery_clock,
        )
        # The actuator that closes the elastic loop (ROADMAP item 4):
        # constructed whenever the pod machinery exists so snapshot()
        # and /metrics expose it, but its background thread only runs
        # with --policy_interval > 0.
        if self.pod_manager is not None:
            from elasticdl_tpu.master.policy import (
                PolicyConfig,
                PolicyEngine,
            )

            self.policy_engine = PolicyEngine(
                self.task_manager,
                self.pod_manager,
                PolicyConfig.from_args(args),
                telemetry_fn=self.servicer.worker_telemetry,
            )
        # Serving fleet supervisor (docs/SERVING.md "Fleet"): same
        # construction gate as the policy engine — needs the pod
        # machinery — plus an explicit replica count.
        if (
            self.pod_manager is not None
            and getattr(args, "serving_replicas", 0) > 0
        ):
            from elasticdl_tpu.master.freshness import FreshnessTracker
            from elasticdl_tpu.master.serving_fleet import (
                ServingFleetConfig,
                ServingFleetManager,
            )

            # Train-to-serve freshness: the manifest's own producer
            # stamp when a checkpoint dir is configured, observation
            # time otherwise.
            ckpt_dir = getattr(args, "checkpoint_dir", "")
            produced_time_fn = None
            if ckpt_dir:
                from elasticdl_tpu.common import save_utils

                def produced_time_fn(step, _dir=ckpt_dir):
                    meta = save_utils.read_produced_meta(_dir, step)
                    return meta.get("produced_unix_s") if meta else None

            self.freshness = FreshnessTracker(
                produced_time_fn=produced_time_fn
            )
            self.serving_fleet = ServingFleetManager(
                k8s_client,
                ServingFleetConfig.from_args(args),
                job_name=args.job_name,
                image=getattr(args, "image_name", ""),
                command_fn=self._serving_command,
                freshness=self.freshness,
            )
        # Metric history + SLO judgment (docs/OBSERVABILITY.md "Metric
        # history & SLOs"): constructed when either loop is enabled so
        # `elasticdl slo` has evidence to render; `0=off` keeps both
        # threads parked exactly like the policy engine.
        history_interval = float(getattr(args, "history_interval", 0.0))
        slo_interval = float(getattr(args, "slo_interval", 0.0))
        incident_dir = getattr(args, "incident_dir", "")
        if history_interval > 0 or slo_interval > 0 or incident_dir:
            from elasticdl_tpu.common.flight import FlightRecorder
            from elasticdl_tpu.common.history import MetricHistory
            from elasticdl_tpu.common.programs import (
                default_program_registry,
            )
            from elasticdl_tpu.common.slo import SloEvaluator, shipped_specs

            self.metric_history = MetricHistory(
                registries=self.telemetry_registries(),
                capacity=int(getattr(args, "history_capacity", 512)),
                interval_s=history_interval,
            )
            # Incident flight recorder (docs/OBSERVABILITY.md "Request
            # tracing & incident bundles"): taps the span-event stream
            # for its forensic rings; without --incident_dir the rings
            # still fill but captures are skipped.
            self.flight_recorder = FlightRecorder(
                incident_dir=incident_dir or None,
                ring_capacity=int(getattr(args, "incident_ring", 256)),
                max_bundles=int(
                    getattr(args, "incident_max_bundles", 8)
                ),
                snapshot_fn=self.snapshot,
                history=self.metric_history,
                # recompile storms pend an immediate capture through
                # the registry's on_storm hook, and every bundle gains
                # a programs.json ledger section
                program_registry=default_program_registry(),
            ).install()
            self.slo_evaluator = SloEvaluator(
                self.metric_history,
                specs=shipped_specs(args),
                interval_s=slo_interval,
                on_breach=self.flight_recorder.breach,
            )
        # Serving autoscaler (docs/SERVING.md "Autoscaling &
        # backpressure"): needs the fleet to actuate and an explicit
        # --max_serving_replicas opt-in.  Burn-rate and shed-ratio
        # signals degrade to 0 gracefully when the history/SLO loops
        # are not configured — the engine then only ever scales down on
        # batch fill, which is the safe direction.
        if (
            self.serving_fleet is not None
            and getattr(args, "max_serving_replicas", 0) > 0
        ):
            from elasticdl_tpu.master.policy import (
                ServingPolicyConfig,
                ServingPolicyEngine,
            )

            self.serving_policy = ServingPolicyEngine(
                self.serving_fleet,
                ServingPolicyConfig.from_args(args),
                history=self.metric_history,
                evaluator=self.slo_evaluator,
            )
        self._grpc_server = None
        self._done = threading.Event()
        self._aborted: Optional[str] = None
        self.bound_port: Optional[int] = None
        self.telemetry = None
        self.task_manager.add_all_done_callback(self._on_all_done)
        # Final evaluation over the validation set: injected atomically by
        # the task manager the moment the queue first drains (no window in
        # which workers can observe job_finished before the eval round).
        self._final_eval_done = False
        self._evaluation_shards = evaluation_shards
        if evaluation_shards and self.job_type == "train":
            self.task_manager.add_pre_finish_provider(self._final_eval_tasks)
        # Cluster mode: final export rides the task queue — ONE SAVE_MODEL
        # task with the output dir in its config rider is injected when the
        # queue drains (after the final eval round; providers run in
        # registration order); the leasing worker exports.
        self._save_model_done = False
        if (
            self.pod_manager is not None
            and self.job_type == "train"
            and getattr(args, "output", "")
        ):
            self.task_manager.add_pre_finish_provider(self._save_model_tasks)

    @staticmethod
    def _load_eval_metrics(args):
        """Lazily load the zoo module's eval_metrics_fn so job-level
        rank metrics (AUC) can be recomputed exactly over merged worker
        samples.  The reference master loaded user model code too
        (ModelHandler, SURVEY C14); failures degrade to weighted
        per-shard means, never abort the job brain."""
        model_zoo = getattr(args, "model_zoo", "")
        model_def = getattr(args, "model_def", "")
        if not model_zoo or not model_def:
            return None
        try:
            from elasticdl_tpu.common.model_handler import load_module

            module, _ = load_module(model_zoo, model_def)
            factory = getattr(
                module,
                getattr(args, "eval_metrics_fn", "") or "eval_metrics_fn",
                None,
            )
            return factory() if factory else None
        except Exception:
            logger.exception(
                "Could not load eval_metrics_fn on the master; job-level "
                "metrics fall back to weighted per-shard means"
            )
            return None

    def _save_model_tasks(self):
        if self._save_model_done:
            return []
        self._save_model_done = True
        import json

        rider = json.dumps({
            "output": self.args.output,
            "saved_model": bool(
                getattr(self.args, "export_saved_model", False)
            ),
        })
        return [(pb.Shard(), pb.SAVE_MODEL, -1, rider)]

    # ---- lifecycle -----------------------------------------------------

    def _worker_command(self, worker_id: int):
        """Worker pod command: this master's args re-serialized as argv
        plus the worker's identity and the master's address (the reference
        passed these through env + argv the same way — SURVEY.md C21)."""
        worker_args = args_lib.build_arguments_from_parsed_result(
            self.args,
            filter_args={"job_type", "worker_id", "master_addr", "func"},
        )
        port = self.bound_port if self.bound_port else self.args.port
        master_host = (
            self._k8s.master_host(self.args.job_name)
            if self._k8s is not None
            else f"{self.args.job_name}-master"
        )
        import sys

        return (
            [sys.executable, "-m", "elasticdl_tpu.worker.main"]
            + worker_args
            + [
                "--master_addr", f"{master_host}:{port}",
                "--worker_id", str(worker_id),
                "--job_type", self.job_type,
            ]
        )

    def _serving_command(self, replica_id: int):
        """Serving replica pod command: `elasticdl serve` over the job's
        live checkpoint dir, so every replica hot-reloads from the same
        stream of steps the trainer writes."""
        import sys

        command = [
            sys.executable, "-m", "elasticdl_tpu.client.main", "serve",
            "--model_zoo", getattr(self.args, "model_zoo", "model_zoo"),
            "--model_def", getattr(self.args, "model_def", ""),
            "--port", str(getattr(self.args, "serving_port", 50061)),
        ]
        if getattr(self.args, "checkpoint_dir", ""):
            command += ["--checkpoint_dir", self.args.checkpoint_dir]
        return command

    def start(self, port: Optional[int] = None) -> int:
        """Serve gRPC, then (cluster mode) create the worker pods."""
        actual = self.start_grpc(port)
        if self.pod_manager is not None:
            self.pod_manager.start()
        if self.policy_engine is not None and self.policy_engine.start():
            logger.info(
                "Policy engine ticking every %.1fs",
                self.policy_engine.config.interval_s,
            )
        if self.serving_fleet is not None:
            self.serving_fleet.start()
            logger.info(
                "Serving fleet: %d replicas placed (probe interval %.1fs)",
                self.serving_fleet.config.replicas,
                self.serving_fleet.config.interval_s,
            )
        if self.metric_history is not None and self.metric_history.start():
            logger.info(
                "Metric history sampling every %.1fs",
                self.metric_history.interval_s,
            )
        if self.slo_evaluator is not None and self.slo_evaluator.start():
            logger.info(
                "SLO evaluator ticking every %.1fs",
                self.slo_evaluator.interval_s,
            )
        if self.serving_policy is not None and self.serving_policy.start():
            logger.info(
                "Serving policy engine ticking every %.1fs "
                "(fleet bounds [%d, %d])",
                self.serving_policy.config.interval_s,
                self.serving_policy.config.min_replicas,
                self.serving_policy.config.max_replicas,
            )
        # A restored task journal may already be terminal (all shards of
        # the final epoch done): no worker report will ever drain the
        # queue, so give the finish check one proactive run.
        self.task_manager.maybe_finish_if_drained()
        return actual

    def start_grpc(self, port: Optional[int] = None) -> int:
        import grpc

        from elasticdl_tpu.proto.service import add_master_servicer_to_server

        options = [
            ("grpc.max_send_message_length", GRPC_MAX_MESSAGE_LENGTH),
            ("grpc.max_receive_message_length", GRPC_MAX_MESSAGE_LENGTH),
        ]
        self._grpc_server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=64), options=options
        )
        add_master_servicer_to_server(self.servicer, self._grpc_server)
        bind = f"[::]:{port if port is not None else self.args.port}"
        actual = self._grpc_server.add_insecure_port(bind)
        self.bound_port = actual
        self._grpc_server.start()
        logger.info("Master gRPC serving on %s", actual)
        self.task_manager.start_lease_reaper()
        return actual

    def _final_eval_tasks(self):
        """Pre-finish provider (runs under the task-manager lock): the
        final evaluation round, exactly once."""
        if self._final_eval_done:
            return []
        self._final_eval_done = True
        version = self.servicer.max_model_version
        logger.info(
            "Final evaluation: %d tasks at version %d",
            len(self._evaluation_shards), version,
        )
        from elasticdl_tpu.proto import elasticdl_pb2 as pb

        return [
            (shard, pb.EVALUATION, version)
            for shard in self._evaluation_shards
        ]

    def _on_all_done(self):
        self._done.set()

    def _on_job_abort(self, reason: str):
        logger.error("Job aborted: %s", reason)
        self._aborted = reason
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        from elasticdl_tpu.common.constants import KEEP_ALIVE_INTERVAL_S

        deadline = None if timeout is None else time.time() + timeout
        stale_after = 3 * KEEP_ALIVE_INTERVAL_S
        next_stale_check = time.time() + stale_after
        while True:
            remaining = None if deadline is None else deadline - time.time()
            if remaining is not None and remaining <= 0:
                return False
            if self._done.wait(timeout=0.2 if remaining is None else min(0.2, remaining)):
                if self._aborted is not None:
                    return False
                if self.task_manager.finished:
                    return True
            if self.pod_manager is not None and time.time() > next_stale_check:
                next_stale_check = time.time() + stale_after
                stale = self.servicer.stale_workers(stale_after)
                # only CURRENT workers are interesting: dead workers keep
                # their last-seen entry forever and would warn every cycle
                alive = set(self.pod_manager.alive_workers())
                stale = {w: s for w, s in stale.items() if w in alive}
                if stale:
                    logger.warning(
                        "Workers silent > %.0fs (lease reaper will recover "
                        "their tasks): %s",
                        stale_after,
                        {w: round(s, 1) for w, s in stale.items()},
                    )

    def snapshot(self) -> dict:
        """One observability surface for chaos runs, job-end logging, and
        /varz (`elasticdl top`): task progress, recovery durations, pod
        churn, per-worker telemetry, and the process-wide fault/retry
        counters — every number read from the unified metrics registry
        through the components that own it."""
        from elasticdl_tpu.common import faults, resilience

        out = {"tasks": self.task_manager.snapshot()}
        online = self.task_manager.online_snapshot()
        if online is not None:
            # perpetual (online) jobs: the `elasticdl top` online line
            out["online"] = online
        if self.recovery_clock is not None:
            out["recovery"] = self.recovery_clock.snapshot()
        if self.pod_manager is not None:
            out["pods"] = self.pod_manager.snapshot()
        if self.policy_engine is not None:
            out["policy"] = self.policy_engine.snapshot()
        if self.serving_fleet is not None:
            out["serving_fleet"] = self.serving_fleet.snapshot()
        if self.serving_policy is not None:
            out["serving_policy"] = self.serving_policy.snapshot()
        if self.freshness is not None:
            out["freshness"] = self.freshness.snapshot()
        if self.slo_evaluator is not None:
            slo = self.slo_evaluator.snapshot()
            if self.metric_history is not None:
                slo["history"] = self.metric_history.snapshot()
                if online is not None:
                    # stream-lag coverage for `elasticdl slo`: how many
                    # samples of the armed-watermark lag gauge the
                    # history holds (docs/ONLINE.md)
                    slo["history"]["stream_lag_samples"] = len(
                        self.metric_history.series(
                            "master_stream_watermark_lag_seconds"
                        )
                    )
            out["slo"] = slo
        out["workers"] = self.servicer.worker_telemetry()
        # Straggler stats come from the task manager's lease clock, not
        # from worker self-reports — merge them onto the same per-worker
        # rows so /varz and `elasticdl top` show one table.
        for wid, stats in self.task_manager.straggler_snapshot().items():
            out["workers"].setdefault(wid, {}).update(stats)
        out["resilience"] = resilience.stats()
        out["faults"] = faults.stats()
        if self.flight_recorder is not None:
            out["flight"] = self.flight_recorder.snapshot()
        return out

    def telemetry_registries(self) -> list:
        """All registries the master exposes on /metrics: the process-wide
        default plus each per-component registry."""
        from elasticdl_tpu.common import metrics as metrics_lib

        registries = [
            metrics_lib.default_registry(),
            self.task_manager.counters.registry,
        ]
        if self.recovery_clock is not None:
            registries.append(self.recovery_clock.metrics_registry)
        if self.pod_manager is not None:
            registries.append(self.pod_manager.metrics_registry)
        if self.policy_engine is not None:
            registries.append(self.policy_engine.metrics_registry)
        if self.serving_fleet is not None:
            registries.append(self.serving_fleet.metrics_registry)
        if self.serving_policy is not None:
            registries.append(self.serving_policy.metrics_registry)
        if self.freshness is not None:
            registries.append(self.freshness.metrics_registry)
        if self.slo_evaluator is not None:
            registries.append(self.slo_evaluator.metrics_registry)
        return registries

    def start_telemetry(self, port: int = 0) -> Optional[int]:
        """Start the /metrics + /healthz + /varz HTTP endpoint; returns
        the bound port, or None when the server could not start (never
        fatal — telemetry must not take down the job brain)."""
        from elasticdl_tpu.common import telemetry as telemetry_lib

        if self.telemetry is not None:
            return self.telemetry.port
        self.telemetry = telemetry_lib.TelemetryServer(
            registries=self.telemetry_registries(),
            role="master",
            port=port,
            healthz_fn=lambda: {
                "job_finished": self.task_manager.finished,
                "aborted": self._aborted,
            },
            varz_fn=lambda: {
                "snapshot": self.snapshot(),
                "grpc_port": self.bound_port,
            },
        )
        try:
            started = self.telemetry.start()
            logger.info("Master telemetry on port %d", started)
            return started
        except Exception:
            logger.exception("telemetry server failed to start")
            self.telemetry = None
            return None

    def stop(self):
        if self.flight_recorder is not None:
            # write any tap-queued captures while components can still
            # contribute a coherent Master.snapshot(), then untap
            self.flight_recorder.flush()
            self.flight_recorder.close()
        if self.serving_policy is not None:
            self.serving_policy.stop()
        if self.slo_evaluator is not None:
            self.slo_evaluator.stop()
        if self.metric_history is not None:
            self.metric_history.stop()
        if self.policy_engine is not None:
            self.policy_engine.stop()
        if self.serving_fleet is not None:
            self.serving_fleet.stop()
        if self.pod_manager is not None:
            self.pod_manager.stop()
        if self._grpc_server is not None:
            self._grpc_server.stop(grace=1)
        if self.telemetry is not None:
            self.telemetry.stop()
            self.telemetry = None


def main(argv=None, k8s_client=None, linger_s: float = 5.0) -> int:
    """Master process entry point.  In cluster strategies this constructs
    the full elastic stack (rendezvous + pod manager over a real — or with
    --use_fake_k8s an in-memory — Kubernetes client); tests may inject
    `k8s_client` directly."""
    args = args_lib.parse_master_args(argv)
    from elasticdl_tpu.common.virtual_mesh import (
        apply_compilation_cache_config,
    )

    apply_compilation_cache_config(args.compilation_cache_dir)
    if k8s_client is None and args.distribution_strategy != "Local":
        if args.use_process_k8s:
            from elasticdl_tpu.common.k8s_client import ProcessK8sClient

            k8s_client = ProcessK8sClient()
        elif args.use_fake_k8s:
            from elasticdl_tpu.common.k8s_client import FakeK8sClient

            k8s_client = FakeK8sClient()
        else:
            from elasticdl_tpu.common.k8s_client import K8sClient

            k8s_client = K8sClient(
                namespace=args.namespace, job_name=args.job_name
            )
    # chaos runs configure the master's fault schedule via the
    # environment, same wire as subprocess workers; no-op otherwise
    from elasticdl_tpu.common import events, faults

    faults.configure_from_env()
    # structured tracing: --event_log wins; otherwise inherit the env
    # wire (ELASTICDL_EVENT_LOG).  export_env=True propagates the path
    # to subprocess workers the same way the fault schedule travels.
    if getattr(args, "event_log", ""):
        events.configure(args.event_log, role="master", export_env=True)
    else:
        events.configure_from_env(role="master")
    master = Master(args, k8s_client=k8s_client)
    master.start()
    master.start_telemetry(getattr(args, "telemetry_port", 0))
    ok = master.wait()
    logger.info("Job complete: %s", master.snapshot())
    if master.recovery_clock is not None and master.recovery_clock.history:
        logger.info(
            "Elastic recoveries this job: %s",
            [round(s, 2) for s in master.recovery_clock.history],
        )
    metrics = master.evaluation_service.latest_metrics()
    if metrics:
        logger.info("Final metrics: %s", metrics)
    # Linger so workers polling get_task observe job_finished and exit
    # cleanly instead of hitting a torn-down server mid-RPC.
    time.sleep(linger_s)
    master.stop()
    return 0 if ok else 1


def _latest_model_checkpoint_step(checkpoint_dir: str):
    """STEP of the newest finalized Orbax checkpoint (its digit-named dir),
    or None when no finalized model checkpoint exists.  Step-based — never
    a clock comparison: async checkpoint writes and cross-host clock skew
    make mtimes unusable for durability decisions."""
    import os

    if not os.path.isdir(checkpoint_dir):
        return None
    steps = [
        int(name)
        for name in os.listdir(checkpoint_dir)
        if name.isdigit()
        and os.path.isdir(os.path.join(checkpoint_dir, name))
    ]
    return max(steps) if steps else None


def _parse_resources(spec: str):
    """'cpu=1,memory=4096Mi' -> {'cpu': '1', 'memory': '4096Mi'}"""
    out = {}
    for part in (spec or "").split(","):
        if "=" in part:
            key, value = part.split("=", 1)
            out[key.strip()] = value.strip()
    return out


if __name__ == "__main__":
    main()
