"""Elastic rendezvous: membership epochs for mesh rebuilds.

Parity: reference python/master/rendezvous_server.py
`HorovodRendezvousServer` (SURVEY.md C6).  The reference bumped a
rendezvous id so workers rebuilt the Horovod NCCL ring; here the epoch
drives the TPU-native cycle instead (SURVEY.md §7): on a bump every worker
re-initialises jax.distributed with the new (world_size, rank,
coordinator), rebuilds its mesh, recompiles the train step and restores
state from checkpoint.  Rank 0's address doubles as the JAX coordination
service address.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.proto import elasticdl_pb2 as pb

logger = get_logger(__name__)


class RendezvousServer:
    def __init__(self, coordinator_port: int = 51001):
        self._lock = threading.Lock()
        self._workers: Dict[int, str] = {}  # worker_id -> address
        self._rendezvous_id = 0
        self._coordinator_port = coordinator_port
        # The pod manager's membership target for the current epoch (how
        # many workers it intends to be alive).  0 = unknown/not managed.
        self._expected = 0
        # worker_id -> last epoch the worker's MAIN thread confirmed
        # readiness for.  The confirmation barrier: a mesh only forms once
        # every member confirmed the current epoch, so wedged ranks (which
        # cannot confirm) never get peers dialing their dead coordinator.
        self._confirmed: Dict[int, int] = {}

    # ---- membership (driven by the pod manager) ------------------------

    def add_worker(self, worker_id: int, address: str = "") -> int:
        with self._lock:
            if worker_id in self._workers and (
                self._workers[worker_id] == address or not address
            ):
                # Idempotent re-add; an empty re-report never clobbers a
                # known-good address.
                return self._rendezvous_id
            self._workers[worker_id] = address
            self._rendezvous_id += 1
            logger.info(
                "Rendezvous %d: +worker %d (%d total)",
                self._rendezvous_id, worker_id, len(self._workers),
            )
            return self._rendezvous_id

    def update_address(self, worker_id: int, address: str) -> int:
        """Worker self-report (keep_alive): correct the stored address when
        the k8s watch delivered RUNNING before the pod IP was assigned.
        Only existing members update — a stale keep_alive from a removed
        worker must not resurrect it.  An address change bumps the epoch:
        rank assignment is stable but the coordinator address may move."""
        with self._lock:
            if not address or worker_id not in self._workers:
                return self._rendezvous_id
            if self._workers[worker_id] == address:
                return self._rendezvous_id
            self._workers[worker_id] = address
            self._rendezvous_id += 1
            logger.info(
                "Rendezvous %d: worker %d address -> %s",
                self._rendezvous_id, worker_id, address,
            )
            return self._rendezvous_id

    def set_expected(self, n: int) -> None:
        """Pod manager publishes its membership target for this epoch."""
        with self._lock:
            self._expected = n

    def remove_worker(self, worker_id: int) -> int:
        with self._lock:
            if worker_id not in self._workers:
                return self._rendezvous_id
            del self._workers[worker_id]
            self._confirmed.pop(worker_id, None)
            self._rendezvous_id += 1
            logger.info(
                "Rendezvous %d: -worker %d (%d left)",
                self._rendezvous_id, worker_id, len(self._workers),
            )
            return self._rendezvous_id

    # ---- worker-facing -------------------------------------------------

    def cluster_spec(
        self, req: Optional[pb.GetClusterSpecRequest] = None
    ) -> pb.ClusterSpec:
        with self._lock:
            if (
                req is not None
                and req.confirm_epoch
                and req.worker_id in self._workers
            ):
                self._confirmed[req.worker_id] = req.confirm_epoch
            all_confirmed = bool(self._workers) and all(
                self._confirmed.get(wid) == self._rendezvous_id
                for wid in self._workers
            )
            spec = pb.ClusterSpec(
                rendezvous_id=self._rendezvous_id,
                world_size=len(self._workers),
                expected_world_size=self._expected,
                all_confirmed=all_confirmed,
            )
            ordered = sorted(self._workers)
            for rank, worker_id in enumerate(ordered):
                spec.workers.append(
                    pb.WorkerSpec(
                        worker_id=worker_id,
                        address=self._workers[worker_id],
                        rank=rank,
                    )
                )
            if ordered:
                host = (self._workers[ordered[0]] or "localhost").split(":")[0]
                spec.coordinator_address = (
                    f"{host}:{self._coordinator_port}"
                )
            return spec

    @property
    def rendezvous_id(self) -> int:
        with self._lock:
            return self._rendezvous_id
