"""Elastic rendezvous: membership epochs for mesh rebuilds.

Parity: reference python/master/rendezvous_server.py
`HorovodRendezvousServer` (SURVEY.md C6).  The reference bumped a
rendezvous id so workers rebuilt the Horovod NCCL ring; here the epoch
drives the TPU-native cycle instead (SURVEY.md §7): on a bump every worker
re-initialises jax.distributed with the new (world_size, rank,
coordinator), rebuilds its mesh, recompiles the train step and restores
state from checkpoint.  Rank 0's address doubles as the JAX coordination
service address.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.proto import elasticdl_pb2 as pb

logger = get_logger(__name__)


class RendezvousServer:
    def __init__(self, coordinator_port: int = 51001):
        self._lock = threading.Lock()
        self._workers: Dict[int, str] = {}  # worker_id -> address
        self._rendezvous_id = 0
        self._coordinator_port = coordinator_port

    # ---- membership (driven by the pod manager) ------------------------

    def add_worker(self, worker_id: int, address: str = "") -> int:
        with self._lock:
            if self._workers.get(worker_id) == address:
                return self._rendezvous_id
            self._workers[worker_id] = address
            self._rendezvous_id += 1
            logger.info(
                "Rendezvous %d: +worker %d (%d total)",
                self._rendezvous_id, worker_id, len(self._workers),
            )
            return self._rendezvous_id

    def remove_worker(self, worker_id: int) -> int:
        with self._lock:
            if worker_id not in self._workers:
                return self._rendezvous_id
            del self._workers[worker_id]
            self._rendezvous_id += 1
            logger.info(
                "Rendezvous %d: -worker %d (%d left)",
                self._rendezvous_id, worker_id, len(self._workers),
            )
            return self._rendezvous_id

    # ---- worker-facing -------------------------------------------------

    def cluster_spec(
        self, req: Optional[pb.GetClusterSpecRequest] = None
    ) -> pb.ClusterSpec:
        with self._lock:
            spec = pb.ClusterSpec(
                rendezvous_id=self._rendezvous_id,
                world_size=len(self._workers),
            )
            ordered = sorted(self._workers)
            for rank, worker_id in enumerate(ordered):
                spec.workers.append(
                    pb.WorkerSpec(
                        worker_id=worker_id,
                        address=self._workers[worker_id],
                        rank=rank,
                    )
                )
            if ordered:
                host = (self._workers[ordered[0]] or "localhost").split(":")[0]
                spec.coordinator_address = (
                    f"{host}:{self._coordinator_port}"
                )
            return spec

    @property
    def rendezvous_id(self) -> int:
        with self._lock:
            return self._rendezvous_id
